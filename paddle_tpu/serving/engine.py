"""Multi-model serving engine: continuous batcher + warm executable cache
+ admission control over `AnalysisPredictor`.

Architecture (docs/SERVING.md):

  submit(model, feed) ──► edge validation ──► bounded per-model queue
                                                   │ (scheduler thread)
                                                   ▼
                      shape-keyed batch assembly (pad to bucket)
                                                   ▼
                      AnalysisPredictor.run_feed_dict — ONE compiled XLA
                      executable per (model signature, bucket shape),
                      warm after warmup(); the executor's in-process
                      cache + FLAGS_compile_cache_dir persistence mean a
                      restarted server recompiles nothing
                                                   ▼
                      split rows back out ──► per-request futures

Callers never see the batching: `submit` returns a future holding only
that caller's rows; `infer` is the blocking convenience.  Admission is
bounded (FLAGS_serving_max_queue) with typed `ServingOverloadError`
rejection, and every stage reports into the observability registry
(`pt_serve_*` families — docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import collections
import concurrent.futures
import hashlib
import threading
import time

import numpy as np

from paddle_tpu.observability import reqtrace as _reqtrace

from .batching import BucketPolicy, Request, assemble_batch, split_outputs, \
    pad_seq
from .errors import (FeedValidationError, ModelNotLoadedError,
                     ServingDeadlineError,
                     ServingOverloadError)

__all__ = ["Engine", "model_signature"]


# ---------------------------------------------------------------------------
# metrics (lazy idempotent registration — the observability contract)
# ---------------------------------------------------------------------------

# request-count buckets for the batch-size histogram: powers of two up to
# the largest sensible serving bucket (latency DEFAULT_BUCKETS would bin
# every batch into the 1-2 bucket)
_BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

# distinct tenant labels per model lane; beyond this, new tenants book
# under "__other__" (tenant is caller-supplied — uncapped it would mint
# one permanent registry series per distinct id)
_MAX_TENANT_LABELS = 64


def _m_latency():
    from paddle_tpu import observability as obs

    return obs.histogram(
        "pt_serve_request_latency_seconds",
        "Request latency from submit to completion (includes queueing, "
        "batching wait, and execution)", labels=("model",))


def _m_queue_wait():
    from paddle_tpu import observability as obs

    return obs.histogram(
        "pt_serve_queue_wait_seconds",
        "Queue-entry to batch-formation wait per request — the share of "
        "pt_serve_request_latency_seconds spent queued/batching; an SLO "
        "p99 breach with this phase dominant names admission/batching, "
        "not the device", labels=("model",))


def _m_execute():
    from paddle_tpu import observability as obs

    return obs.histogram(
        "pt_serve_execute_seconds",
        "Batch-formation to futures-resolve time per request — the "
        "execution share of pt_serve_request_latency_seconds (device "
        "dispatch + output split)", labels=("model",))


def _m_batch_size():
    from paddle_tpu import observability as obs

    return obs.histogram(
        "pt_serve_batch_size",
        "Real (pre-padding) rows per executed serving batch — mass above "
        "1 means continuous batching is forming multi-request batches",
        labels=("model",), buckets=_BATCH_SIZE_BUCKETS)


def _m_queue_depth():
    from paddle_tpu import observability as obs

    return obs.gauge(
        "pt_serve_queue_depth",
        "Requests currently queued per model (admission control rejects "
        "beyond FLAGS_serving_max_queue)", labels=("model",))


def _m_rejected():
    # the ONE owner of this family's registration — the decode lane
    # books through this helper too, so the help text can never drift
    # between the two serving lanes
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_serve_rejected_total",
        "Requests rejected at the admission edge, by reason "
        "(overload / closed / invalid / deadline / tenant_quota / "
        "draining / scheduler_failed)", labels=("model", "reason"))


def _m_requests():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_serve_requests_total",
        "Requests admitted, by model and tenant (per-tenant accounting; "
        "capped at 64 distinct tenants per lane, then '__other__')",
        labels=("model", "tenant"))


def _m_rows():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_serve_rows_total",
        "Rows served (real) vs padding rows added by bucketing — the "
        "padding overhead of the bucket policy",
        labels=("model", "kind"))


def _m_exec_cache():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_serve_executable_cache_total",
        "Serving executable-cache outcomes per dispatched batch: warmup "
        "(explicit precompile), warm (bucket shape already compiled), "
        "cold (first traffic on a bucket shape — a compile in the "
        "request path)", labels=("model", "result"))


# ---------------------------------------------------------------------------
# model signature
# ---------------------------------------------------------------------------


def _np_dtype(dtype_str):
    """Shared framework dtype resolver ('bfloat16', proto enum ints) —
    None when unresolvable.  Same resolution as the edge validation in
    inference.check_feed_against_var, so the serving cast and the edge
    check can never disagree."""
    from paddle_tpu.inference import _resolve_np_dtype

    return _resolve_np_dtype(dtype_str)


def model_signature(program, feed_names, fetch_names):
    """Stable signature of a loaded model: op types + feed/fetch names +
    static var specs, hashed.  With the executor's persistent XLA cache
    (FLAGS_compile_cache_dir) this is the stable half of the
    (model signature, bucket shape) executable key — the same saved model
    reloaded in a restarted server hashes identically, so its warmup
    compiles resolve from the on-disk cache."""
    def stable(v):
        # only hash attr payloads whose repr is process-independent — a
        # sub-block/Variable repr can embed a memory address, which
        # would break the restarted-server-hashes-identically contract
        if isinstance(v, (bool, int, float, str, bytes, type(None))):
            return True
        if isinstance(v, (list, tuple)):
            return all(stable(x) for x in v)
        return False

    h = hashlib.sha1()
    blk = program.global_block()
    for b in program.blocks:  # sub-blocks (while/cond bodies) count too
        for op in b.ops:
            h.update(op.type.encode())
            h.update(b"\x00")  # delimit: ['mat','mul'] != ['matmul']
            for k in sorted(op.attrs):
                v = op.attrs[k]
                if stable(v):
                    h.update(f"{k}={v!r}".encode())
                    h.update(b"\x00")
    # delimit the two lists: feeds=[a,b]/fetches=[c] must not hash the
    # same as feeds=[a]/fetches=[b,c] — different serving interfaces
    for n in sorted(feed_names) + ["\x00fetch\x00"] + sorted(fetch_names):
        v = blk._find_var_recursive(n)
        spec = (n, tuple(v.shape or ()) if v is not None else (),
                v.dtype if v is not None else None)
        h.update(repr(spec).encode())
        h.update(b"\x00")
    return h.hexdigest()[:16]


# ---------------------------------------------------------------------------
# per-model serving lane
# ---------------------------------------------------------------------------


class _ModelLane:
    """One served model: predictor + bounded queue + scheduler thread."""

    def __init__(self, name, predictor, policy, max_wait_s, max_queue,
                 deadline_s=0.0, ragged=False):
        self.name = name
        self.predictor = predictor
        self.policy = policy
        self.max_wait_s = max_wait_s
        self.max_queue = max_queue
        self.deadline_s = float(deadline_s or 0.0)
        # ragged mode (docs/KERNELS.md "Ragged attention"): every
        # dynamic dim-1 feed pads to ONE length (the largest sequence
        # bucket) instead of its nearest bucket, so mixed-length traffic
        # shares a single shape key — it batches TOGETHER (padding rows
        # stop existing for full batches) and warmup compiles one
        # executable per batch bucket instead of the seq-bucket cross
        # product.  The model masks the padded tail itself via a
        # per-row lengths feed (layers.ragged_attention).
        self._ragged = bool(ragged)
        if self._ragged and not policy.seq_buckets:
            raise ValueError(
                f"model {name!r}: ragged=True needs sequence buckets to "
                f"name the single padded length (the largest bucket) — "
                f"set FLAGS_serving_seq_buckets or "
                f"Engine(seq_buckets=...)")
        self._ragged_len = policy.seq_buckets[-1] if self._ragged else None
        self.signature = model_signature(predictor._program,
                                         predictor.get_input_names(),
                                         predictor.get_output_names())
        self._var_cache = {}
        # the whole design batches requests along dim 0: a feed var with
        # a FIXED leading dim can neither pad nor concatenate, so reject
        # the model at load with the fix spelled out rather than letting
        # the batcher feed shape-violating batches into XLA
        self._dyn_seq_inputs = []
        for n in predictor.get_input_names():
            v = self._var(n)
            if v is None or v.shape is None:
                continue
            if not len(v.shape):
                raise ValueError(
                    f"model {name!r}: input {n!r} is scalar-shaped "
                    f"(static shape []), so it has no leading batch dim "
                    f"to concatenate requests along — re-export with "
                    f"shape [-1, ...] (layers.data "
                    f"append_batch_size=True)")
            if v.shape[0] not in (-1, None):
                raise ValueError(
                    f"model {name!r}: input {n!r} has a FIXED leading "
                    f"dim {v.shape[0]} (static shape {list(v.shape)}); "
                    f"the serving batcher needs a dynamic batch dim — "
                    f"re-export with shape [-1, ...] "
                    f"(layers.data append_batch_size=True)")
            if len(v.shape) >= 2 and v.shape[1] == -1:
                self._dyn_seq_inputs.append(n)
        dyn_seq_inputs = bool(self._dyn_seq_inputs)
        # without sequence buckets a dynamic dim-1 feed is unwarmable:
        # warmup() cannot synthesize its shapes, so EVERY distinct
        # traffic length pays a cold compile in the request path — warn
        # at load, where the flag fix is still cheap
        if dyn_seq_inputs and not policy.seq_buckets:
            import warnings

            warnings.warn(
                f"model {name!r} has a dynamic dim-1 feed but no "
                f"sequence buckets are configured: warmup() cannot "
                f"precompile its shapes, and each distinct sequence "
                f"length will compile COLD in the request path — set "
                f"FLAGS_serving_seq_buckets (or Engine(seq_buckets=...))")
        # same contract on the OUTPUT side: split_outputs row-slices
        # every fetch along axis 0, so a batch-reduced or fixed-leading-
        # dim fetch would silently hand request 0 the whole-batch
        # aggregate (computed over padding zeros) and later requests
        # empty arrays — reject at load with the fix named.  Outputs
        # whose dim-1 is dynamic follow the (padded) sequence length;
        # _execute slices those back to each request's pre-pad length so
        # padding positions never reach the caller.
        self._dyn_seq_outputs = set()
        for n in predictor.get_output_names():
            v = self._var(n)
            if v is None or v.shape is None:
                continue
            if len(v.shape) == 0 or v.shape[0] not in (-1, None):
                raise ValueError(
                    f"model {name!r}: output {n!r} has static shape "
                    f"{list(v.shape)} without a dynamic leading (batch) "
                    f"dim; batched serving slices outputs by request "
                    f"rows, so every fetch needs per-row results — "
                    f"fetch the pre-reduction tensor and aggregate "
                    f"client-side (or re-export with a [-1, ...] fetch)")
            if len(v.shape) >= 2 and v.shape[1] == -1:
                self._dyn_seq_outputs.add(n)
        # slice-back on a dyn-declared output is only provably safe once
        # warmup() has OBSERVED its width tracking the fed sequence
        # length (the declaration alone can lie: a constant-width output
        # declared [-1, -1] would be truncated whenever its width
        # collides with a padded bucket).  Until that observation the
        # request path rejects padded traffic typed instead of guessing
        # — bucket-aligned lengths are unaffected.
        self._seq_outputs_confirmed = not (self._dyn_seq_inputs
                                           and self._dyn_seq_outputs)
        self._probe_seqs = None  # set per warmup() by _warmup_shapes
        self._queue = collections.deque()
        # queued rows per shape key, maintained at append/pop/drain so
        # the scheduler's batch-fill wait checks fullness in O(1): the
        # check re-runs on EVERY submit() wakeup, and a full-queue scan
        # there is O(queue × wakeups) inside the lock submit needs
        self._queued_rows = collections.Counter()
        self._cv = threading.Condition()
        # serializes _execute between the scheduler thread and a
        # caller-thread warmup() on a live engine: without it the two
        # could jit-trace the same (bucket, shape) executable twice and
        # race the _warm bookkeeping
        self._exec_lock = threading.Lock()
        self._thread = None
        self._closed = False
        # graceful drain (elastic.DrainHandler): admission stopped, the
        # scheduler finishes the batch in flight, queued futures fail
        # typed with reason="draining"
        self._draining = False
        # engine-level warm-executable bookkeeping, keyed on the padded
        # batch shape key (the executor's own cache holds the jitted
        # executables; this set is what /servez reports as "warm")
        self._warm = set()
        # exec keys of synthetic probe shapes (see _warmup_shapes):
        # warm in _warm so traffic bookkeeping stays exact, but hidden
        # from the warm_executables ops count, which must agree with
        # warmup()'s one-per-bucket-shape return
        self._probe_keys = set()
        self._served_requests = 0
        self._served_batches = 0
        self._tenant_requests = collections.Counter()
        # lane-LOCAL executable-cache outcomes for stats(): the
        # pt_serve_* registry counters are process-cumulative, so a
        # re-created engine serving the same model name would inherit a
        # predecessor's cold counts in its /servez hit rate
        self._cache_counts = collections.Counter()
        self._bind_metrics()

    def _bind_metrics(self):
        """Resolve each family's label child ONCE per lane: the hot path
        must not take the process-wide registry lock for a family lookup
        on every request (only the tenant-labeled counter needs a
        per-call .labels() — its label value is caller-supplied).
        Caching breaks the registry's reset() contract ("call sites
        re-register lazily"), so the entry points compare the registry
        epoch (_check_metrics_epoch) and rebind after a reset instead of
        counting into orphaned families forever."""
        from paddle_tpu import observability as obs

        self._metrics_epoch = obs.REGISTRY.epoch
        name = self.name
        self._lat = _m_latency().labels(model=name)
        self._queue_wait = _m_queue_wait().labels(model=name)
        self._execute_hist = _m_execute().labels(model=name)
        self._batch_size = _m_batch_size().labels(model=name)
        self._queue_depth = _m_queue_depth().labels(model=name)
        self._rejected = {r: _m_rejected().labels(model=name, reason=r)
                          for r in ("overload", "closed", "invalid",
                                    "deadline", "draining")}
        self._rows = {k: _m_rows().labels(model=name, kind=k)
                      for k in ("real", "padding")}
        self._exec_cache = {r: _m_exec_cache().labels(model=name, result=r)
                            for r in ("warmup", "warm", "cold")}
        # tenant label values are caller-supplied, so only .labels() can
        # be per-request — but the family lookup itself is cacheable
        self._requests_family = _m_requests()
        # same isolation for latency: the registry histogram is
        # process-cumulative per model name, so snapshot it now and
        # report the DELTA — a fresh lane must not inherit a closed
        # predecessor's p50/p99
        self._lat_baseline = self._lat.hist_data()
        self._queue_wait_baseline = self._queue_wait.hist_data()
        self._execute_baseline = self._execute_hist.hist_data()

    def _check_metrics_epoch(self):
        """One int compare on the hot path; rebinds the cached label
        children iff observability.reset() dropped the families since
        they were resolved.  A concurrent double-rebind is benign (same
        families, same children)."""
        from paddle_tpu import observability as obs

        if self._metrics_epoch != obs.REGISTRY.epoch:
            self._bind_metrics()

    def _serve_span(self, fut, rows, tenant):
        """Engine-side serve span for one admitted request.  A router /
        frontend caller carries its span in via reqtrace.attach() on the
        submit edge (no signature change, so duck-typed fakes keep
        working); a direct caller with no ambient span becomes its own
        trace root.  Finishes when the request's future resolves."""
        parent = _reqtrace.current_span()
        if parent is not None:
            span = _reqtrace.start_span(
                f"serve:{self.name}", kind="serve", parent=parent,
                attrs={"model": self.name, "tenant": tenant,
                       "rows": rows})
        else:
            span = _reqtrace.start_request(
                f"serve:{self.name}", kind="serve",
                attrs={"model": self.name, "tenant": tenant,
                       "rows": rows})
        if span is not None:
            fut.add_done_callback(
                lambda f, s=span: _reqtrace.finish_future(s, f))
        return span

    # -- feed validation edge ---------------------------------------------

    def _var(self, name):
        if name not in self._var_cache:
            self._var_cache[name] = (self.predictor._program.global_block()
                                     ._find_var_recursive(name))
        return self._var_cache[name]

    def _validate_and_pad(self, feed):
        """Edge validation + per-request sequence padding.  Returns
        (padded_feed, rows, shape_key).  Everything a bad caller could
        get wrong fails HERE with a typed error naming the problem —
        never inside the shared XLA trace."""
        from paddle_tpu.inference import check_feed_against_var

        names = self.predictor.get_input_names()
        missing = [n for n in names if n not in feed]
        extra = [n for n in feed if n not in names]
        if missing or extra:
            raise FeedValidationError(
                f"model {self.name!r} expects inputs {names}; "
                f"missing {missing}, unexpected {extra}")
        rows = None
        padded = {}
        key = []
        seq_pads = []
        other_widths = set()  # dim-1 widths of feeds NOT seq-padded
        for n in names:
            arr = np.asarray(feed[n])
            var = self._var(n)
            if arr.ndim == 0:
                raise FeedValidationError(
                    f"input {n!r} must have a leading batch dim; got a "
                    f"scalar")
            check_feed_against_var(n, arr, var,
                                   error_cls=FeedValidationError)
            if rows is None:
                rows = int(arr.shape[0])
            elif int(arr.shape[0]) != rows:
                raise FeedValidationError(
                    f"inconsistent request rows: {n!r} has "
                    f"{arr.shape[0]}, expected {rows}")
            if var is not None and var.dtype is not None \
                    and var.dtype != "":  # bool's proto enum is 0: no
                # truthiness here.
                # cast to the var dtype NOW (the executor would coerce
                # anyway): the shape key must reflect the post-coercion
                # dtype, or a float64 caller would segregate into its
                # own batch lane and falsely book cold executables.
                # Cast BEFORE any sequence pad so the pad's allocation
                # and concat run at the var's width, not the caller's
                # possibly wider dtype (a float64 feed would otherwise
                # pay a full padded-array copy twice)
                want = _np_dtype(var.dtype)
                if want is not None and arr.dtype != want:
                    arr = arr.astype(want)
            if (self.policy.seq_buckets and arr.ndim >= 2
                    and var is not None and var.shape is not None
                    and len(var.shape) >= 2 and var.shape[1] == -1):
                orig = int(arr.shape[1])
                if self._ragged:
                    # one shape for ALL lengths: mixed-length traffic
                    # must share a batch, so over-length can't fall
                    # through to an unpadded cold shape like the
                    # bucketed path allows — reject typed instead
                    if orig > self._ragged_len:
                        raise FeedValidationError(
                            f"input {n!r} has length {orig}, above the "
                            f"ragged lane's single padded length "
                            f"{self._ragged_len} (the largest sequence "
                            f"bucket) — raise the bucket set or split "
                            f"the request")
                    tgt = self._ragged_len
                else:
                    tgt = self.policy.seq_bucket(orig)
                arr = pad_seq(arr, tgt)
                seq_pads.append((orig, tgt))
            elif arr.ndim >= 2:
                other_widths.add(int(arr.shape[1]))
            padded[n] = arr
            key.append((n, tuple(arr.shape[1:]), str(arr.dtype)))
        if rows is None:
            raise FeedValidationError("empty feed")
        if rows == 0:
            # fail at the edge like every other malformed feed: letting
            # a zero-row request through would burn the full batch
            # timeout plus a device dispatch on pure padding, then
            # resolve with empty arrays
            raise FeedValidationError(
                f"request has 0 rows (feed arrays have a zero-length "
                f"batch dim) — nothing to serve")
        if rows > self.policy.max_rows:
            raise FeedValidationError(
                f"request of {rows} rows exceeds the largest batch "
                f"bucket {self.policy.max_rows} — split the request "
                f"(buckets: {list(self.policy.batch_buckets)})")
        # slice-back mapping {padded_len: orig_len}: an output whose
        # dim-1 equals a padded length slices back to that feed's
        # original length.  The mapping is ambiguous only when two
        # different original lengths land on the SAME padded length
        # (which original would the output follow?) — reject that at
        # the edge (when the model has dynamic-length outputs) rather
        # than silently handing the caller positions computed from
        # padding zeros.  Differing lengths on different buckets are
        # fine (the seq2seq src/tgt case).
        seq_pad = None
        if seq_pads:
            by_padded = {}
            for orig, tgt in seq_pads:
                by_padded.setdefault(tgt, set()).add(orig)
            if self._dyn_seq_outputs and any(
                    len(origs) > 1 for origs in by_padded.values()):
                raise FeedValidationError(
                    f"request's dynamic dim-1 feeds have differing "
                    f"lengths {sorted(set(p[0] for p in seq_pads))} "
                    f"padding onto one bucket, and model {self.name!r} "
                    f"has dynamic-length outputs "
                    f"({sorted(self._dyn_seq_outputs)}): padding could "
                    f"not be sliced back unambiguously — pad the feeds "
                    f"to one common length at the caller")
            # an output is matched to its feed by padded length, so a
            # NON-padded feed sharing that width makes the match
            # uncertain — skip slicing there rather than risk
            # truncating valid positions that followed the other feed
            # (the caller sees zero padding, never silent data loss)
            seq_pad = {tgt: next(iter(origs))
                       for tgt, origs in by_padded.items()
                       if next(iter(origs)) != tgt
                       and tgt not in other_widths} or None
            if seq_pad and self._dyn_seq_outputs \
                    and not self._seq_outputs_confirmed:
                raise FeedValidationError(
                    f"model {self.name!r} declares dynamic-length "
                    f"outputs ({sorted(self._dyn_seq_outputs)}) but "
                    f"warmup() has not yet verified which of them "
                    f"actually track the fed sequence length, so this "
                    f"padded request could not be sliced back safely — "
                    f"call Engine.warmup() before serving, or pad feeds "
                    f"to a bucket length "
                    f"({list(self.policy.seq_buckets)}) at the caller")
        return padded, rows, tuple(key), seq_pad

    # -- submission --------------------------------------------------------

    def submit(self, feed, tenant):
        self._check_metrics_epoch()
        try:
            padded, rows, key, seq_pad = self._validate_and_pad(feed)
        except FeedValidationError:
            self._rejected["invalid"].inc()
            raise
        fut = concurrent.futures.Future()
        tenant = str(tenant)
        with self._cv:
            if self._closed:
                self._rejected["closed"].inc()
                raise ServingOverloadError(
                    f"model {self.name!r}: engine is closed",
                    reason="closed")
            if self._draining:
                self._rejected["draining"].inc()
                raise ServingOverloadError(
                    f"model {self.name!r}: engine is draining (graceful "
                    f"preemption) — resubmit to another replica",
                    reason="draining")
            if len(self._queue) >= self.max_queue:
                self._rejected["overload"].inc()
                raise ServingOverloadError(
                    f"model {self.name!r}: queue at admission limit "
                    f"({self.max_queue} requests, "
                    f"FLAGS_serving_max_queue) — retry with backoff",
                    reason="overload")
            # tenant is a caller-supplied string feeding a metric label:
            # cap its cardinality or a per-user/per-request id scheme
            # grows the registry (and /servez) without bound
            if tenant not in self._tenant_requests and \
                    len(self._tenant_requests) >= _MAX_TENANT_LABELS:
                tenant = "__other__"
            req = Request(padded, rows, tenant, fut, key, seq_pad,
                          deadline_s=self.deadline_s)
            req.span = self._serve_span(fut, rows, tenant)
            self._queue.append(req)
            self._queued_rows[key] += rows
            self._queue_depth.set(len(self._queue))
            self._tenant_requests[tenant] += 1
            self._cv.notify_all()
        self._requests_family.labels(model=self.name, tenant=tenant).inc()
        return fut

    # -- scheduler ---------------------------------------------------------

    def start(self):
        # under the lane lock: two Engine.start() calls racing the
        # None-check would each spawn a scheduler thread, and close()
        # would join only the survivor of the overwrite
        with self._cv:
            if self._thread is not None or self._closed:
                return
            self._thread = threading.Thread(
                target=self._scheduler, daemon=True,
                name=f"pt-serve-{self.name}")
            self._thread.start()

    def _matching_rows(self, key):
        return self._queued_rows[key]  # missing key reads 0, no insert

    def _expire_queued(self):
        """Under _cv: resolve every queued request past its per-request
        deadline with a typed ServingDeadlineError (booked as
        reason="deadline") and drop it from the queue — a stale request
        must neither wait forever behind other shape keys nor burn a
        device dispatch its caller already gave up on."""
        if self.deadline_s <= 0:
            return
        now = time.monotonic()
        if not any(r.deadline is not None and now > r.deadline
                   for r in self._queue):
            return
        kept = collections.deque()
        for r in self._queue:
            if r.deadline is None or now <= r.deadline:
                kept.append(r)
                continue
            left = self._queued_rows[r.shape_key] - r.rows
            if left > 0:
                self._queued_rows[r.shape_key] = left
            else:
                self._queued_rows.pop(r.shape_key, None)
            self._rejected["deadline"].inc()
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(ServingDeadlineError(
                    f"model {self.name!r}: request exceeded its "
                    f"{self.deadline_s * 1000:.0f} ms deadline while "
                    f"queued (FLAGS_serving_deadline_ms)"))
        self._queue = kept
        self._queue_depth.set(len(self._queue))

    def _take_batch(self):
        """Pop the next batch: FIFO head anchors the shape key; requests
        sharing it join until the largest bucket fills or the head's
        max-wait deadline passes.  Other shape keys stay queued; queued
        requests past their per-request deadline expire typed."""
        with self._cv:
            while True:
                while not self._queue and not self._closed:
                    # bounded wait: an IDLE lane must still observe a
                    # process-level SIGTERM drain (nothing queues on a
                    # draining lane, so no submit would ever wake it)
                    if not self._draining:
                        from paddle_tpu.distributed import elastic

                        if elastic.drain_requested():
                            # queue is empty under the lock: flipping
                            # the flag IS the whole drain here
                            self._draining = True
                    self._cv.wait(timeout=0.5)
                if not self._queue:
                    return None  # closed and drained
                # a process-level SIGTERM drain (elastic.DrainHandler)
                # observed here fails the woken queue typed before any
                # of it reaches the device; Engine.drain() is the
                # explicit form of the same transition
                if not self._draining:
                    from paddle_tpu.distributed import elastic

                    if elastic.drain_requested():
                        # drop the condition's lock around drain(): it
                        # re-enters `with self._cv` and resolves futures
                        # (whose done-callbacks may call back into the
                        # engine) — both forbidden under the held lock
                        self._cv.release()
                        try:
                            self.drain()
                        finally:
                            # re-take OUR lock, released 4 lines up —
                            # not a wait on a peer
                            self._cv.acquire()  # resilience: allow
                        continue
                self._expire_queued()
                if not self._queue:
                    if self._closed:
                        return None
                    continue
                head = self._queue[0]
                deadline = head.t_arrival + self.max_wait_s
                if head.deadline is not None:
                    # a deadline-bearing head must not spend its whole
                    # budget waiting for batch-mates (a lone request
                    # with deadline < max_wait would otherwise be held
                    # the full max_wait and then burn a device dispatch
                    # on a result only the in-flight check could
                    # discard): wait at most HALF the deadline window,
                    # leaving the other half for execution
                    deadline = min(deadline,
                                   head.t_arrival + self.deadline_s / 2)
                while (self._matching_rows(head.shape_key)
                       < self.policy.max_rows):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._closed:
                        break
                    self._cv.wait(timeout=remaining)
                # the wait may have outlived some deadlines (including
                # the head's): expire now, and re-anchor if the head
                # itself is gone
                self._expire_queued()
                if not self._queue:
                    if self._closed:
                        return None
                    continue
                if self._queue[0] is head:
                    break
            batch, rows, rest = [], 0, collections.deque()
            for r in self._queue:
                if (r.shape_key == head.shape_key
                        and rows + r.rows <= self.policy.max_rows):
                    batch.append(r)
                    rows += r.rows
                else:
                    rest.append(r)
            self._queue = rest
            left = self._queued_rows[head.shape_key] - rows
            if left > 0:
                self._queued_rows[head.shape_key] = left
            else:
                del self._queued_rows[head.shape_key]
            self._queue_depth.set(len(self._queue))
            return batch

    def _scheduler(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            self._execute(batch)

    # -- execution ---------------------------------------------------------

    def _execute(self, batch, warmup=False):
        self._check_metrics_epoch()
        # batch-formation timestamp: the boundary between the two halves
        # of the request-latency split (pt_serve_queue_wait_seconds /
        # pt_serve_execute_seconds) — taken BEFORE the exec lock, so a
        # warmup holding the lock counts as execution pressure, not as
        # a mysteriously long queue
        t_batch = time.monotonic()  # observability: allow — split anchor
        with self._exec_lock:
            self._execute_locked(batch, t_batch, warmup=warmup)

    def _execute_locked(self, batch, t_batch=None, warmup=False):
        from paddle_tpu.observability import profiling as _profiling

        if t_batch is None:
            t_batch = time.monotonic()  # observability: allow
        rows = sum(r.rows for r in batch)
        bucket = self.policy.batch_bucket(rows)
        # warmup batches are compile time: they stay out of the
        # attribution surface entirely (NullRecorder), mirroring their
        # exclusion from the latency SLO histograms below
        ph = _profiling.step_phases("serve", self.name,
                                    enabled=not warmup)
        # one shared batch span: every traced request in the batch links
        # to it (fan-in), so the span tree shows which requests rode the
        # same device dispatch
        bspan = None
        if not warmup:
            bspan = _reqtrace.start_batch(
                f"batch:{self.name}",
                attrs={"model": self.name, "rows": rows,
                       "bucket": bucket})
        ph.__enter__()
        try:
            with ph.phase("feed_prep"):
                feed, slices = assemble_batch(batch, bucket)
            exec_key = (bucket, batch[0].shape_key)
            if warmup:
                result = "warmup"
            else:
                result = "warm" if exec_key in self._warm else "cold"
            # validate=False: every request was already validated at
            # submit against the lane's cached vars
            with ph.phase("dispatch"):
                outputs = self.predictor.run_feed_dict(feed,
                                                       validate=False)
            # booked only after the run succeeds: a failed batch must
            # not count phantom warm/cold dispatches (each retry would
            # re-book "cold" and drag the /servez hit rate toward 0)
            self._exec_cache[result].inc()
            self._cache_counts[result] += 1
            # under _cv: stats() iterates _warm (set subtraction) on the
            # exposition handler thread, and a concurrent resize would
            # raise mid-iteration there (membership tests above don't
            # iterate and stay lock-free)
            with self._cv:
                self._warm.add(exec_key)
            # split_outputs also slices dynamic-dim-1 outputs back to
            # each request's pre-pad sequence length (docs/SERVING.md
            # §2): padding positions must not reach the caller, and the
            # single final-shape copy must not pin the padded batch
            with ph.phase("fetch_sync"):
                per_req = split_outputs(
                    outputs, slices,
                    seq_pads=[r.seq_pad for r in batch],
                    dyn_seq=self._dyn_seq_outputs)
        except BaseException as e:  # resilience: allow — fanned to futures
            # covers post-run splitting/slicing too: an exception there
            # must fail the batch's futures, not kill the scheduler
            # thread and leave callers blocked forever (no future is
            # resolved before this point, so the fan-out never races a
            # set_result)
            ph.__exit__(type(e), e, None)
            if bspan is not None:
                bspan.finish("error", error=e)
            for r in batch:
                if not r.future.set_running_or_notify_cancel():
                    continue
                r.future.set_exception(e)
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            return
        ph.__exit__(None, None, None)
        if not warmup:
            # serve steps join the attribution layer too (flight ring +
            # per-model phase breakdown; seconds from the recorder).  A
            # cold batch compiled in the request path: first_run=True
            # keeps the compile seconds out of the serve-lane EMA and
            # the slow-step detector (a legitimate cold compile must not
            # burn the flight recorder's rate-limit window on a bogus
            # "slow step" postmortem)
            _profiling.note_step("serve", first_run=(result != "warm"))
        now = time.monotonic()
        execute_s = max(now - t_batch, 0.0)
        for r, out in zip(batch, per_req):
            if (not warmup and r.deadline is not None
                    and now > r.deadline):
                # in-flight deadline miss: the result exists but the
                # caller's budget is spent — resolve typed (and book it)
                # rather than hand back an answer it stopped waiting for
                self._rejected["deadline"].inc()
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(ServingDeadlineError(
                        f"model {self.name!r}: request exceeded its "
                        f"{self.deadline_s * 1000:.0f} ms deadline in "
                        f"flight (FLAGS_serving_deadline_ms)"))
                continue
            if r.span is not None:
                # attrs + fan-in link land BEFORE set_result: resolving
                # the future finishes the serve span (and, for a direct
                # caller, completes the whole trace)
                if bspan is not None:
                    r.span.link(bspan)
                r.span.set_attr("queue_wait_s",
                                max(t_batch - r.t_arrival, 0.0))
                r.span.set_attr("execute_s", execute_s)
            if r.future.set_running_or_notify_cancel():
                r.future.set_result(out)
            if not warmup:
                # warmup latency is compile time — it must not pollute
                # the SLO histograms traffic is judged by.  The split:
                # queue_wait (submit -> batch formation) + execute
                # (batch formation -> resolve) ≈ the total latency, so
                # a p99 breach names the guilty phase on /servez
                self._lat.observe(
                    max(now - r.t_arrival, 0.0),
                    exemplar=(r.span.trace_id if r.span is not None
                              else None))
                self._queue_wait.observe(
                    max(t_batch - r.t_arrival, 0.0))
                self._execute_hist.observe(execute_s)
        if bspan is not None:
            bspan.finish("ok", n_requests=len(batch))
        if not warmup:
            self._batch_size.observe(rows)
            self._rows["real"].inc(rows)
            self._rows["padding"].inc(bucket - rows)
            self._served_requests += len(batch)
            self._served_batches += 1

    # -- warmup ------------------------------------------------------------

    # warmup compiles the cross product of sequence buckets over the
    # model's dynamic dim-1 feeds (traffic may pad different feeds to
    # different buckets — the seq2seq src/tgt case); beyond this many
    # combinations per batch bucket, warn and truncate VISIBLY rather
    # than compile-storm warmup (the un-warmed rest still serves, books
    # cold, and shows up in pt_serve_executable_cache_total)
    _MAX_SEQ_COMBOS = 64

    def _warmup_shapes(self):
        """Zero-feed (rows, trailing) combinations covering the bucket
        set: every batch bucket × every assignment of sequence buckets
        to the dynamic dim-1 feeds (the request path pads each such
        feed independently, so mixed-length assignments are reachable
        traffic, not just the uniform diagonal).  Feeds with OTHER
        dynamic dims can't be synthesized and are skipped (their first
        traffic shape compiles cold — and books as such)."""
        import itertools

        names = self.predictor.get_input_names()
        dyn = self._dyn_seq_inputs
        if dyn and self._ragged:
            # ragged lane: every dynamic feed always pads to the ONE
            # ragged length, so the only reachable assignment is the
            # uniform diagonal at that length — one executable per
            # batch bucket, no cross product, no truncation warning
            seq_opts = [(self._ragged_len,) * len(dyn)]
        elif dyn and self.policy.seq_buckets:
            combos = itertools.product(self.policy.seq_buckets,
                                       repeat=len(dyn))
            seq_opts = list(itertools.islice(combos,
                                             self._MAX_SEQ_COMBOS + 1))
            if len(seq_opts) > self._MAX_SEQ_COMBOS:
                import warnings

                n_total = len(self.policy.seq_buckets) ** len(dyn)
                warnings.warn(
                    f"model {self.name!r}: {len(dyn)} dynamic dim-1 "
                    f"feeds × {len(self.policy.seq_buckets)} sequence "
                    f"buckets = {n_total} combinations per batch "
                    f"bucket; warming only the first "
                    f"{self._MAX_SEQ_COMBOS} — the rest compile cold "
                    f"on first traffic (use fewer seq buckets, or pad "
                    f"feeds to one length at the caller)")
                seq_opts = seq_opts[:self._MAX_SEQ_COMBOS]
        else:
            seq_opts = [()]
        pairs = [(rows, seqs) for rows in self.policy.batch_buckets
                 for seqs in seq_opts]
        # the slice-back refinement below (warmup()) can only tell a
        # sequence-following output from a constant-width one if every
        # dynamic feed's fed length VARIES across the warmed shapes.  A
        # single sequence bucket (or a combo truncation that pinned one
        # feed) would leave it blind — add one off-bucket probe shape,
        # at the smallest batch bucket, that perturbs every dynamic
        # feed's length.  Costs one extra (never-trafficked) compile;
        # without it padded traffic stays rejected (see
        # _validate_and_pad) because slicing would be a guess.  Probe
        # DOWNWARD when possible: every in-bucket traffic length is
        # below the bucket, so bucket-1 is far likelier to compile on a
        # length-sensitive model than bucket+1 (and a probe failure is
        # tolerated in warmup(), it just leaves slice-back unverified).
        self._probe_seqs = None
        if dyn and self._dyn_seq_outputs and seq_opts != [()] and any(
                len({t[i] for t in seq_opts}) < 2 for i in range(len(dyn))):
            probe = tuple(v - 1 if v > 1 else v + 1 for v in seq_opts[0])
            self._probe_seqs = probe
            pairs.append((self.policy.batch_buckets[0], probe))
        shapes = []
        for rows, seqs in pairs:
            by_name = dict(zip(dyn, seqs))
            feed = {}
            for n in names:
                var = self._var(n)
                if var is None or var.shape is None:
                    feed = None
                    break
                dims = list(var.shape)
                dims[0] = rows
                if len(dims) >= 2 and dims[1] == -1:
                    if n not in by_name:
                        feed = None
                        break
                    dims[1] = by_name[n]
                if any(d < 0 for d in dims[1:]):
                    feed = None  # non-seq dynamic dim: cannot warm
                    break
                dt = _np_dtype("float32" if var.dtype is None
                               or var.dtype == "" else var.dtype)
                if dt is None:  # unresolvable dtype: cannot warm
                    feed = None
                    break
                feed[n] = np.zeros(dims, dtype=dt)
            if feed:
                shapes.append(feed)
        return shapes

    def warmup(self):
        """Compile (or cache-load, with FLAGS_compile_cache_dir) one
        executable per bucket shape, OUTSIDE the request path.  Returns
        the number of bucket shapes warmed."""
        warmed = 0
        out_widths = collections.defaultdict(set)
        seqs_fed = set()
        skipped_mixed = []
        for feed in self._warmup_shapes():
            # Engine.warmup()'s closed check releases the engine lock
            # before reaching the lane, so a concurrent close() could
            # otherwise leave this loop compiling the whole bucket cross
            # product for a dead engine — re-check per shape
            if self._closed:
                raise ServingOverloadError(
                    f"model {self.name!r}: engine closed during warmup",
                    reason="closed")
            fut = concurrent.futures.Future()
            rows = next(iter(feed.values())).shape[0]
            key = tuple((n, tuple(a.shape[1:]), str(a.dtype))
                        for n, a in feed.items())
            req = Request(feed, rows, "__warmup__", fut, key)
            self._execute([req], warmup=True)
            seqs = tuple(int(feed[n].shape[1])
                         for n in self._dyn_seq_inputs)
            try:
                out = fut.result()
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception:
                # the PROBE shape is synthetic (an off-bucket length no
                # traffic ever takes): a length-sensitive model failing
                # it must not make the real bucket set unwarmable —
                # skip it, but say that slice-back stays unverified, so
                # padded dyn-output traffic keeps rejecting typed.
                if seqs and seqs == self._probe_seqs:
                    import warnings

                    warnings.warn(
                        f"model {self.name!r}: the off-bucket probe "
                        f"shape (seq lengths {seqs}) failed to "
                        f"compile, so warmup could not verify which "
                        f"dynamic-length outputs track the fed "
                        f"sequence length — padded requests will be "
                        f"rejected; pad feeds to a bucket length at "
                        f"the caller, or re-export with static output "
                        f"widths")
                    continue
                # a UNIFORM assignment (every dynamic feed at one
                # length) failing means the model itself is broken:
                # propagate loudly.  A MIXED assignment may simply
                # violate the model's own contract (elementwise ops
                # need equal lengths) — such traffic would fail at
                # request time with the error fanned to that future
                # anyway, so skip the shape and say so, rather than
                # making every equal-length-contract model unwarmable.
                if len(set(seqs)) <= 1:
                    raise
                skipped_mixed.append(seqs)
                continue
            seqs_fed.add(seqs)
            for n, a in out.items():
                if n in self._dyn_seq_outputs and a.ndim >= 2:
                    out_widths[n].add(int(a.shape[1]))
            # the synthetic off-bucket probe still feeds the width
            # observation above but is NOT a bucket shape: the returned
            # count must stay one-per-bucket-shape (ops scripts assert
            # warmed == expected bucket count), and stats() excludes
            # its executable from warm_executables for the same reason
            if seqs and seqs == self._probe_seqs:
                with self._cv:  # stats() iterates this set too
                    self._probe_keys.add((self.policy.batch_bucket(rows),
                                          key))
            else:
                warmed += 1
        if skipped_mixed:
            import warnings

            warnings.warn(
                f"model {self.name!r}: {len(skipped_mixed)} mixed "
                f"sequence-bucket assignment(s) failed to warm "
                f"(e.g. {skipped_mixed[0]}) and were skipped — the "
                f"model likely requires equal dynamic lengths; "
                f"requests mixing those lengths will fail at request "
                f"time")
        # empirical refinement of the declared dynamic-dim-1 output set:
        # a dynamic-DECLARED output whose width stayed CONSTANT while the
        # fed sequence buckets varied does not actually follow the
        # sequence — slicing it back by width match would silently
        # truncate real columns whenever its constant width coincides
        # with a padded length.  Only valid when EVERY dynamic feed's
        # length varied (an output tracking a never-varied feed would
        # look constant and be wrongly exempted); _warmup_shapes adds a
        # probe shape to guarantee that.  (Atomic rebinds: submit
        # threads read both attributes unlocked.)
        if len(seqs_fed) > 1 and all(
                len({t[i] for t in seqs_fed}) > 1
                for i in range(len(self._dyn_seq_inputs))):
            self._dyn_seq_outputs = {
                n for n in self._dyn_seq_outputs
                if len(out_widths.get(n, ())) != 1}
            self._seq_outputs_confirmed = True
        return warmed

    # -- lifecycle / stats -------------------------------------------------

    def drain(self):
        """Graceful drain (the serving half of the `elastic.DrainHandler`
        contract): stop admission — new submits reject typed with
        ``reason="draining"`` — and fail the QUEUED futures typed; the
        batch already in flight on the scheduler thread completes and
        resolves normally.  The scheduler stays alive (close() still
        owns teardown), so a SIGTERM'd replica finishes real work
        instead of dying mid-batch.  Idempotent."""
        with self._cv:
            if self._closed or self._draining:
                return
            self._draining = True
            leftovers, self._queue = list(self._queue), collections.deque()
            self._queued_rows.clear()
            self._queue_depth.set(0)
            self._cv.notify_all()
        for r in leftovers:
            if r.future.set_running_or_notify_cancel():
                self._rejected["draining"].inc()
                r.future.set_exception(ServingOverloadError(
                    f"model {self.name!r}: engine drained before the "
                    f"request was scheduled — resubmit to another "
                    f"replica", reason="draining"))

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # a never-started (or wedged) lane may still hold queued
        # requests: fail their futures typed instead of leaving callers
        # blocked forever
        with self._cv:
            leftovers, self._queue = list(self._queue), collections.deque()
            self._queued_rows.clear()
            self._queue_depth.set(0)
        for r in leftovers:
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(ServingOverloadError(
                    f"model {self.name!r}: engine closed before the "
                    f"request was scheduled", reason="closed"))

    def stats(self):
        from paddle_tpu import observability as obs

        self._check_metrics_epoch()
        with self._cv:
            depth = len(self._queue)
            # copy under the lock: submit() inserts first-seen tenant
            # keys while holding _cv, and _execute/warmup add warm exec
            # keys under _cv — an unlocked dict()/set-subtraction here
            # can raise mid-iteration on a /servez scrape
            tenants = dict(self._tenant_requests)
            n_warm = len(self._warm - self._probe_keys)
        # lane-local counts, NOT the process-cumulative registry: a
        # fresh engine must not inherit a closed predecessor's figures
        cache = {k: int(self._cache_counts.get(k, 0))
                 for k in ("warmup", "warm", "cold")}
        dispatched = cache["warm"] + cache["cold"]

        def delta_quantiles(child, baseline):
            """Lane-local p50/p99 of a process-cumulative histogram: the
            delta against the bind-time baseline, so a fresh lane never
            inherits a closed predecessor's figures."""
            cur = child.hist_data()
            h = {"buckets": [(le, c - b) for (le, c), (_, b) in
                             zip(cur["buckets"], baseline["buckets"])],
                 "sum": cur["sum"] - baseline["sum"],
                 "count": cur["count"] - baseline["count"]}
            if h["count"] <= 0:
                return {}
            return {"p50": obs.hist_quantile(h, 0.50),
                    "p99": obs.hist_quantile(h, 0.99),
                    "count": h["count"]}

        return {
            "signature": self.signature,
            "queue_depth": depth,
            "draining": self._draining,
            "requests": self._served_requests,
            "batches": self._served_batches,
            "warm_executables": n_warm,
            "executable_cache": dict(
                cache, hit_rate=(cache["warm"] / dispatched
                                 if dispatched else None)),
            "tenants": tenants,
            "latency_seconds": delta_quantiles(self._lat,
                                               self._lat_baseline),
            # the latency SPLIT (docs/SERVING.md): queue_wait = submit
            # -> batch formation, execute = batch formation -> resolve;
            # an SLO p99 breach names the guilty phase right here
            "queue_wait_seconds": delta_quantiles(
                self._queue_wait, self._queue_wait_baseline),
            "execute_seconds": delta_quantiles(
                self._execute_hist, self._execute_baseline),
        }


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class Engine:
    """Production request path over N `AnalysisPredictor`s.

    ``models`` maps a serving name to a saved-model dir, an
    `AnalysisConfig`, or an already-built `AnalysisPredictor`.  Requests
    are dicts of numpy arrays with a leading row dim; `submit` returns a
    future resolving to ``{output_name: array[rows, ...]}``; `infer` is
    the blocking form.  Recommended lifecycle: build with
    ``auto_start=False`` → `warmup()` (precompile every bucket outside
    the request path) → `start()` → traffic → `close()`.  `warmup()` on
    an already-started engine is safe (a per-lane lock serializes it
    against scheduler dispatch), but traffic arriving before it
    finishes pays cold compiles in the request path.
    """

    def __init__(self, models=None, batch_buckets=None, seq_buckets=None,
                 max_wait_ms=None, max_queue=None, name="engine",
                 auto_start=True, deadline_ms=None):
        from paddle_tpu.fluid import flags as _flags

        self.name = name
        self.policy = BucketPolicy(batch_buckets, seq_buckets)
        self._max_wait_s = (
            _flags.flag("serving_batch_timeout_ms")
            if max_wait_ms is None else max_wait_ms) / 1000.0
        self._max_queue = int(_flags.flag("serving_max_queue")
                              if max_queue is None else max_queue)
        if self._max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        # per-request deadline (0 = off): queued or in-flight requests
        # past it resolve ServingDeadlineError instead of waiting forever
        self._deadline_s = (
            _flags.flag("serving_deadline_ms")
            if deadline_ms is None else deadline_ms) / 1000.0
        self._lanes = {}
        # serializes lane-map mutation against lifecycle transitions and
        # snapshots: load_model() from one thread must not race a
        # concurrent close()/start()/stats() iterating the map (a /servez
        # scrape runs on the exposition server's handler thread)
        self._lock = threading.Lock()
        self._started = False
        self._closed = False
        try:
            for mname, model in (models or {}).items():
                self.load_model(mname, model)
            from . import status as _status

            # inside the cleanup block: register_page('/servez') raises
            # when another subsystem owns the path, and the caller has
            # no engine reference to close() the built lanes with
            _status.track_engine(self)
            # auto_start inside the cleanup block too: a scheduler
            # thread that fails to spawn (process thread limit) must
            # not leak the built lanes and the tracked /servez entry
            if auto_start:
                self.start()
        except BaseException:
            # the caller never gets an engine reference to close(): shut
            # the already-built lanes down here instead of leaking them
            self.close()
            raise

    # -- model management --------------------------------------------------

    def load_model(self, name, model, ragged=None):
        """Load a model under a serving name.  `model`: saved-model dir
        (str), `AnalysisConfig`, or a built `AnalysisPredictor`.

        ``ragged`` (default: FLAGS_ragged_attention) puts the lane in
        ragged mode — every dynamic dim-1 feed pads to the single
        largest sequence bucket so mixed-length traffic shares one
        shape key (and one batch), and warmup compiles one executable
        per batch bucket instead of the seq-bucket cross product.  The
        model must mask its own padded tail from a per-row lengths feed
        (layers.ragged_attention; docs/KERNELS.md)."""
        from paddle_tpu.fluid import flags as _flags
        from paddle_tpu.inference import (AnalysisConfig, AnalysisPredictor,
                                          create_paddle_predictor)

        if ragged is None:
            ragged = bool(_flags.flag("ragged_attention"))

        if self._closed:
            raise ServingOverloadError(
                f"engine {self.name!r} is closed; cannot load models",
                reason="closed")
        if name in self._lanes:
            raise ValueError(f"model {name!r} already loaded")
        if isinstance(model, str):
            config = AnalysisConfig(model)
            config.disable_gpu()  # serving default: current process device
            predictor = create_paddle_predictor(config)
        elif isinstance(model, AnalysisConfig):
            predictor = create_paddle_predictor(model)
        elif isinstance(model, AnalysisPredictor):
            predictor = model
        else:
            raise TypeError(
                f"model must be a dir, AnalysisConfig or "
                f"AnalysisPredictor; got {type(model).__name__}")
        lane = _ModelLane(name, predictor, self.policy, self._max_wait_s,
                          self._max_queue, deadline_s=self._deadline_s,
                          ragged=ragged)
        # pt_serve_* series are keyed by model name: a second engine in
        # this process serving the same name would alias its series (and
        # /servez stats) onto this one — warn, don't corrupt silently
        from . import status as _status

        for other in _status.live_engines():
            if other is not self and name in getattr(other, "_lanes", {}):
                import warnings

                warnings.warn(
                    f"model name {name!r} is already served by engine "
                    f"{other.name!r} in this process; pt_serve_* metrics "
                    f"and /servez stats for the two will alias — use "
                    f"distinct model names")
        with self._lock:
            # re-check under the lock: a close() between the cheap early
            # guard and here must not end with a live lane on a dead
            # engine (the lane has no threads yet, so discarding is safe)
            if self._closed:
                raise ServingOverloadError(
                    f"engine {self.name!r} is closed; cannot load models",
                    reason="closed")
            if name in self._lanes:
                raise ValueError(f"model {name!r} already loaded")
            self._lanes[name] = lane
            started = self._started
        if started:
            lane.start()
        return lane.signature

    def models(self):
        with self._lock:
            return sorted(self._lanes)

    def _lane(self, model):
        lane = self._lanes.get(model)
        if lane is None:
            raise ModelNotLoadedError(
                f"model {model!r} not loaded; serving {self.models()}")
        return lane

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Start the per-model scheduler threads.  Submissions before
        start() queue up (admission control still applies)."""
        with self._lock:
            if self._closed:
                raise ServingOverloadError(
                    f"engine {self.name!r} is closed; cannot start",
                    reason="closed")
            self._started = True
            lanes = list(self._lanes.values())
        for lane in lanes:
            lane.start()
        return self

    def warmup(self, model=None):
        """Precompile every bucket-shape executable (all models, or
        one).  With FLAGS_compile_cache_dir set, a restarted server's
        warmup resolves from the persistent XLA cache instead of
        recompiling.  Returns {model: n_shapes_warmed}."""
        with self._lock:
            if self._closed:
                raise ServingOverloadError(
                    f"engine {self.name!r} is closed; cannot warm up",
                    reason="closed")
            if model is None:
                lanes = list(self._lanes.values())
            elif model in self._lanes:
                lanes = [self._lanes[model]]
            else:
                lanes = None
        if lanes is None:
            raise ModelNotLoadedError(
                f"model {model!r} not loaded; serving {self.models()}")
        return {lane.name: lane.warmup() for lane in lanes}

    def drain(self):
        """Graceful drain across every lane (the `elastic.DrainHandler`
        hookup): admission stops typed (``reason="draining"``), queued
        futures fail typed, in-flight batches complete.  The engine
        stays open — call close() after the process snapshot/LEAVE
        choreography finishes."""
        with self._lock:
            lanes = list(self._lanes.values())
        for lane in lanes:
            lane.drain()

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            lanes = list(self._lanes.values())
        for lane in lanes:
            lane.close()
        from . import status as _status

        _status.untrack_engine(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- request path ------------------------------------------------------

    def submit(self, model, feed, tenant="default"):
        """Enqueue one request; returns a `concurrent.futures.Future`
        resolving to ``{output_name: array[rows, ...]}`` (this caller's
        rows only — batching is invisible).  Raises
        `ServingOverloadError` at the admission limit and
        `FeedValidationError` on a bad feed."""
        # a closed engine's lanes reject with reason="closed" — routed
        # through the lane so the rejection is booked per model
        return self._lane(model).submit(feed, tenant)

    def infer(self, model, feed, tenant="default", timeout=None):
        """Blocking convenience: submit + wait."""
        return self.submit(model, feed, tenant=tenant).result(
            timeout=timeout)

    # -- introspection -----------------------------------------------------

    def stats(self):
        """The /servez payload for this engine: bucket policy, per-model
        queue/served/cache-hit-rate/tenant/latency figures."""
        with self._lock:  # /servez scrapes from the exposition thread
            lanes = sorted(self._lanes.items())
        return {
            "engine": self.name,
            "started": self._started,
            "buckets": self.policy.describe(),
            "batch_timeout_ms": self._max_wait_s * 1000.0,
            "max_queue": self._max_queue,
            "models": {name: lane.stats() for name, lane in lanes},
        }
