"""Shape-bucketed continuous batching: the scheduler-side data plane.

TPU serving economics (PAPER.md §1 redesign): the whole pruned inference
program compiles to ONE XLA executable per feed-shape signature, so the
problem is not per-op dispatch but bounding the number of distinct
signatures under variable traffic.  The classic answer — shape buckets:
every formed batch is padded up to the smallest configured row bucket
(and, for feeds with a dynamic dim-1, the smallest sequence bucket), so
a fixed small set of executables serves every request mix, and after
warmup nothing ever recompiles.

This module is the pure data plane: bucket selection, batch assembly
(concatenate + zero-pad), and output row-splitting.  Queueing, futures,
threads and metrics live in `engine`.
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["BucketPolicy", "Request", "assemble_batch", "split_outputs"]


def _norm_buckets(spec):
    """'1,2,4,8' (tolerates spaces) or an int iterable -> sorted unique
    positive ints; zero/negative sizes raise on BOTH input forms."""
    if isinstance(spec, str):
        vals = [int(tok) for tok in spec.split(",") if tok.strip()]
    else:
        vals = [int(v) for v in spec]
    for v in vals:
        if v <= 0:
            raise ValueError(f"bucket sizes must be positive, got {v}")
    return tuple(sorted(set(vals)))


class BucketPolicy:
    """The bucket set one engine serves: row (batch) buckets plus
    optional sequence-length buckets for dynamic dim-1 feeds.

    Defaults come from FLAGS_serving_batch_buckets /
    FLAGS_serving_seq_buckets at construction time (not import time, so
    `set_flags` before building an Engine behaves as expected)."""

    def __init__(self, batch_buckets=None, seq_buckets=None):
        from paddle_tpu.fluid import flags as _flags

        if batch_buckets is None:
            batch_buckets = _flags.flag("serving_batch_buckets")
        if seq_buckets is None:
            seq_buckets = _flags.flag("serving_seq_buckets")
        self.batch_buckets = _norm_buckets(batch_buckets)
        if not self.batch_buckets:
            raise ValueError("serving needs at least one batch bucket")
        self.seq_buckets = _norm_buckets(seq_buckets)

    @property
    def max_rows(self):
        return self.batch_buckets[-1]

    def batch_bucket(self, rows):
        """Smallest row bucket >= rows; None when rows exceed the largest
        (the caller rejects — a request bigger than the largest bucket
        would mint a new executable per size, defeating the design)."""
        for b in self.batch_buckets:
            if rows <= b:
                return b
        return None

    def seq_bucket(self, length):
        """Smallest sequence bucket >= length.  Lengths beyond the
        largest bucket pass through unpadded (they compile on demand and
        the engine counts them as cold-cache work — visible, not
        silently truncated)."""
        for b in self.seq_buckets:
            if length <= b:
                return b
        return int(length)

    def describe(self):
        return {"batch": list(self.batch_buckets),
                "seq": list(self.seq_buckets)}


class Request:
    """One caller's unit of work: a feed dict of numpy arrays sharing a
    leading row dim, a future the engine resolves, and the arrival time
    the latency metric is measured from."""

    __slots__ = ("feed", "rows", "tenant", "future", "t_arrival",
                 "shape_key", "seq_pad", "deadline", "span")

    def __init__(self, feed, rows, tenant, future, shape_key,
                 seq_pad=None, deadline_s=0.0):
        self.feed = feed
        self.rows = rows
        self.tenant = tenant
        self.future = future
        self.span = None  # serve span (observability.reqtrace), if traced
        self.t_arrival = time.monotonic()
        # absolute monotonic deadline (FLAGS_serving_deadline_ms): a
        # request older than this resolves ServingDeadlineError instead
        # of waiting forever, queued or in flight; None = no deadline
        self.deadline = (self.t_arrival + deadline_s
                         if deadline_s and deadline_s > 0 else None)
        # trailing-dims signature AFTER sequence padding: only requests
        # with equal keys can share a batch (concat needs it, and the
        # padded batch must land in one executable signature)
        self.shape_key = shape_key
        # {padded_len: orig_len} for the dim-1 sequence padding this
        # request's dynamic feeds received — the engine slices a
        # dynamic-dim-1 output whose length matches a padded_len back
        # to its orig_len so padding positions never reach the caller;
        # None when nothing was padded
        self.seq_pad = seq_pad


def _pad_axis0(arr, target_rows):
    rows = arr.shape[0]
    if rows == target_rows:
        return arr
    pad = np.zeros((target_rows - rows, *arr.shape[1:]), dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def pad_seq(arr, target_len):
    """Zero-pad dim-1 up to target_len (no-op when already there)."""
    if arr.ndim < 2 or arr.shape[1] == target_len:
        return arr
    if arr.shape[1] > target_len:
        raise ValueError(
            f"cannot pad dim-1 of {arr.shape} down to {target_len}")
    pad_shape = (arr.shape[0], target_len - arr.shape[1], *arr.shape[2:])
    return np.concatenate(
        [arr, np.zeros(pad_shape, dtype=arr.dtype)], axis=1)


def assemble_batch(requests, bucket_rows):
    """Concatenate same-shape-key requests along axis 0 and zero-pad up
    to `bucket_rows`.  Returns (feed, row_slices) where row_slices[i] is
    the (start, stop) of request i's rows in every batch array.

    Requests must already carry sequence-padded arrays (the engine pads
    per request at submit so the shape_key is settled before grouping).
    """
    if not requests:
        raise ValueError("empty batch")
    names = list(requests[0].feed)
    slices, start = [], 0
    for r in requests:
        slices.append((start, start + r.rows))
        start += r.rows
    if start > bucket_rows:
        raise ValueError(
            f"batch of {start} rows exceeds bucket {bucket_rows}")
    feed = {}
    for n in names:
        arr = (requests[0].feed[n] if len(requests) == 1
               else np.concatenate([r.feed[n] for r in requests], axis=0))
        feed[n] = _pad_axis0(np.asarray(arr), bucket_rows)
    return feed, slices


def split_outputs(outputs, slices, seq_pads=None, dyn_seq=()):
    """Slice each request's rows back out of the batch outputs.
    outputs: {name: array [bucket_rows, ...]}; returns a list (one dict
    per request) in `slices` order — padding rows never escape.  Rows
    are copied, not viewed: a caller retaining one small result must
    not pin the whole bucket-sized batch array.

    seq_pads: optional per-request ``{padded_len: orig_len}`` mappings
    (one entry per slice, None allowed).  Outputs named in `dyn_seq`
    whose dim-1 equals a padded length are sliced back to the original
    length in the SAME copy — one allocation at the final shape, never
    a padded-width copy followed by a second slice copy."""
    out = []
    for i, (start, stop) in enumerate(slices):
        pad = seq_pads[i] if seq_pads else None
        per = {}
        for n, v in outputs.items():
            base = np.asarray(v)
            a = base[start:stop]
            if pad and n in dyn_seq and a.ndim >= 2 and a.shape[1] in pad:
                a = a[:, :pad[a.shape[1]]]
            # the copy exists so a retained small result can't pin the
            # bucket-sized batch array — when the slice IS the whole
            # array (a lone max-size request, the common full-bucket
            # case under load) it pins nothing and the memcpy is pure
            # waste.  The skip must still preserve the result contract:
            # np.asarray over a jax buffer is READ-ONLY, so a full-span
            # view would make writability flip with bucket alignment —
            # copy unless the view is already writable
            per[n] = a if (a.size == base.size
                           and a.flags.writeable) else a.copy()
        out.append(per)
    return out
