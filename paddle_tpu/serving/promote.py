"""Canary weight promotion with auto-rollback (docs/SERVING.md
"Resilience").

Closes the train→serve loop ROADMAP names ("zero-downtime weight
promotion") robustness-first: a training checkpoint's parameter arrays
are published into a RUNNING replica group one replica at a time, with
a measured probe window between each step and automatic rollback on
regression.

Why a swap needs zero compiles: the decode lane's two executables are
keyed by program signature, not by parameter VALUES — `WeightSet.apply`
replaces arrays in the replica's scope under its `_exec_lock` and the
programs/executables are untouched, so `pt_compile_cache_total` misses
stay flat across the whole promotion (the drill harness gates on
exactly that; with FLAGS_aot_cache_dir a RELAUNCHED replica is equally
zero-compile, which is why the launchers now forward it).

Promotion sequence per replica (the canary first, then the rest):

  hold      `router.set_held(name)` — out of rotation, live traffic
            routes to the other replicas (zero dropped requests)
  quiesce   wait for the replica's live-sequence count to hit zero
  swap      capture old arrays, apply the new WeightSet under
            `_exec_lock`
  probe     greedy-decode the probe prompts on the canary and gate on
            (a) error rate, (b) per-probe latency ratio vs the same
            replica's pre-swap probes, (c) greedy-token drift vs the
            pre-swap streams — the logprob-drift proxy the greedy lane
            exposes.  Probes route through
            `fault_injection.on_serve(replica)` so a `serve_error:`
            rule injects a deterministic canary regression.
  verdict   gates pass → release the hold, promote the next replica;
            any gate fails → restore the old arrays, release the hold,
            book `pt_serve_promotions_total{outcome="rolled_back"}`
            and stop.  All replicas converged → one
            `{outcome="promoted"}` sample.

Greedy-only caveat (same as failover): the drift gate compares argmax
token streams, so it detects distribution shift only where it flips the
argmax.  A sampling lane will need true logprob deltas.
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["WeightSet", "PromotionGates", "promote", "capture_weights"]


def _m_promotions():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_serve_promotions_total",
        "Canary weight promotions by outcome: `promoted` (gates passed "
        "on every replica, whole group converged on the new weights) "
        "vs `rolled_back` (a probe gate failed; the canary's old "
        "arrays were restored)", labels=("router", "outcome"))


class WeightSet:
    """Named parameter arrays — the unit a promotion publishes.

    Build one `from_scope` (a training process's live parameters, or a
    scratch scope a checkpoint was `fluid.io`-loaded into) or directly
    from a `{name: ndarray}` dict.  `apply(scope)` replaces the arrays
    by name; programs and executables are untouched (zero compiles)."""

    def __init__(self, arrays):
        self.arrays = {str(k): np.asarray(v) for k, v in arrays.items()}

    @classmethod
    def from_scope(cls, scope, names):
        missing = [n for n in names if scope.find_var(n) is None]
        if missing:
            raise KeyError(
                f"WeightSet.from_scope: {len(missing)} names not in "
                f"scope (first: {missing[:3]})")
        return cls({n: np.array(scope.find_var(n).get_tensor())
                    for n in names})

    def names(self):
        return sorted(self.arrays)

    def apply(self, scope):
        for n, a in self.arrays.items():
            scope.set(n, a)

    def __len__(self):
        return len(self.arrays)


def capture_weights(scope, names):
    """Snapshot `names` out of `scope` as a WeightSet (the rollback
    save, or a training loop publishing its current parameters)."""
    return WeightSet.from_scope(scope, names)


class PromotionGates:
    """The canary verdict thresholds.

    max_error_rate     fraction of probe requests that may fail
                       (default 0.0 — any probe error rolls back)
    max_latency_ratio  canary mean probe latency / pre-swap mean probe
                       latency ceiling (None = don't gate; the default
                       8.0 is lenient — it catches a pathological swap,
                       not noise)
    max_drift          fraction of probe TOKENS that may differ from
                       the pre-swap streams (None = don't gate — the
                       right setting when the new weights are a real
                       training delta; 0.0 gates a same-weights
                       republish bit-exact)
    """

    def __init__(self, max_error_rate=0.0, max_latency_ratio=8.0,
                 max_drift=None):
        self.max_error_rate = float(max_error_rate)
        self.max_latency_ratio = (None if max_latency_ratio is None
                                  else float(max_latency_ratio))
        self.max_drift = None if max_drift is None else float(max_drift)

    def verdict(self, probe, baseline):
        """(ok, reasons) for a post-swap `probe` vs the pre-swap
        `baseline` (both from `_run_probes`)."""
        reasons = []
        if probe["error_rate"] > self.max_error_rate:
            reasons.append(
                f"error_rate {probe['error_rate']:.3f} > "
                f"{self.max_error_rate:.3f}")
        if self.max_latency_ratio is not None \
                and baseline["mean_latency_s"] > 0:
            ratio = probe["mean_latency_s"] / baseline["mean_latency_s"]
            if ratio > self.max_latency_ratio:
                reasons.append(
                    f"latency ratio {ratio:.2f} > "
                    f"{self.max_latency_ratio:.2f}")
        if self.max_drift is not None:
            drift = _token_drift(baseline["streams"], probe["streams"])
            if drift > self.max_drift:
                reasons.append(
                    f"token drift {drift:.3f} > {self.max_drift:.3f}")
        return not reasons, reasons


def _token_drift(ref_streams, new_streams):
    """Fraction of positions where the greedy streams disagree (a
    failed probe counts every position as drifted)."""
    total = mismatch = 0
    for ref, new in zip(ref_streams, new_streams):
        if ref is None or new is None:
            n = max(len(ref or ()), len(new or ()), 1)
            total += n
            mismatch += n
            continue
        n = max(len(ref), len(new))
        total += max(n, 1)
        mismatch += sum(1 for i in range(n)
                        if i >= len(ref) or i >= len(new)
                        or ref[i] != new[i])
    return mismatch / max(total, 1)


def _run_probes(rep, prompts, max_new_tokens, timeout_s):
    """Greedy-decode every probe prompt directly on `rep` (bypassing
    the router — the canary is held out of rotation).  Each probe
    passes the `fault_injection.on_serve` gate under the REPLICA name,
    so a `serve_error:<replica>:req:N` rule lands deterministically in
    this window."""
    from paddle_tpu.distributed import fault_injection as _fault

    streams, latencies, errors = [], [], 0
    for prompt in prompts:
        t0 = time.monotonic()
        try:
            _fault.on_serve(rep.name)
            fut = rep.engine.submit(prompt, max_new_tokens)
            streams.append(list(fut.result(timeout=timeout_s)))
            latencies.append(time.monotonic() - t0)
        except Exception:
            errors += 1
            streams.append(None)
    return {
        "streams": streams,
        "errors": errors,
        "error_rate": errors / max(len(prompts), 1),
        "mean_latency_s": (sum(latencies) / len(latencies)
                           if latencies else 0.0),
    }


def _quiesce(rep, timeout_s):
    deadline = time.monotonic() + timeout_s
    while rep.load() > 0:
        if time.monotonic() > deadline:
            return False
        time.sleep(0.005)
    return True


def promote(router, weights, *, probe_prompts, probe_max_new_tokens=8,
            gates=None, quiesce_timeout_s=30.0, probe_timeout_s=60.0,
            order=None):
    """Publish `weights` (a WeightSet) into `router`'s decode replica
    group one replica at a time with probe gates and auto-rollback.

    Returns a report dict: ``outcome`` (`promoted` / `rolled_back`),
    ``replicas`` (per-replica probe/verdict records in promotion
    order), and on rollback ``rolled_back_on`` + ``reasons``.  Books
    one `pt_serve_promotions_total{outcome}` sample either way.

    ``order``: replica names, canary first (default: enrollment order).
    Raises TimeoutError if a replica never quiesces (nothing was
    swapped on that replica; earlier replicas KEEP the new weights —
    re-run or roll back explicitly)."""
    gates = gates if gates is not None else PromotionGates()
    prompts = [list(p) for p in probe_prompts]
    if not prompts:
        raise ValueError("promote: probe_prompts must be non-empty — "
                         "the gates need a measured probe window")
    reps = {r.name: r for r in router.replicas("decode")}
    if not reps:
        raise ValueError(f"router {router.name!r} has no decode replicas")
    names = list(order) if order is not None else list(reps)
    unknown = [n for n in names if n not in reps]
    if unknown:
        raise KeyError(f"promote: unknown replicas {unknown}")

    report = {"outcome": None, "replicas": [], "weights": len(weights)}
    for name in names:
        rep = reps[name]
        router.set_held(name, True)
        try:
            if not _quiesce(rep, quiesce_timeout_s):
                raise TimeoutError(
                    f"promote: replica {name!r} did not quiesce within "
                    f"{quiesce_timeout_s}s (load={rep.load()}) — no swap "
                    f"performed on it")
            baseline = _run_probes(rep, prompts, probe_max_new_tokens,
                                   probe_timeout_s)
            old = capture_weights(rep.engine.scope, weights.names())
            # swap under the replica's dispatch lock: no decode step may
            # read a half-applied parameter set
            with rep.engine._exec_lock:
                weights.apply(rep.engine.scope)
            probe = _run_probes(rep, prompts, probe_max_new_tokens,
                                probe_timeout_s)
            ok, reasons = gates.verdict(probe, baseline)
            rec = {"replica": name, "ok": ok, "reasons": reasons,
                   "baseline": {k: baseline[k] for k in
                                ("error_rate", "mean_latency_s")},
                   "probe": {k: probe[k] for k in
                             ("error_rate", "mean_latency_s")}}
            report["replicas"].append(rec)
            if not ok:
                with rep.engine._exec_lock:
                    old.apply(rep.engine.scope)
                report["outcome"] = "rolled_back"
                report["rolled_back_on"] = name
                report["reasons"] = reasons
                _m_promotions().labels(router=router.name,
                                       outcome="rolled_back").inc()
                return report
        finally:
            router.set_held(name, False)
    report["outcome"] = "promoted"
    _m_promotions().labels(router=router.name, outcome="promoted").inc()
    return report
