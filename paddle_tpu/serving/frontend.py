"""HTTP front door for the serving stack (docs/SERVING.md
"Resilience").

The stdlib-http precedent is observability/exposition.py: a
`ThreadingHTTPServer` on a daemon thread, JSON in/out, no new
dependencies.  The frontend wires the EXISTING serving contracts — typed
admission, per-tenant quotas, deadlines, SIGTERM drain — through a real
listener, fronted by a `Router` (or a bare engine; both call surfaces
are duck-typed):

  POST /v1/generate   {"prompt": [ids], "max_new_tokens": n,
                       "eos_id"?, "tenant"?, "timeout_s"?}
                      → {"tokens": [ids]} via the decode lane
                      (router failover/retry apply underneath)
  POST /v1/infer      {"model": m, "feed": {name: nested lists},
                       "tenant"?, "timeout_s"?}
                      → {"outputs": {name: nested lists}} via the
                      stateless lane (hedging applies underneath)
  GET  /healthz       {"ok": true|false, "draining": ...} — 503 while
                      draining, the load-balancer's out-of-rotation cue
  GET  /routerz       the router's replica table (also registered on
                      the /metricsz exposition server)

Typed serving errors map onto HTTP statuses instead of leaking
tracebacks: ServingOverloadError → 429 (503 for `draining`/`closed`),
ServingDeadlineError → 504, FeedValidationError/ValueError → 400,
ModelNotLoadedError → 404, anything else → 500.

SIGTERM drain ordering (the `elastic.DrainHandler` chain, satellite of
ISSUE 18): on drain the frontend FIRST stops admission (new requests
get 503), THEN drains the replicas (in-flight batches finish, queued
futures fail typed — the engine drain contract), waits for open HTTP
connections to write their responses, and only THEN closes the
listener and lets the handler chain re-deliver the signal.  A client
mid-request at SIGTERM gets its completed tokens, not a reset
connection.
"""

from __future__ import annotations

import concurrent.futures
import json
import threading
import time

import numpy as np

from paddle_tpu.distributed import resilience as _resilience
from paddle_tpu.observability import reqtrace as _reqtrace

from .errors import (FeedValidationError, ModelNotLoadedError,
                     ServingDeadlineError, ServingError,
                     ServingOverloadError)

__all__ = ["Frontend"]

_DEFAULT_TIMEOUT_S = 600.0


def _error_status(exc):
    """Typed serving error → HTTP status (the admission contract on the
    wire)."""
    if isinstance(exc, ServingOverloadError):
        return 503 if exc.reason in ("draining", "closed") else 429
    if isinstance(exc, ServingDeadlineError):
        return 504
    if isinstance(exc, (FeedValidationError, ValueError)):
        return 400
    if isinstance(exc, ModelNotLoadedError):
        return 404
    return 500


def _error_body(exc):
    body = {"error": type(exc).__name__, "message": str(exc)}
    reason = getattr(exc, "reason", None)
    if reason:
        body["reason"] = reason
    return body


class Frontend:
    """One HTTP listener over a router (or bare engine).

    ``backend``: a `Router` (decode `submit` + stateless `submit_feed`),
    a `DecodeEngine` (generate only), or an `Engine` (infer only).
    ``port=0`` binds an ephemeral port (tests); read `.port` after
    construction.  The server thread is a daemon; `close()` (or the
    drain path) shuts it down deterministically."""

    def __init__(self, backend, host="127.0.0.1", port=0,
                 name="frontend", auto_start=True):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        self.backend = backend
        self.name = name
        self._draining = False
        self._closed = False
        self._lock = threading.Lock()
        self._inflight = 0
        self._idle = threading.Event()
        self._idle.set()
        frontend = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # silence per-request spam
                pass

            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                trace = (payload.get("trace")
                         if isinstance(payload, dict) else None)
                if trace:
                    # echo the request's trace id (minted or joined) so
                    # a client can fetch it from /tracez by id
                    self.send_header("x-pt-trace", str(trace))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    code, payload = frontend._handle_get(self.path)
                    self._send(code, payload)
                except BrokenPipeError:
                    # client hung up mid-response; nothing left to write to
                    _resilience.record("frontend_client_disconnects")
                except Exception as e:
                    self._send(500, _error_body(e))

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(length) if length else b""
                    code, payload = frontend._handle_post(
                        self.path, raw, headers=self.headers)
                    self._send(code, payload)
                except BrokenPipeError:
                    # client hung up mid-response; nothing left to write to
                    _resilience.record("frontend_client_disconnects")
                except Exception as e:
                    self._send(500, _error_body(e))

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name=f"pt-frontend-{name}")
        if auto_start:
            self._thread.start()

    # -- request handling ---------------------------------------------------

    def _handle_get(self, path):
        path = path.split("?", 1)[0]
        if path == "/healthz":
            with self._lock:
                draining = self._draining
            code = 503 if draining or self._closed else 200
            return code, {"ok": code == 200, "draining": draining,
                          "frontend": self.name}
        if path == "/routerz":
            stats = getattr(self.backend, "stats", None)
            if stats is None:
                return 404, {"error": "backend has no stats surface"}
            return 200, stats()
        return 404, {"error": f"no such path {path!r}",
                     "paths": ["/healthz", "/routerz", "/v1/generate",
                               "/v1/infer"]}

    def _admit(self):
        """Admission edge shared by every POST: 503 while draining (the
        typed reject the drain contract promises), else count the
        request in flight so drain can wait for open connections."""
        with self._lock:
            if self._draining or self._closed:
                raise ServingOverloadError(
                    f"frontend {self.name!r} is draining — resubmit to "
                    f"another replica group", reason="draining")
            self._inflight += 1
            self._idle.clear()

    def _release(self):
        with self._lock:
            self._inflight -= 1
            if self._inflight <= 0:
                self._idle.set()

    def _handle_post(self, path, raw, headers=None):
        path = path.split("?", 1)[0]
        try:
            body = json.loads(raw.decode() or "{}")
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            return 400, {"error": "BadJSON", "message": str(e)}
        if not isinstance(body, dict):
            return 400, {"error": "BadJSON",
                         "message": "request body must be a JSON object"}
        try:
            self._admit()
        except ServingOverloadError as e:
            return _error_status(e), _error_body(e)
        # the frontend is the trace mint: an `x-pt-trace` request header
        # joins an upstream trace, else a fresh id; the root span rides
        # the thread-local attach through the router into the engines
        span = None
        if path in ("/v1/generate", "/v1/infer"):
            span = _reqtrace.start_request(
                "generate" if path == "/v1/generate" else "infer",
                trace_id=(headers.get("x-pt-trace") if headers else None),
                attrs={"frontend": self.name})
        code, error = None, None
        try:
            with _reqtrace.attach(span):
                if path == "/v1/generate":
                    code, payload = self._generate(body)
                elif path == "/v1/infer":
                    code, payload = self._infer(body)
                else:
                    code, payload = 404, {"error": f"no such path {path!r}"}
        except ServingError as e:
            error = e
            code, payload = _error_status(e), _error_body(e)
        except (ValueError, TypeError, KeyError) as e:
            error = e
            code, payload = 400, _error_body(e)
        except (TimeoutError, concurrent.futures.TimeoutError) as e:
            # 3.10: futures.TimeoutError is NOT the builtin alias yet
            error = e
            code, payload = 504, {"error": "Timeout", "message": str(e)}
        except BaseException as e:
            error = e
            raise
        finally:
            self._release()
            if span is not None:
                if error is None:
                    span.finish("ok", http_status=code)
                else:
                    span.finish("error", error=error,
                                http_status=code if code else 500)
        if span is not None and isinstance(payload, dict):
            payload["trace"] = span.trace_id
        return code, payload

    def _generate(self, body):
        prompt = body.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            raise ValueError("generate: 'prompt' must be a non-empty "
                             "list of token ids")
        max_new = int(body.get("max_new_tokens", 16))
        timeout_s = float(body.get("timeout_s", _DEFAULT_TIMEOUT_S))
        submit = getattr(self.backend, "submit", None)
        if submit is None:
            raise ModelNotLoadedError(
                "backend has no decode surface (submit)")
        t0 = time.monotonic()
        fut = submit(prompt, max_new,
                     eos_id=body.get("eos_id"),
                     tenant=str(body.get("tenant", "default")))
        try:
            tokens = fut.result(timeout=timeout_s)
        except (TimeoutError, concurrent.futures.TimeoutError):
            fut.cancel()
            raise ServingDeadlineError(
                f"generate did not finish within {timeout_s}s")
        return 200, {"tokens": [int(t) for t in tokens],
                     "latency_s": round(time.monotonic() - t0, 6)}

    def _infer(self, body):
        model = body.get("model")
        feed_spec = body.get("feed")
        if not model or not isinstance(feed_spec, dict) or not feed_spec:
            raise ValueError("infer: 'model' and a non-empty 'feed' "
                             "object are required")
        timeout_s = float(body.get("timeout_s", _DEFAULT_TIMEOUT_S))
        tenant = str(body.get("tenant", "default"))
        feed = {str(k): np.asarray(v) for k, v in feed_spec.items()}
        t0 = time.monotonic()
        submit_feed = getattr(self.backend, "submit_feed", None)
        if submit_feed is not None:
            fut = submit_feed(model, feed, tenant=tenant)
        else:
            fut = self.backend.submit(model, feed, tenant=tenant)
        try:
            out = fut.result(timeout=timeout_s)
        except (TimeoutError, concurrent.futures.TimeoutError):
            fut.cancel()
            raise ServingDeadlineError(
                f"infer did not finish within {timeout_s}s")
        return 200, {"outputs": {k: np.asarray(v).tolist()
                                 for k, v in out.items()},
                     "latency_s": round(time.monotonic() - t0, 6)}

    # -- lifecycle / drain --------------------------------------------------

    def start(self):
        if not self._thread.is_alive():
            self._thread.start()
        return self

    def drain(self, timeout=30.0):
        """The SIGTERM drain contract, in order: (1) stop admission —
        new requests get a typed 503; (2) drain every replica engine —
        in-flight batches/sequences finish, queued futures fail typed;
        (3) wait for open HTTP connections to write their responses;
        (4) close the listener.  Engines stay open (the caller snapshots
        / LEAVEs before close).  Idempotent; returns True when the
        in-flight work finished inside `timeout`."""
        with self._lock:
            if self._closed:
                return True
            already = self._draining
            self._draining = True
        deadline = time.monotonic() + max(float(timeout), 0.0)
        if not already:
            for eng in self._engines():
                drain = getattr(eng, "drain", None)
                if drain is None:
                    continue
                remaining = max(deadline - time.monotonic(), 0.0)
                try:
                    drain(timeout=remaining)
                except TypeError:
                    drain()  # Engine.drain() takes no timeout
        ok = self._idle.wait(timeout=max(deadline - time.monotonic(),
                                         0.0))
        self._shutdown_listener()
        return ok

    def _engines(self):
        reps = getattr(self.backend, "replicas", None)
        if reps is not None:
            return [r.engine for r in reps()]
        return [self.backend]

    def _shutdown_listener(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._server.shutdown()
        self._server.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=5)

    def close(self):
        """Immediate teardown (tests / non-drain exits).  The drain
        path calls `_shutdown_listener` itself, last."""
        self._shutdown_listener()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def install_drain(self, timeout=30.0, poll_s=0.1):
        """Chained-DrainHandler path for the frontend process: install
        the process `elastic.DrainHandler` (idempotent — chains any
        previously installed handlers) and a watcher thread that, when
        SIGTERM lands, runs the full drain ordering above and then
        `handler.finish()` — drain marker, handler restore, signal
        re-delivery.  Returns the handler."""
        from paddle_tpu.distributed import elastic

        handler = elastic.install_drain_handler()

        def _watch():
            while not handler.requested.wait(timeout=poll_s):
                with self._lock:
                    if self._closed:
                        return  # frontend closed without a signal
            self.drain(timeout=timeout)
            handler.finish()

        t = threading.Thread(target=_watch, daemon=True,
                             name=f"pt-frontend-drain-{self.name}")
        t.start()
        return handler

    def stats(self):
        with self._lock:
            return {
                "frontend": self.name,
                "host": self.host,
                "port": self.port,
                "draining": self._draining,
                "closed": self._closed,
                "inflight": self._inflight,
            }
