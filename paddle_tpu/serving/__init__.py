"""paddle_tpu.serving — the production serving lane (docs/SERVING.md).

Turns `AnalysisPredictor` (the reference's inference engine, PAPER.md §1)
into a production request path on the TPU compilation model: one XLA
executable per (model signature, bucket shape), variable traffic padded
into a small fixed bucket set so nothing recompiles after warmup.

  batching   shape-bucketed continuous batcher: per-model request
             queues, batch assembly padded to powers-of-two row (and
             optional sequence) buckets, per-request futures
  engine     multi-model Engine: warm executable cache (in-process
             executor cache + FLAGS_compile_cache_dir persistence —
             a restarted server recompiles nothing), bounded-queue
             admission control with typed ServingOverloadError,
             per-tenant request accounting
  status     /servez page on the existing /metricsz endpoint: loaded
             models, bucket set, cache hit rates, p50/p99 latency
  errors     typed serving errors (overload / not-loaded / bad feed)

SLO surfaces ride the observability registry: `pt_serve_request_latency_
seconds{model}`, `pt_serve_batch_size`, `pt_serve_queue_depth`,
`pt_serve_rejected_total`, … (docs/OBSERVABILITY.md).  Flags:
FLAGS_serving_batch_buckets / FLAGS_serving_seq_buckets /
FLAGS_serving_batch_timeout_ms / FLAGS_serving_max_queue.
"""

from . import batching  # noqa: F401
from . import decode  # noqa: F401
from . import drill  # noqa: F401
from . import engine  # noqa: F401
from . import errors  # noqa: F401
from . import frontend  # noqa: F401
from . import kv_pool  # noqa: F401
from . import promote  # noqa: F401
from . import router  # noqa: F401
from . import status  # noqa: F401
from .batching import BucketPolicy
from .decode import DecodeEngine, DecodeRequest
from .engine import Engine, model_signature
from .errors import (FeedValidationError, ModelNotLoadedError,
                     PoolExhaustedError, ServingDeadlineError,
                     ServingError, ServingOverloadError)
from .frontend import Frontend
from .kv_pool import KVPool
# NOTE: the promote() FUNCTION is not re-exported at package level — it
# would shadow the `serving.promote` submodule binding.  Call
# `serving.promote.promote(...)` (or import it from the submodule).
from .promote import PromotionGates, WeightSet, capture_weights
from .router import CircuitBreaker, Replica, Router, routerz_payload
from .status import servez_payload

__all__ = [
    "batching", "decode", "drill", "engine", "errors", "frontend",
    "kv_pool", "promote", "router", "status",
    "Engine", "BucketPolicy", "model_signature", "servez_payload",
    "DecodeEngine", "DecodeRequest", "KVPool",
    "Router", "Replica", "CircuitBreaker", "routerz_payload",
    "Frontend", "WeightSet", "PromotionGates", "capture_weights",
    "ServingError", "ServingOverloadError", "ModelNotLoadedError",
    "FeedValidationError", "ServingDeadlineError", "PoolExhaustedError",
]
