"""/servez — the serving lane's status page on the existing exposition
endpoint (FLAGS_metrics_port).

A process can run several engines; each registers itself here on
construction.  Engines must be `close()`d when done (close unregisters,
joins the scheduler threads, and fails leftover futures); the weak
registration is only a safety net so a LEAKED engine at least drops off
the page — its scheduler threads and model parameters are NOT reclaimed
without close().  The page renders JSON: every live engine's bucket
policy, loaded models, queue depths, executable-cache hit rates,
per-tenant counts, and p50/p99 request latency (PromQL
`histogram_quantile` semantics via `observability.hist_quantile`).

`GET /servez` works on any `MetricsServer` in the process — the one
`FLAGS_metrics_port` started, or an ephemeral `MetricsServer(port=0)`.
"""

from __future__ import annotations

import threading
import weakref

__all__ = ["servez_payload", "track_engine", "untrack_engine",
           "live_engines", "track_decode_engine",
           "untrack_decode_engine", "live_decode_engines"]

_engines = weakref.WeakSet()
_decode_engines = weakref.WeakSet()
_lock = threading.Lock()


def track_engine(engine):
    """Add an engine to the /servez page (called from Engine.__init__)
    and (re-)register the page with the exposition server.  No
    registered-once latch: register_page is an idempotent no-op for the
    same renderer, and a latch would go stale after an
    unregister_page("/servez") — every later engine would then skip
    registration and /servez would 404 for the rest of the process."""
    with _lock:
        _engines.add(engine)
        from paddle_tpu.observability import exposition

        exposition.register_page("/servez", servez_payload)


def untrack_engine(engine):
    with _lock:
        _engines.discard(engine)


def track_decode_engine(engine):
    """Add a decode-lane engine (serving/decode.py DecodeEngine) to the
    /servez page's "decode" section — same registration contract as
    track_engine."""
    with _lock:
        _decode_engines.add(engine)
        from paddle_tpu.observability import exposition

        exposition.register_page("/servez", servez_payload)


def untrack_decode_engine(engine):
    with _lock:
        _decode_engines.discard(engine)


def live_engines():
    """Snapshot of the engines currently tracked (strong refs)."""
    with _lock:
        return list(_engines)


def live_decode_engines():
    """Snapshot of the decode engines currently tracked."""
    with _lock:
        return list(_decode_engines)


def servez_payload():
    """JSON-serializable /servez body: one entry per live engine, plus
    the decode lane's section (slot occupancy, KV-pool figures,
    eviction counts — docs/SERVING.md "Decode lane") and the request-
    trace ring's health (completed/kept/live counts plus trace-derived
    request quantiles — the /tracez summary, docs/OBSERVABILITY.md
    "Request tracing")."""
    from paddle_tpu.observability import reqtrace

    return {"engines": [e.stats() for e in live_engines()],
            "decode": [e.stats() for e in live_decode_engines()],
            "reqtrace": {**reqtrace.ring_stats(),
                         **reqtrace.request_quantiles()}}
