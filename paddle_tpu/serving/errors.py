"""Typed serving-path errors.

The serving lane multiplexes many callers onto shared executables, so
failures must be classifiable at the edge: admission rejection is a
load-shedding signal the client retries with backoff, while a feed error
is a caller bug that must never reach XLA (where it would surface as an
opaque trace/compile failure attributed to the wrong request).
"""

from __future__ import annotations

__all__ = ["ServingError", "ServingOverloadError", "ModelNotLoadedError",
           "FeedValidationError", "ServingDeadlineError",
           "PoolExhaustedError"]


class ServingError(RuntimeError):
    """Base class of every serving-lane error."""


class ServingOverloadError(ServingError):
    """Admission control rejected the request: the model's queue is at
    FLAGS_serving_max_queue, a tenant is over its
    FLAGS_serving_tenant_quota, the engine is draining for a graceful
    SIGTERM, or it is closed.  The typed rejection IS the contract —
    callers shed/retry instead of the engine queueing unboundedly and
    timing out everyone.

    ``reason`` classifies the rejection (``overload`` / ``closed`` /
    ``tenant_quota`` / ``draining`` / ``scheduler_failed``) and matches
    the ``pt_serve_rejected_total{reason}`` label the rejection books —
    a caller's backoff policy can branch on it (a tenant-quota reject
    is per-tenant pressure, not engine-wide overload)."""

    def __init__(self, message, reason="overload"):
        super().__init__(message)
        self.reason = str(reason)


class ModelNotLoadedError(ServingError, KeyError):
    """Request named a model the engine does not serve."""

    def __str__(self):
        # KeyError.__str__ reprs the message (quotes + escapes in every
        # log line); render it like any other error
        return RuntimeError.__str__(self)


class FeedValidationError(ServingError, ValueError):
    """Request feed failed the edge validation (names, dtypes, shapes,
    row consistency) against the model's static program signature."""


class PoolExhaustedError(ServingError, MemoryError):
    """The paged KV pool (serving/kv_pool.py) has no free page for an
    allocation.  Internal to the decode scheduler — it catches this,
    evicts a victim sequence (booked as
    ``pt_decode_evictions_total``) and retries; it only escapes to a
    caller when the pool is sized below one full sequence, which the
    KVPool constructor rejects up front."""


class ServingDeadlineError(ServingError, TimeoutError):
    """The request outlived its per-request deadline
    (FLAGS_serving_deadline_ms / Engine(deadline_ms=...)) while queued
    or in flight; its future resolves with THIS instead of waiting
    forever.  Booked as ``pt_serve_rejected_total{reason="deadline"}``
    — a load-shedding signal like the overload rejection, but measured
    in wall time rather than queue depth."""
