"""FaultPlan-driven serving fault drills (`make serve-drill`,
PT_BENCH_SERVE_DRILL=1).

The PR-14 recovery-drill precedent, applied to serving: every claim the
resilience layer makes is MEASURED here, deterministically, with the
FaultPlan grammar — not asserted from code reading.

  failover_drill      2-replica decode group under closed-loop load; a
                      `replica_kill:` rule murders one scheduler
                      mid-decode; the router fails the victim sequences
                      over and every stream must finish TOKEN-EXACT vs
                      the uninterrupted single-replica baseline (greedy
                      determinism is the contract).  Books
                      pt_serve_failovers_total + pt_serve_recovery_
                      seconds; gates on zero steady-state compile
                      misses across the failover.
  promotion_drill     canary weight promotion over the live group:
                      clean (perturbed weights, gates pass, whole group
                      converges, background traffic sees zero drops —
                      and zero compiles: the swap is arrays-only) and
                      regression (a `serve_error:` rule fails the
                      canary's probe window → auto-rollback restores
                      the old arrays bit-exact).
  hedge_drill         two continuous-batch Engine replicas, one built
                      slow (large batch timeout); hedged requests beat
                      it to the fast replica and the win-rate is
                      recorded.

Each drill returns a plain report dict; `run_drill()` composes them and
`python -m paddle_tpu.serving.drill` prints one JSON report (the bench
rung parses the same shape).

These drills build real engines and compile real (tiny) programs — the
subprocess test wrapper (tests/test_serve_drill.py) runs them in a
fresh child with the persistent compile cache off, the same isolation
tests/decode_e2e_checks.py needs on the brittle jaxlib.
"""

from __future__ import annotations

import threading
import time

import numpy as np

__all__ = ["failover_drill", "promotion_drill", "hedge_drill",
           "run_drill", "main"]

_GPT_CFG = dict(num_layers=2, hidden_dropout=0.0,
                use_flash_attention=False)


def _compile_misses():
    """Total executable-cache misses so far (every path) — the
    zero-compile gates are deltas of this."""
    from paddle_tpu import observability as obs

    fam = (obs.snapshot().get("pt_compile_cache_total") or {})
    return sum(int(v) for k, v in fam.get("samples", {}).items()
               if k[-1] == "miss")


def _recovery_hist(router_name):
    from paddle_tpu import observability as obs

    fam = obs.snapshot().get("pt_serve_recovery_seconds") or {}
    h = fam.get("samples", {}).get((router_name,))
    if not h:
        return {"count": 0, "sum": 0.0}
    return {"count": int(h["count"]), "sum": float(h["sum"])}


def _build_decode_group(n_replicas, *, pool_slots=2, seed=3):
    """One tiny random-init GPT; each replica gets its OWN scope holding
    a copy of the same parameters (a real group has per-replica scopes —
    promotion swaps one replica's arrays at a time) and its own
    DecodeEngine.  Greedy decode over identical weights is identical
    across replicas — the property both drills lean on."""
    from paddle_tpu import fluid, serving
    from paddle_tpu.models import gpt

    cfg = gpt.GPTConfig.tiny(**_GPT_CFG)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        gpt.build_gpt_lm(cfg)
    scope0 = fluid.Scope()
    with fluid.scope_guard(scope0):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
    param_names = [n for n in scope0.keys()
                   if scope0.get(n) is not None]
    scopes, engines = [], []
    for i in range(n_replicas):
        s = fluid.Scope()
        for n in param_names:
            s.set(n, np.array(scope0.get(n)))
        eng = serving.DecodeEngine(
            cfg, scope=s, pool_slots=pool_slots, page_size=4,
            prefill_chunk=4, max_len=32, name=f"replica{i}",
            auto_start=False, drain_on_sigterm=False)
        eng.warmup()
        eng.start()
        scopes.append(s)
        engines.append(eng)
    return cfg, scopes, engines, param_names


def _prompts(cfg, n, plen=4, seed=11):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(1, cfg.vocab_size, plen)) for _ in range(n)]


def failover_drill(n_requests=6, max_new_tokens=8, kill_after=2,
                   timeout_s=300.0, slo_clear_timeout_s=20.0):
    """replica_kill mid-decode under load → router failover, token-exact
    resumed streams, recovery seconds booked, zero compile misses —
    AND the availability SLO's page alert must FIRE during the kill and
    CLEAR after recovery (the drill measures alert latency, not just
    data-path recovery: an outage nobody is paged for is not survived,
    docs/OBSERVABILITY.md "SLOs & burn-rate alerts")."""
    from paddle_tpu.distributed import fault_injection as _fault
    from paddle_tpu.observability import reqtrace as _reqtrace
    from paddle_tpu.observability import slo as _slo
    from paddle_tpu.serving.router import Router

    cfg, _scopes, engines, _names = _build_decode_group(2)
    r0, r1 = engines
    router = None
    # the production spec shape over the production families, with the
    # SRE-workbook page window compressed to drill scale (seconds, not
    # hours): bad = failovers booked by THIS router, total = admitted
    # serving requests
    spec = _slo.parse_spec(
        "drill_availability|availability"
        "|bad=pt_serve_failovers_total{router=drill}"
        "|total=pt_serve_requests_total"
        "|objective=0.999")
    slo_eng = _slo.SLOEngine(
        [spec], windows=(_slo.BurnWindow("page", 1.0, 4.0, 14.4),))
    marks = {"t_kill": None, "t_fired": None, "t_cleared": None}
    stop_poll = threading.Event()

    def _poll_slo():
        # evaluate FIRST, wait after: the kill lands within ~100 ms of
        # submission — a wait-first loop could take its first sample
        # with the failovers already booked, and a window whose every
        # sample is post-failure has zero delta (no fire, ever)
        while True:
            if marks["t_kill"] is None and not r0.healthy():
                marks["t_kill"] = time.monotonic()
            slo_eng.evaluate()
            st = slo_eng.alert_state("drill_availability", "page")
            if st["active"] and marks["t_fired"] is None:
                marks["t_fired"] = time.monotonic()
            if (not st["active"] and marks["t_fired"] is not None
                    and marks["t_cleared"] is None):
                marks["t_cleared"] = time.monotonic()
                return
            if stop_poll.wait(0.02):
                return

    try:
        prompts = _prompts(cfg, n_requests)
        # uninterrupted baseline on replica0 alone (greedy oracle)
        baseline = r0.generate(prompts, max_new_tokens,
                               timeout=timeout_s)
        # arm the mid-decode death: kill replica0's scheduler a couple
        # of decode steps into the loaded run (its step counter kept
        # counting through the baseline)
        kill_step = r0.stats()["steps"] + int(kill_after)
        _fault.install(f"replica_kill:replica0:step:{kill_step}")
        misses_before = _compile_misses()
        router = Router([r0, r1], name="drill", hedge_ms=0,
                        probe_interval_ms=20)
        # pre-kill baseline sample: every burn window needs a healthy
        # base to delta against
        slo_eng.evaluate()
        poller = threading.Thread(target=_poll_slo, daemon=True)
        poller.start()
        t0 = time.monotonic()
        futs = [router.submit(p, max_new_tokens) for p in prompts]
        outs = [f.result(timeout=timeout_s) for f in futs]
        wall_s = time.monotonic() - t0
        t_recovered = time.monotonic()
        # the kill window is over and counters have stopped moving: the
        # short burn window must drain and the alert must CLEAR
        deadline = time.monotonic() + float(slo_clear_timeout_s)
        while marks["t_cleared"] is None and time.monotonic() < deadline:
            time.sleep(0.05)
        stop_poll.set()
        poller.join(timeout=5)
        misses_delta = _compile_misses() - misses_before
        token_exact = outs == baseline
        stats = router.stats()
        rec = _recovery_hist("drill")
        alert = slo_eng.alert_state("drill_availability", "page")
        slo_report = {
            "spec": spec.describe(),
            "alert_fired": marks["t_fired"] is not None,
            "alert_cleared": marks["t_cleared"] is not None,
            "fire_latency_s": round(
                marks["t_fired"] - marks["t_kill"], 3)
            if marks["t_fired"] is not None
            and marks["t_kill"] is not None else None,
            "clear_latency_s": round(
                marks["t_cleared"] - t_recovered, 3)
            if marks["t_cleared"] is not None else None,
            "fired_total": alert["fired_total"],
        }
        # trace-derived per-request quantiles (span tree, not the
        # aggregate histogram): the drill's requests are attributable
        quantiles = _reqtrace.request_quantiles()
        report = {
            "requests": n_requests,
            "max_new_tokens": max_new_tokens,
            "kill_step": kill_step,
            "replica0_died": not r0.healthy(),
            "token_exact": token_exact,
            "failovers": stats["failovers"],
            "recovery": rec,
            "mttr_s": round(rec["sum"] / rec["count"], 6)
            if rec["count"] else None,
            "compile_miss_delta": misses_delta,
            "wall_s": round(wall_s, 3),
            "slo": slo_report,
            "trace_quantiles": quantiles,
        }
        report["ok"] = (token_exact and report["replica0_died"]
                        and stats["failovers"] > 0
                        and rec["count"] > 0 and misses_delta == 0
                        and slo_report["alert_fired"]
                        and slo_report["alert_cleared"])
        return report
    finally:
        stop_poll.set()
        _fault.uninstall()
        if router is not None:
            router.close()
        for eng in engines:
            eng.close()


def promotion_drill(regress=False, n_traffic=4, max_new_tokens=6,
                    probe_count=3, timeout_s=300.0):
    """Canary promotion over a live 2-replica group.  ``regress=False``:
    perturbed weights pass the gates, the whole group converges, the
    background traffic completes with zero drops and the swap performs
    zero compiles.  ``regress=True``: a `serve_error:` rule lands in the
    canary's post-swap probe window → auto-rollback, old arrays restored
    bit-exact."""
    from paddle_tpu.distributed import fault_injection as _fault
    from paddle_tpu.serving import promote as _promote
    from paddle_tpu.serving.router import Router

    cfg, scopes, engines, param_names = _build_decode_group(2)
    router = None
    try:
        router = Router(engines, name="promo", hedge_ms=0,
                        probe_interval_ms=20)
        # the checkpoint being published: the same parameters nudged by
        # a small deterministic delta (a stand-in training delta — large
        # enough that a restored rollback is distinguishable)
        rng = np.random.RandomState(5)
        new_weights = _promote.WeightSet({
            n: np.asarray(scopes[0].get(n))
            + rng.normal(0, 1e-3, np.shape(scopes[0].get(n)))
            .astype(np.asarray(scopes[0].get(n)).dtype)
            for n in param_names})
        probe_prompts = _prompts(cfg, probe_count, seed=23)
        old_sample = {n: np.array(scopes[0].get(n))
                      for n in param_names[:2]}
        if regress:
            # fail the canary's FIRST post-swap probe: per-replica probe
            # counts run baseline (probe_count) then post-swap
            _fault.install(
                f"serve_error:replica0:req:{probe_count + 1}")
        traffic_outs, traffic_errors = [], []

        def _traffic():
            prompts = _prompts(cfg, n_traffic, seed=31)
            futs = [router.submit(p, max_new_tokens) for p in prompts]
            for f in futs:
                try:
                    traffic_outs.append(f.result(timeout=timeout_s))
                except Exception as e:  # surfaced in the report
                    traffic_errors.append(repr(e))

        misses_before = _compile_misses()
        traffic_thread = None
        if not regress:
            # background load proves zero dropped requests across the
            # rolling swap (regress runs un-loaded: router traffic would
            # consume the serve_error count aimed at the probe window)
            traffic_thread = threading.Thread(target=_traffic,
                                              daemon=True)
            traffic_thread.start()
        gates = _promote.PromotionGates(max_error_rate=0.0,
                                        max_latency_ratio=None,
                                        max_drift=None)
        report_p = _promote.promote(
            router, new_weights, probe_prompts=probe_prompts,
            probe_max_new_tokens=4, gates=gates,
            probe_timeout_s=timeout_s)
        if traffic_thread is not None:
            traffic_thread.join(timeout=timeout_s)
        misses_delta = _compile_misses() - misses_before
        restored = all(
            np.array_equal(np.asarray(scopes[0].get(n)), old_sample[n])
            for n in old_sample)
        converged = all(
            np.array_equal(np.asarray(s.get(param_names[0])),
                           new_weights.arrays[param_names[0]])
            for s in scopes)
        report = {
            "mode": "regress" if regress else "clean",
            "outcome": report_p["outcome"],
            "replicas": report_p["replicas"],
            "compile_miss_delta": misses_delta,
            "traffic_completed": len(traffic_outs),
            "traffic_errors": traffic_errors,
            "canary_restored_bit_exact": restored,
            "group_converged": converged,
        }
        if regress:
            report["ok"] = (report_p["outcome"] == "rolled_back"
                            and restored and misses_delta == 0)
        else:
            report["ok"] = (report_p["outcome"] == "promoted"
                            and converged and not traffic_errors
                            and len(traffic_outs) == n_traffic
                            and misses_delta == 0)
        return report
    finally:
        _fault.uninstall()
        if router is not None:
            router.close()
        for eng in engines:
            eng.close()


def hedge_drill(n_requests=12, hedge_ms=30, slow_wait_ms=300,
                timeout_s=120.0):
    """Two continuous-batch Engine replicas serving one model; the
    first is built SLOW (its batcher waits `slow_wait_ms` before
    dispatching) so the hedge timer beats it to the fast replica —
    hedge win-rate measured, not asserted."""
    import shutil
    import tempfile
    import warnings

    from paddle_tpu import fluid, serving
    from paddle_tpu.fluid.executor import Scope, scope_guard
    from paddle_tpu.serving.router import Router

    feature, hidden, classes = 16, 32, 8
    model_dir = tempfile.mkdtemp(prefix="pt_serve_drill_")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[feature], dtype="float32")
        h = fluid.layers.fc(x, size=hidden, act="relu")
        pred = fluid.layers.fc(h, size=classes, act="softmax")
    scope = Scope()
    with scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe,
                                      main_program=main)
    engines, router = [], None
    try:
        with warnings.catch_warnings():
            # both replicas serve model name "m" on purpose (their
            # pt_serve_* series alias — the router is the one caller)
            warnings.simplefilter("ignore")
            for name, wait_ms in (("slow", slow_wait_ms), ("fast", 1)):
                eng = serving.Engine({"m": model_dir},
                                     max_wait_ms=wait_ms,
                                     name=f"hedge-{name}",
                                     auto_start=False)
                eng.warmup()
                eng.start()
                engines.append(eng)
        router = Router(engines, name="hedge", hedge_ms=hedge_ms,
                        probe_interval_ms=50)
        xb = np.arange(feature, dtype=np.float32).reshape(1, feature)
        t0 = time.monotonic()
        outs = [router.infer("m", {"x": xb}, timeout=timeout_s)
                for _ in range(n_requests)]
        wall_s = time.monotonic() - t0
        hedges = router.hedge_stats()
        fired = hedges["win"] + hedges["lose"]
        report = {
            "requests": n_requests,
            "completed": len(outs),
            "hedge_ms": hedge_ms,
            "hedges_fired": fired,
            "hedge_wins": hedges["win"],
            "hedge_win_rate": round(hedges["win"] / fired, 3)
            if fired else None,
            "wall_s": round(wall_s, 3),
        }
        report["ok"] = (len(outs) == n_requests and fired > 0
                        and hedges["win"] > 0)
        return report
    finally:
        shutil.rmtree(model_dir, ignore_errors=True)
        if router is not None:
            router.close()
        for eng in engines:
            eng.close()


def run_drill(include=("failover", "promotion_clean",
                       "promotion_rollback", "hedge")):
    """Compose the serving drills into one report (the `make
    serve-drill` / PT_BENCH_SERVE_DRILL surface)."""
    report = {}
    if "failover" in include:
        report["failover"] = failover_drill()
    if "promotion_clean" in include:
        report["promotion_clean"] = promotion_drill(regress=False)
    if "promotion_rollback" in include:
        report["promotion_rollback"] = promotion_drill(regress=True)
    if "hedge" in include:
        report["hedge"] = hedge_drill()
    report["ok"] = all(r.get("ok") for r in report.values()
                       if isinstance(r, dict))
    return report


def main(argv=None):
    import json
    import sys

    include = tuple(argv) if argv else ("failover", "promotion_clean",
                                        "promotion_rollback", "hedge")
    report = run_drill(include=include)
    print("SERVE_DRILL_RESULT "  # observability: allow — CLI surface
          + json.dumps(report, default=str), flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:] or None))
