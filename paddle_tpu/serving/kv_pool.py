"""Paged KV-cache slot pool — the decode lane's memory allocator.

vLLM-style paged memory for KV caches on the executor's scope model:
the pool is one persistable program var per (layer, K/V) shaped
``[num_pages, page_size, n_heads, head_dim]``, donated by the executor
every step so it updates in place; a sequence's cache is a LIST of page
ids (its page table), not a contiguous slab.  Admission, growth and
eviction therefore move ZERO cache memory — they edit host-side page
lists — and the decode step stays one fixed-shape executable
(models/gpt.py build_gpt_decode_step) no matter how sequences come and
go.

Page 0 is the TRASH page: never allocated, the write target of inactive
decode slots and padded prefill tails.  Readers can't observe it —
paged attention masks every position past a row's own length.

This module is the pure allocator (page lists, free-list reuse,
accounting); scheduling policy — WHO gets evicted under pressure — lives
in `serving/decode.py`.  Freed pages are reused LIFO so the hot pages of
a churning slot stay the same physical pages across steps (cross-step
slot reuse: the steady-state working set stops growing once warm, which
`reused_allocs` makes visible).
"""

from __future__ import annotations

import collections

import numpy as np

from .errors import PoolExhaustedError

__all__ = ["KVPool", "PoolExhaustedError"]

TRASH_PAGE = 0


class KVPool:
    """Host-side page allocator + the device-resident pool vars.

    ``num_pages`` INCLUDES the trash page, so ``num_pages - 1`` pages
    are allocatable; a single sequence needs up to ``max_pages_per_seq``
    of them (the constructor enforces one sequence always fits —
    otherwise eviction could never unblock the allocator)."""

    def __init__(self, num_layers, num_heads, head_dim, num_pages,
                 page_size, max_pages_per_seq, dtype="float32",
                 prefix=None):
        from paddle_tpu.models.gpt import KV_POOL_PREFIX, kv_pool_var_names

        if num_pages - 1 < max_pages_per_seq:
            raise ValueError(
                f"KV pool of {num_pages} pages (1 reserved for trash) "
                f"cannot hold one full sequence of {max_pages_per_seq} "
                f"pages — raise num_pages or lower max_len")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.dtype = dtype
        self.prefix = KV_POOL_PREFIX if prefix is None else prefix
        self.var_names = kv_pool_var_names(self.num_layers, self.prefix)
        if dtype == "int8":
            from paddle_tpu.models.gpt import kv_pool_quant_var_names
            self.quant_var_names = kv_pool_quant_var_names(
                self.num_layers, self.prefix)
        else:
            self.quant_var_names = None
        # LIFO free list: a just-freed page is the next one handed out,
        # so a churning slot's working set stays the same physical pages
        self._free = collections.deque(range(1, self.num_pages))
        self._tables = {}           # seq_id -> [page ids]
        self._ever_used = set()     # pages that have ever been allocated
        self.alloc_total = 0
        self.free_total = 0
        self.reused_allocs = 0      # allocations served by a reused page

    # -- device arrays ------------------------------------------------------

    def install(self, scope):
        """Zero the pool vars into `scope` (idempotent on shape AND
        dtype match — an engine rebuild over a live scope keeps the
        resident pool; a rebuild with a different pool_dtype must NOT,
        or every later write trips the dtype guard blaming the
        payload)."""
        shape = (self.num_pages, self.page_size, self.num_heads,
                 self.head_dim)
        if self.dtype == "int8":
            # dual-int8 pool: hi/lo int8 + per-vector fp32 scale per
            # K/V (docs/KERNELS.md "int8 KV")
            sc_shape = shape[:-1] + (1,)
            for k_names, v_names in self.quant_var_names:
                for hi_n, lo_n, sc_n in (k_names, v_names):
                    for name, shp, dt in ((hi_n, shape, "int8"),
                                          (lo_n, shape, "int8"),
                                          (sc_n, sc_shape, "float32")):
                        cur = scope.get(name)
                        if (cur is None
                                or tuple(np.shape(cur)) != shp
                                or np.asarray(cur).dtype != np.dtype(dt)):
                            scope.set(name, np.zeros(shp, dtype=dt))
            return
        want = np.dtype(self.dtype)
        for kn, vn in self.var_names:
            for name in (kn, vn):
                cur = scope.get(name)
                if (cur is None or tuple(np.shape(cur)) != shape
                        or np.asarray(cur).dtype != want):
                    scope.set(name, np.zeros(shape, dtype=self.dtype))

    # -- modeled bytes ------------------------------------------------------

    def modeled_bytes(self):
        """Modeled device bytes of the resident pool across all layers
        and both K/V — dual-int8 accounting when dtype == 'int8'
        (kernels/primitives/int8.py dual_int8_bytes with a per-head_dim
        scale block), plain dtype-width bytes otherwise."""
        n_vec = self.num_pages * self.page_size * self.num_heads
        n_elems = n_vec * self.head_dim
        per_var = (self._dual_int8_bytes(n_elems)
                   if self.dtype == "int8"
                   else n_elems * np.dtype(self.dtype).itemsize)
        return per_var * 2 * self.num_layers

    def modeled_bytes_fp32(self):
        """The same pool's modeled bytes at fp32 — the denominator of
        the int8 saving claim (bench.py PT_BENCH_RAGGED rung)."""
        n_elems = (self.num_pages * self.page_size * self.num_heads
                   * self.head_dim)
        return n_elems * 4 * 2 * self.num_layers

    def _dual_int8_bytes(self, n_elems):
        from paddle_tpu.kernels import primitives as _prims
        return _prims.dual_int8_bytes(n_elems, self.head_dim)

    # -- allocation ---------------------------------------------------------

    def open_seq(self, seq_id):
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already open")
        self._tables[seq_id] = []

    def ensure_capacity(self, seq_id, n_tokens):
        """Grow `seq_id`'s page table to cover `n_tokens` positions.
        Raises PoolExhaustedError — with the shortfall named — when the
        free list runs dry; the caller (the scheduler) evicts and
        retries."""
        table = self._tables[seq_id]
        need = -(-int(n_tokens) // self.page_size)  # ceil
        if need > self.max_pages_per_seq:
            raise ValueError(
                f"sequence {seq_id!r} needs {need} pages for "
                f"{n_tokens} tokens, above max_pages_per_seq="
                f"{self.max_pages_per_seq}")
        while len(table) < need:
            if not self._free:
                raise PoolExhaustedError(
                    f"KV pool out of pages: sequence {seq_id!r} needs "
                    f"{need - len(table)} more (of {need}) but 0 of "
                    f"{self.num_pages - 1} allocatable pages are free "
                    f"— evict a sequence or grow the pool")
            page = self._free.pop()
            if page in self._ever_used:
                self.reused_allocs += 1
            self._ever_used.add(page)
            self.alloc_total += 1
            table.append(page)
        return table

    def free_seq(self, seq_id):
        """Return every page of `seq_id` to the free list (LIFO)."""
        pages = self._tables.pop(seq_id, [])
        for p in reversed(pages):
            self._free.append(p)
        self.free_total += len(pages)
        return len(pages)

    # -- views --------------------------------------------------------------

    def table(self, seq_id):
        return list(self._tables[seq_id])

    def live_seqs(self):
        return list(self._tables)

    def pages_in_use(self):
        return (self.num_pages - 1) - len(self._free)

    def padded_table(self, seq_id=None):
        """One row of the decode feed: the sequence's page table padded
        with the trash page to max_pages_per_seq (all-trash when
        seq_id is None — the inactive-slot row)."""
        row = np.full(self.max_pages_per_seq, TRASH_PAGE, np.int32)
        if seq_id is not None:
            pages = self._tables[seq_id]
            row[:len(pages)] = pages
        return row

    def stats(self):
        return {
            "pages_total": self.num_pages - 1,
            "pages_in_use": self.pages_in_use(),
            "page_size": self.page_size,
            "live_seqs": len(self._tables),
            "alloc_total": self.alloc_total,
            "free_total": self.free_total,
            "reused_allocs": self.reused_allocs,
        }
