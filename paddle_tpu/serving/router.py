"""Multi-replica serving router (docs/SERVING.md "Resilience").

The decode lane (decode.py) and continuous-batch engine (engine.py) are
single in-process replicas: one scheduler death drops every in-flight
sequence.  This module is the resilience layer on top — a `Router` holds
N replicas per model (any mix of `DecodeEngine` streams and `Engine`
prefill-only lanes) and gives the serving path the fault story training
already has (distributed/resilience.py, PR 3/14):

  least-loaded dispatch   live queue-depth + slot occupancy (the
                          `pt_decode_slot_occupancy` signal) picks the
                          replica per request — no static assignment
  liveness probes         a background probe thread trips a dead
                          replica's breaker even with no traffic
  circuit breaker         consecutive-failure open → cooldown →
                          half-open single probe → close
                          (`pt_serve_breaker_state{replica}`)
  bounded retry           typed admission rejections (overload /
                          tenant_quota / draining) retry with backoff
                          on a `RetryPolicy`; budget exhaustion
                          re-raises the typed error
  hedged requests         idempotent prefill-only calls get a second
                          copy on another replica after
                          FLAGS_serving_hedge_ms (-1 = rolling p99);
                          first result wins, the loser is cancelled
                          (`pt_serve_hedges_total{outcome}`)
  decode failover         a replica death mid-stream (scheduler
                          `_fail_all` fan-out) re-prefills each victim
                          sequence on a surviving replica from its
                          already-emitted prefix (`submit_request
                          (prefix=...)`, the eviction-replay contract)
                          — token-exact under greedy decode, booked on
                          `pt_serve_failovers_total` and
                          `pt_serve_recovery_seconds`

Fault drills: `fault_injection.on_serve(replica)` fires at the dispatch
edge (`serve_error:` / `serve_delay:` rules) and the decode step calls
`on_replica_step` (`replica_kill:` rules), so every behavior above is
exercised deterministically by `serving/drill.py` (`make serve-drill`).

The router is deliberately duck-typed over its replicas: anything with
`submit_request/healthy/load` routes as a decode stream, anything with
`submit(model, feed, tenant)` routes as a stateless engine — the unit
tests drive the state machines with fake replicas, no device needed.
"""

from __future__ import annotations

import collections
import concurrent.futures
import threading
import time
import weakref

from paddle_tpu.observability import reqtrace as _reqtrace

from .errors import (ModelNotLoadedError, ServingDeadlineError,
                     ServingOverloadError)

__all__ = ["Router", "Replica", "CircuitBreaker",
           "BREAKER_CLOSED", "BREAKER_HALF_OPEN", "BREAKER_OPEN",
           "routerz_payload"]


# ---------------------------------------------------------------------------
# metrics (lazy idempotent registration — the observability contract)
# ---------------------------------------------------------------------------


def _m_failovers():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_serve_failovers_total",
        "Decode-sequence failovers: a victim sequence re-prefilled on a "
        "surviving replica from its already-emitted prefix after a "
        "replica death or breaker-open (one per recovered sequence)",
        labels=("router",))


def _m_recovery():
    from paddle_tpu import observability as obs

    return obs.histogram(
        "pt_serve_recovery_seconds",
        "Serving failover recovery time: replica-death detection (the "
        "fanned exception) to the victim sequence re-admitted on a "
        "surviving replica — the serving-side MTTR the drill harness "
        "gates on", labels=("router",))


def _m_hedges():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_serve_hedges_total",
        "Hedged prefill-only requests by outcome: `win` (the hedge "
        "finished first and its result was used) vs `lose` (the "
        "primary finished first; the hedge was cancelled)",
        labels=("router", "outcome"))


def _m_breaker():
    from paddle_tpu import observability as obs

    return obs.gauge(
        "pt_serve_breaker_state",
        "Per-replica circuit-breaker state: 0=closed, 1=half-open "
        "(single probe in flight), 2=open (out of rotation until "
        "FLAGS_serving_breaker_cooldown_ms elapses)",
        labels=("router", "replica"))


def _m_retries():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_serve_router_retries_total",
        "Router-level retries of typed admission rejections "
        "(RetryPolicy backoff; budget exhaustion re-raises the typed "
        "error)", labels=("router",))


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

BREAKER_CLOSED = 0
BREAKER_HALF_OPEN = 1
BREAKER_OPEN = 2

_STATE_NAMES = {BREAKER_CLOSED: "closed", BREAKER_HALF_OPEN: "half_open",
                BREAKER_OPEN: "open"}


class CircuitBreaker:
    """Consecutive-failure circuit breaker (per replica).

    closed --[`failures` consecutive failures]--> open
    open --[`cooldown_ms` elapsed, next allow()]--> half-open
    half-open: exactly ONE probe request passes; its success closes the
    breaker (counters reset), its failure re-opens it (cooldown re-arms).

    `clock` is injectable (monotonic seconds) so the state machine is
    unit-testable without sleeping."""

    def __init__(self, failures=None, cooldown_ms=None, clock=None):
        from paddle_tpu.fluid import flags as _flags

        self.failures = int(_flags.flag("serving_breaker_failures")
                            if failures is None else failures)
        self.cooldown_s = float(
            _flags.flag("serving_breaker_cooldown_ms")
            if cooldown_ms is None else cooldown_ms) / 1000.0
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._consecutive = 0
        self._opened_at = None
        self._probe_in_flight = False

    @property
    def state(self):
        with self._lock:
            self._maybe_half_open()
            return self._state

    def state_name(self):
        return _STATE_NAMES[self.state]

    def _maybe_half_open(self):
        # caller holds the lock
        if (self._state == BREAKER_OPEN
                and self._clock() - self._opened_at >= self.cooldown_s):
            self._state = BREAKER_HALF_OPEN
            self._probe_in_flight = False

    def allow(self):
        """May a request be dispatched through this breaker right now?
        In half-open state only the first caller gets True (the single
        probe); everyone else waits for its verdict."""
        with self._lock:
            self._maybe_half_open()
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_HALF_OPEN \
                    and not self._probe_in_flight:
                self._probe_in_flight = True
                return True
            return False

    def record_success(self):
        with self._lock:
            self._state = BREAKER_CLOSED
            self._consecutive = 0
            self._opened_at = None
            self._probe_in_flight = False

    def record_failure(self):
        with self._lock:
            self._maybe_half_open()
            if self._state == BREAKER_HALF_OPEN:
                # the probe failed: straight back to open, cooldown re-arms
                self._trip_locked()
                return
            self._consecutive += 1
            if self._consecutive >= self.failures:
                self._trip_locked()

    def trip(self):
        """Force-open (the liveness probe's verdict on a dead replica —
        no point counting N failures against a corpse)."""
        with self._lock:
            self._trip_locked()

    def _trip_locked(self):
        self._state = BREAKER_OPEN
        self._consecutive = self.failures
        self._opened_at = self._clock()
        self._probe_in_flight = False


# ---------------------------------------------------------------------------
# replica wrapper
# ---------------------------------------------------------------------------


class Replica:
    """One engine in the rotation: the engine itself, its breaker, and
    the duck-typed kind (`decode` = streaming `submit_request` surface,
    `engine` = stateless `submit(model, feed)` surface)."""

    __slots__ = ("name", "engine", "breaker", "kind", "held")

    def __init__(self, engine, breaker):
        self.engine = engine
        self.name = getattr(engine, "name", repr(engine))
        self.breaker = breaker
        self.kind = ("decode" if hasattr(engine, "submit_request")
                     else "engine")
        # held = administratively out of rotation (canary promotion's
        # quiesce/swap window) — orthogonal to breaker state
        self.held = False

    def healthy(self):
        probe = getattr(self.engine, "healthy", None)
        if probe is not None:
            return bool(probe())
        # continuous-batch Engine: closed is the only dead state its
        # surface exposes (lane scheduler errors fail futures typed)
        return not getattr(self.engine, "_closed", False)

    def load(self):
        probe = getattr(self.engine, "load", None)
        if probe is not None:
            return int(probe())
        lanes = getattr(self.engine, "_lanes", None)
        if lanes:
            return sum(len(lane._queue) for lane in list(lanes.values()))
        return 0

    def available(self):
        return not self.held and self.healthy() and self.breaker.allow()


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------

_live_routers = weakref.WeakSet()
_track_lock = threading.Lock()
_page_registered = False


def routerz_payload():
    """The /routerz page: every live router's replica table + counters."""
    with _track_lock:
        routers = list(_live_routers)
    return {"routers": [r.stats() for r in routers]}


def _track(router):
    global _page_registered
    with _track_lock:
        _live_routers.add(router)
        if not _page_registered:
            from paddle_tpu.observability import exposition

            exposition.register_page("/routerz", routerz_payload)
            _page_registered = True


def _untrack(router):
    with _track_lock:
        _live_routers.discard(router)


class Router:
    """N-replica front for one model's serving group.

    ``replicas``: engines to enroll (more via `add_replica`).  The
    router does NOT own replica lifecycle — `close()` stops the probe
    thread and unregisters the router, the engines keep running (the
    drill harness / frontend own their shutdown ordering).

    ``retry``: a `distributed.resilience.RetryPolicy` for typed
    admission rejections (default: the FLAGS_rpc_retry_* policy).
    ``hedge_ms``/``breaker_*``: override the FLAGS_serving_* defaults.
    """

    # ServingOverloadError reasons worth retrying on another replica /
    # after backoff; everything else is either fatal to the request
    # (deadline, validation) or fatal to the replica (handled as death)
    _RETRYABLE = ("overload", "tenant_quota", "draining", "closed")
    _DEATH = ("scheduler_failed",)

    def __init__(self, replicas=(), *, name="router", retry=None,
                 hedge_ms=None, breaker_failures=None,
                 breaker_cooldown_ms=None, probe_interval_ms=100,
                 auto_probe=True):
        from paddle_tpu.fluid import flags as _flags

        self.name = name
        self._breaker_failures = breaker_failures
        self._breaker_cooldown_ms = breaker_cooldown_ms
        if retry is None:
            from paddle_tpu.distributed.resilience import RetryPolicy

            retry = RetryPolicy()
        self.retry = retry
        self._hedge_ms = int(_flags.flag("serving_hedge_ms")
                             if hedge_ms is None else hedge_ms)
        self._lock = threading.Lock()
        self._replicas = []
        self._latencies = collections.deque(maxlen=256)
        self._failovers = 0
        self._hedges = {"win": 0, "lose": 0}
        self._retries = 0
        self._closed = False
        self._bind_metrics()
        for eng in replicas:
            self.add_replica(eng)
        self._probe_interval_s = max(probe_interval_ms, 1) / 1000.0
        self._stop = threading.Event()
        self._probe_thread = None
        if auto_probe:
            self._probe_thread = threading.Thread(
                target=self._probe_loop, daemon=True,
                name=f"pt-router-probe-{name}")
            self._probe_thread.start()
        _track(self)

    # -- metrics ------------------------------------------------------------

    def _bind_metrics(self):
        from paddle_tpu import observability as obs

        self._metrics_epoch = obs.REGISTRY.epoch
        r = self.name
        self._failover_ctr = _m_failovers().labels(router=r)
        self._recovery_hist = _m_recovery().labels(router=r)
        self._hedge_ctr = {o: _m_hedges().labels(router=r, outcome=o)
                           for o in ("win", "lose")}
        self._retry_ctr = _m_retries().labels(router=r)

    def _check_metrics_epoch(self):
        from paddle_tpu import observability as obs

        if self._metrics_epoch != obs.REGISTRY.epoch:
            self._bind_metrics()

    # -- membership ---------------------------------------------------------

    def add_replica(self, engine, breaker=None):
        """Enroll one engine (DecodeEngine or Engine duck-alike) in the
        rotation.  Returns the `Replica` wrapper."""
        if breaker is None:
            breaker = CircuitBreaker(
                failures=self._breaker_failures,
                cooldown_ms=self._breaker_cooldown_ms)
        rep = Replica(engine, breaker)
        with self._lock:
            if any(r.name == rep.name for r in self._replicas):
                raise ValueError(
                    f"router {self.name!r}: replica name {rep.name!r} "
                    f"already enrolled (names key the breaker gauge and "
                    f"fault rules — keep them distinct)")
            self._replicas.append(rep)
        return rep

    def replicas(self, kind=None):
        with self._lock:
            reps = list(self._replicas)
        return [r for r in reps if kind is None or r.kind == kind]

    def set_held(self, name, held=True):
        """Administratively pull a replica from (or return it to) the
        rotation — the promotion quiesce/swap window.  Raises KeyError
        on an unknown name."""
        for rep in self.replicas():
            if rep.name == name:
                rep.held = bool(held)
                return rep
        raise KeyError(f"router {self.name!r}: no replica {name!r}")

    def close(self):
        """Stop the probe thread and unregister.  Replica engines are
        NOT closed — the caller owns their drain/close ordering
        (frontend.py does drain-then-close on SIGTERM)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
        _untrack(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- liveness probe -----------------------------------------------------

    def _probe_loop(self):
        while not self._stop.wait(self._probe_interval_s):
            try:
                self.probe_once()
            except Exception:
                # the probe is advisory: a scrape racing a replica
                # teardown must not kill the probe thread
                from paddle_tpu.distributed import resilience

                resilience.record("router_probe_errors")

    def probe_once(self):
        """One liveness sweep: trip the breaker of every dead replica
        (so it leaves rotation even with no traffic) and publish the
        per-replica breaker-state gauge."""
        self._check_metrics_epoch()
        gauge = _m_breaker()
        for rep in self.replicas():
            if not rep.healthy() and rep.breaker.state != BREAKER_OPEN:
                rep.breaker.trip()
            gauge.labels(router=self.name,
                         replica=rep.name).set(rep.breaker.state)

    # -- selection ----------------------------------------------------------

    def _pick(self, kind, exclude=()):
        """Least-loaded available replica of `kind`, or None."""
        best = None
        best_load = None
        for rep in self.replicas(kind):
            if rep.name in exclude or not rep.available():
                continue
            load = rep.load()
            if best is None or load < best_load:
                best, best_load = rep, load
        return best

    def _no_replica(self, kind):
        reps = self.replicas(kind)
        if not reps:
            raise ModelNotLoadedError(
                f"router {self.name!r} has no {kind} replicas enrolled")
        return ServingOverloadError(
            f"router {self.name!r}: no available {kind} replica "
            f"({len(reps)} enrolled, all dead or breaker-open) — retry "
            f"with backoff", reason="overload")

    # -- decode lane (streaming, failover) ----------------------------------

    def submit(self, prompt, max_new_tokens, eos_id=None,
               tenant="default"):
        """Route one greedy generation to the least-loaded decode
        replica; returns a Future resolving to the generated ids.  On
        replica death mid-stream the sequence fails over: a surviving
        replica re-prefills prompt + emitted prefix and the stream
        resumes token-exact (greedy).  Typed admission rejections retry
        with backoff on the RetryPolicy; budget exhaustion re-raises
        the typed error."""
        outer = concurrent.futures.Future()
        tspan = self._root_span(outer, "generate")
        self._dispatch_decode(outer, list(prompt), int(max_new_tokens),
                              eos_id, tenant, prefix=[], attempt=0,
                              failovers=0, t_detect=None, tspan=tspan)
        return outer

    def _root_span(self, outer, name):
        """The request's trace root: the caller's current span (the
        Frontend attached one) — else mint a fresh trace here (direct
        Router callers) whose root finishes when `outer` resolves."""
        tspan = _reqtrace.current_span()
        if tspan is not None:
            return tspan
        tspan = _reqtrace.start_request(name,
                                        attrs={"router": self.name})
        if tspan is not None:
            outer.add_done_callback(
                lambda f, s=tspan: _reqtrace.finish_future(s, f))
        return tspan

    def generate(self, prompts, max_new_tokens, eos_id=None,
                 timeout=None):
        futs = [self.submit(p, max_new_tokens, eos_id=eos_id)
                for p in prompts]
        return [f.result(timeout=timeout) for f in futs]

    def _dispatch_decode(self, outer, prompt, max_new_tokens, eos_id,
                         tenant, prefix, attempt, failovers, t_detect,
                         tspan=None):
        from paddle_tpu.distributed import fault_injection as _fault

        if outer.cancelled():
            return
        tried = set()  # replicas that failed THIS dispatch attempt
        while True:
            rep = self._pick("decode", exclude=tried)
            if rep is None:
                self._retry_or_fail(
                    outer, self._no_replica("decode"), attempt,
                    lambda a: self._dispatch_decode(
                        outer, prompt, max_new_tokens, eos_id, tenant,
                        prefix, a, failovers, t_detect, tspan=tspan))
                return
            # one attempt span per dispatch: retries/failovers each get
            # their own child, so the trace enumerates every replica the
            # request touched (the Dapper attempt story)
            att = _reqtrace.start_span(
                f"dispatch:{rep.name}", kind="attempt", parent=tspan,
                attrs={"replica": rep.name, "attempt": attempt,
                       "failovers": failovers,
                       "resumed": bool(prefix)})
            try:
                _fault.on_serve(rep.name)
                with _reqtrace.attach(att):
                    req = rep.engine.submit_request(
                        prompt, max_new_tokens, eos_id=eos_id,
                        tenant=tenant, prefix=prefix or None)
            except ServingOverloadError as e:
                if att is not None:
                    att.finish("error", error=e)
                if e.reason in self._DEATH:
                    rep.breaker.record_failure()
                    tried.add(rep.name)
                    continue  # dead replica: try another immediately
                self._retry_or_fail(
                    outer, e, attempt,
                    lambda a: self._dispatch_decode(
                        outer, prompt, max_new_tokens, eos_id, tenant,
                        prefix, a, failovers, t_detect, tspan=tspan))
                return
            except _fault.FaultInjected as e:
                if att is not None:
                    att.finish("error", error=e)
                rep.breaker.record_failure()
                tried.add(rep.name)
                continue  # injected dispatch-edge failure: next replica
            if t_detect is not None:
                # a failover just completed re-admission: book the
                # detection → resumed window
                self._recovery_hist.observe(
                    max(time.monotonic() - t_detect, 0.0))
                t_detect = None
            self._watch_decode(outer, rep, req, prompt, max_new_tokens,
                               eos_id, tenant, failovers, tspan=tspan,
                               att=att)
            return

    def _watch_decode(self, outer, rep, req, prompt, max_new_tokens,
                      eos_id, tenant, failovers, tspan=None, att=None):
        t_submit = time.monotonic()

        def _done(fut):
            _reqtrace.finish_future(att, fut)
            exc = fut.exception()
            if exc is None:
                rep.breaker.record_success()
                self._latencies.append(time.monotonic() - t_submit)
                if outer.set_running_or_notify_cancel():
                    outer.set_result(fut.result())
                return
            if isinstance(exc, ServingDeadlineError):
                # the request's own budget ran out — failing over would
                # just miss the deadline on another replica
                if outer.set_running_or_notify_cancel():
                    outer.set_exception(exc)
                return
            if isinstance(exc, ServingOverloadError) \
                    and exc.reason not in self._DEATH:
                # typed back-pressure surfaced after queueing (drain
                # flush, close): retry the whole request elsewhere —
                # nothing was emitted, so there is no prefix to carry
                self._retry_or_fail(
                    outer, exc, 0,
                    lambda a: self._dispatch_decode(
                        outer, prompt, max_new_tokens, eos_id, tenant,
                        list(req.generated), a, failovers, None,
                        tspan=tspan))
                return
            # death class: the scheduler fanned a fatal error to every
            # live future.  Fail this sequence over to a survivor,
            # resuming from the prefix already emitted.
            rep.breaker.record_failure()
            t_detect = time.monotonic()
            if failovers + 1 >= max(len(self.replicas("decode")), 1) + 1:
                if outer.set_running_or_notify_cancel():
                    outer.set_exception(exc)
                return
            self._failover_ctr.inc()
            with self._lock:
                self._failovers += 1
            self._dispatch_decode(
                outer, prompt, max_new_tokens, eos_id, tenant,
                list(req.generated), 0, failovers + 1, t_detect,
                tspan=tspan)

        req.future.add_done_callback(_done)

    # -- stateless lane (prefill-only, hedging) -----------------------------

    def submit_feed(self, model, feed, tenant="default"):
        """Route one stateless inference (the continuous-batch Engine
        lane) to the least-loaded engine replica, hedging to a second
        replica after the hedge delay (FLAGS_serving_hedge_ms; -1 arms
        from the rolling p99).  First result wins; the loser is
        cancelled.  Idempotent calls only — a hedged request may
        execute on BOTH replicas."""
        outer = concurrent.futures.Future()
        tspan = self._root_span(outer, "infer")
        self._dispatch_feed(outer, model, feed, tenant, attempt=0,
                            tspan=tspan)
        return outer

    def infer(self, model, feed, tenant="default", timeout=None):
        return self.submit_feed(model, feed, tenant=tenant).result(
            timeout=timeout)

    def _hedge_delay_s(self):
        if self._hedge_ms == 0:
            return None
        if self._hedge_ms > 0:
            return self._hedge_ms / 1000.0
        lat = sorted(self._latencies)
        if not lat:
            return None  # adaptive with no history yet: no hedge
        return max(lat[int(0.99 * (len(lat) - 1))], 0.001)

    def _dispatch_feed(self, outer, model, feed, tenant, attempt,
                       tspan=None):
        from paddle_tpu.distributed import fault_injection as _fault

        if outer.cancelled():
            return
        tried = set()
        while True:
            primary = self._pick("engine", exclude=tried)
            if primary is None:
                self._retry_or_fail(
                    outer, self._no_replica("engine"), attempt,
                    lambda a: self._dispatch_feed(outer, model, feed,
                                                  tenant, a, tspan=tspan))
                return
            att = _reqtrace.start_span(
                f"dispatch:{primary.name}", kind="attempt", parent=tspan,
                attrs={"replica": primary.name, "attempt": attempt,
                       "hedge": False})
            try:
                _fault.on_serve(primary.name)
                with _reqtrace.attach(att):
                    fut = primary.engine.submit(model, feed,
                                                tenant=tenant)
            except ServingOverloadError as e:
                if att is not None:
                    att.finish("error", error=e)
                if e.reason in self._DEATH:
                    primary.breaker.record_failure()
                    tried.add(primary.name)
                    continue
                self._retry_or_fail(
                    outer, e, attempt,
                    lambda a: self._dispatch_feed(outer, model, feed,
                                                  tenant, a, tspan=tspan))
                return
            except _fault.FaultInjected as e:
                if att is not None:
                    att.finish("error", error=e)
                primary.breaker.record_failure()
                tried.add(primary.name)
                continue
            break
        t0 = time.monotonic()
        state = {"winner": None, "errors": [], "branches": 1,
                 "hedged": False, "timer": None,
                 "futs": {"primary": fut},
                 "spans": {"primary": att}}
        lock = threading.Lock()

        def _finish(which, rep, f):
            """First successful branch wins outer; a branch error waits
            for the other branch before propagating; cancellation (the
            hedge loser) just retires its branch."""
            _reqtrace.finish_future(state["spans"].get(which), f)
            with lock:
                if state["winner"] is not None:
                    return
                if f.cancelled():
                    state["branches"] -= 1
                    if state["branches"] > 0 or not state["errors"]:
                        return
                    last_rep, last_exc = state["errors"][-1]
                elif f.exception() is None:
                    state["winner"] = which
                    if state["timer"] is not None:
                        state["timer"].cancel()
                    if state["hedged"]:
                        outcome = "win" if which == "hedge" else "lose"
                        self._hedge_ctr[outcome].inc()
                        self._hedges[outcome] += 1
                    loser = ("hedge" if which == "primary" else "primary")
                    to_cancel = state["futs"].get(loser)
                    loser_span = state["spans"].get(loser)
                    last_exc = None
                else:
                    exc = f.exception()
                    state["errors"].append((rep, exc))
                    state["branches"] -= 1
                    if not isinstance(exc, ServingOverloadError) \
                            or exc.reason in self._DEATH:
                        rep.breaker.record_failure()
                    if state["branches"] > 0:
                        return
                    last_rep, last_exc = state["errors"][-1]
            if last_exc is None:
                rep.breaker.record_success()
                self._latencies.append(time.monotonic() - t0)
                if to_cancel is not None and not to_cancel.done():
                    to_cancel.cancel()
                if loser_span is not None:
                    # the loser loses even if the engine can no longer
                    # abort it: the trace records who was discarded
                    loser_span.finish("cancelled")
                if outer.set_running_or_notify_cancel():
                    outer.set_result(f.result())
                return
            # every branch failed: typed back-pressure retries with
            # backoff, anything else propagates
            if isinstance(last_exc, ServingOverloadError) \
                    and last_exc.reason not in self._DEATH:
                self._retry_or_fail(
                    outer, last_exc, 0,
                    lambda a: self._dispatch_feed(outer, model, feed,
                                                  tenant, a, tspan=tspan))
                return
            if outer.set_running_or_notify_cancel():
                outer.set_exception(last_exc)

        def _fire_hedge():
            with lock:
                if state["winner"] is not None:
                    return
            hedge_rep = self._pick("engine", exclude=(primary.name,))
            if hedge_rep is None:
                return
            hatt = _reqtrace.start_span(
                f"dispatch:{hedge_rep.name}", kind="attempt",
                parent=tspan,
                attrs={"replica": hedge_rep.name, "attempt": attempt,
                       "hedge": True})
            try:
                _fault.on_serve(hedge_rep.name)
                with _reqtrace.attach(hatt):
                    hfut = hedge_rep.engine.submit(model, feed,
                                                   tenant=tenant)
            except Exception as e:
                if hatt is not None:
                    hatt.finish("error", error=e)
                return  # the primary is still in flight; hedge is optional
            with lock:
                if state["winner"] is not None:
                    hfut.cancel()
                    if hatt is not None:
                        hatt.finish("cancelled")
                    return
                state["hedged"] = True
                state["branches"] += 1
                state["futs"]["hedge"] = hfut
                state["spans"]["hedge"] = hatt
            hfut.add_done_callback(
                lambda f: _finish("hedge", hedge_rep, f))

        delay = self._hedge_delay_s()
        if delay is not None and len(self.replicas("engine")) > 1:
            timer = threading.Timer(delay, _fire_hedge)
            timer.daemon = True
            with lock:
                state["timer"] = timer
            timer.start()
        fut.add_done_callback(lambda f: _finish("primary", primary, f))

    # -- retry machinery ----------------------------------------------------

    def _retry_or_fail(self, outer, exc, attempt, redispatch):
        """Typed-rejection path: schedule `redispatch(attempt+1)` after
        the RetryPolicy backoff, or fail `outer` with the typed error
        once the budget is spent."""
        if not self.retry.should_retry(attempt) or self._closed:
            if outer.set_running_or_notify_cancel():
                outer.set_exception(exc)
            return
        self._retry_ctr.inc()
        with self._lock:
            self._retries += 1
        timer = threading.Timer(self.retry.delay(attempt),
                                lambda: redispatch(attempt + 1))
        timer.daemon = True
        timer.start()

    # -- introspection ------------------------------------------------------

    def hedge_stats(self):
        with self._lock:
            return dict(self._hedges)

    def stats(self):
        """The /routerz payload row for this router."""
        reps = []
        for rep in self.replicas():
            reps.append({
                "name": rep.name,
                "kind": rep.kind,
                "healthy": rep.healthy(),
                "load": rep.load(),
                "breaker": rep.breaker.state_name(),
            })
        with self._lock:
            return {
                "router": self.name,
                "replicas": reps,
                "failovers": self._failovers,
                "hedges": dict(self._hedges),
                "retries": self._retries,
                "hedge_ms": self._hedge_ms,
                "retry_times": self.retry.times,
            }
