"""Inference engine: AnalysisPredictor-style serving API.

Reference: paddle/fluid/inference/ — `AnalysisPredictor`
(api/analysis_predictor.h:46) loads a saved ProgramDesc + params, runs the
Analyzer fusion-pass pipeline, then serves through a NaiveExecutor with
ZeroCopyTensor inputs/outputs (:68); TensorRT/Anakin/nGraph subgraphs offload
pieces of the graph (analysis/ir_pass_manager.cc).

TPU-native redesign: the "engine subgraph offload" side-path of the
reference IS this framework's main path — the whole pruned inference program
compiles to one XLA executable, cached per input-shape signature, with
parameters resident on device across calls (the ZeroCopyRun property: no
per-call weight transfer; only inputs/outputs cross the host boundary).
Fusion passes are XLA's job.  `config.switch_ir_optim` etc. are accepted for
API parity but have no separate pass pipeline to toggle.
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["AnalysisConfig", "AnalysisPredictor", "PaddleTensor",
           "PaddleDType", "create_paddle_predictor", "ZeroCopyTensor",
           "check_feed_against_var"]


def _resolve_np_dtype(dtype):
    """np.dtype for a framework dtype (string or proto enum int),
    resolving ml_dtypes extension floats (bfloat16) via the shared
    ops.common helper — None when unresolvable."""
    try:
        from paddle_tpu.ops.common import np_dtype

        return np.dtype(np_dtype(dtype))
    except Exception:
        return None


def _dtype_kind(dt):
    """numpy kind char, with ml_dtypes extension floats (bfloat16,
    float8* — numpy kind 'V') reported as 'f'.  A true void/structured
    dtype stays 'V' (np.finfo rejects it)."""
    if dt.kind == "V":
        try:
            import ml_dtypes

            ml_dtypes.finfo(dt)
            return "f"
        except Exception:
            pass
    return dt.kind


def check_feed_against_var(name, arr, var, error_cls=ValueError):
    """Cheap edge validation of a feed array against the program's static
    var: rank and every fixed dim must match, and the dtype KIND must
    match (width differences — float64→float32, int64→int32 — are safe,
    the executor coerces them like the reference feed path).  `var=None`
    (no static info) passes.

    The serving lane multiplexes many callers onto one compiled
    executable, so a bad feed must fail HERE with the caller's name on
    it, not inside XLA attributed to whoever shares the batch."""
    if var is None:
        return
    arr = np.asarray(arr)
    # shape None = no static info; shape () is a GENUINE scalar var and
    # still gets the rank check (a (4, 8) feed against it must fail
    # here, not deep in XLA)
    if var.shape is not None:
        want = tuple(var.shape)
        if arr.ndim != len(want):
            raise error_cls(
                f"feed {name!r}: rank {arr.ndim} array {tuple(arr.shape)} "
                f"does not match the program's static shape {list(want)}")
        for axis, (got, exp) in enumerate(zip(arr.shape, want)):
            if exp >= 0 and int(got) != int(exp):
                raise error_cls(
                    f"feed {name!r}: shape {tuple(arr.shape)} does not "
                    f"match the program's static shape {list(want)} "
                    f"(dim {axis}: got {got}, expected {exp})")
    # "is not None"/"!= ''" rather than truthiness: the proto enum for
    # bool is 0, and `if var.dtype:` would silently skip validating it
    if var.dtype is not None and var.dtype != "":
        want_dtype = _resolve_np_dtype(var.dtype)
        if want_dtype is None:
            return  # unresolvable dtype: executor coerces
        got_kind, want_kind = _dtype_kind(arr.dtype), _dtype_kind(want_dtype)
        if got_kind != want_kind:
            raise error_cls(
                f"feed {name!r}: dtype {arr.dtype} is not "
                f"{var.dtype}-compatible (kind {got_kind!r} vs "
                f"{want_kind!r}) — cast at the caller")


class PaddleDType:
    FLOAT32 = "float32"
    INT64 = "int64"
    INT32 = "int32"


class PaddleTensor:
    """Input/output container for the non-zero-copy `run` API
    (reference api/paddle_api.h PaddleTensor)."""

    def __init__(self, data=None, name="", lod=None):
        arr = np.asarray(data) if data is not None else None
        self.name = name
        self.data = arr
        self.dtype = str(arr.dtype) if arr is not None else None
        self.shape = list(arr.shape) if arr is not None else []
        self.lod = lod or []

    def as_ndarray(self):
        return self.data


class AnalysisConfig:
    """Reference api/paddle_analysis_config.h.  Device toggles map to
    Places; pass/engine switches are parity no-ops (XLA compiles and fuses
    the whole graph unconditionally)."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        self._model_dir = model_dir
        self._prog_file = prog_file
        self._params_file = params_file
        self._use_tpu = True
        self._ir_optim = True
        self._enable_memory_optim = False
        self._quantizer_enabled = False
        self._quantizer_config = None

    def set_model(self, model_dir, params_file=None):
        if params_file is None:
            self._model_dir = model_dir
        else:
            self._prog_file = model_dir
            self._params_file = params_file

    def model_dir(self):
        return self._model_dir

    def disable_gpu(self):
        self._use_tpu = False

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # GPU knob accepted for source compatibility; device is the TPU
        self._use_tpu = True

    def switch_ir_optim(self, x=True):
        self._ir_optim = bool(x)

    def enable_memory_optim(self):
        self._enable_memory_optim = True

    def switch_use_feed_fetch_ops(self, x=True):
        pass

    def switch_specify_input_names(self, x=True):
        pass

    # -- post-training int8 quantization (reference EnableMkldnnQuantizer,
    #    inference/api/mkldnn_quantizer.cc) ------------------------------
    def enable_quantizer(self):
        """Calibrate on warmup data at predictor build, then run the
        int8-QDQ rewritten program (fluid/contrib/ptq.py)."""
        from paddle_tpu.fluid.contrib.ptq import PTQConfig

        self._quantizer_enabled = True
        if self._quantizer_config is None:
            self._quantizer_config = PTQConfig()
        return self._quantizer_config

    # reference spelling
    enable_mkldnn_quantizer = enable_quantizer

    def quantizer_enabled(self):
        return self._quantizer_enabled

    mkldnn_quantizer_enabled = quantizer_enabled

    def quantizer_config(self):
        """Pure accessor (the reference's mkldnn_quantizer_config never
        enables quantization as a side effect)."""
        from paddle_tpu.fluid.contrib.ptq import PTQConfig

        if self._quantizer_config is None:
            self._quantizer_config = PTQConfig()
        return self._quantizer_config

    mkldnn_quantizer_config = quantizer_config


class ZeroCopyTensor:
    """Named handle onto a predictor slot (reference ZeroCopyTensor):
    copy_from_cpu stages the next run's input; copy_to_cpu reads the last
    run's output without an extra staging buffer on the Python side."""

    def __init__(self, predictor, name, is_input):
        self._pred = predictor
        self.name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr):
        if not self._is_input:
            raise ValueError(f"{self.name} is an output tensor")
        arr = np.ascontiguousarray(arr)
        # fail bad feeds at the edge (dtype kind / rank / fixed dims)
        # instead of inside XLA — serving multiplexes many callers
        var = self._pred._program.global_block()._find_var_recursive(
            self.name)
        check_feed_against_var(self.name, arr, var)
        self._pred._staged[self.name] = arr

    def copy_to_cpu(self):
        store = self._pred._staged if self._is_input else self._pred._outputs
        if self.name not in store:
            raise RuntimeError(
                f"tensor {self.name!r} has no value yet — "
                + ("copy_from_cpu() first" if self._is_input
                   else "call zero_copy_run() first"))
        return np.asarray(store[self.name])

    def shape(self):
        store = self._pred._staged if self._is_input else self._pred._outputs
        if self.name in store:
            return list(np.shape(store[self.name]))
        # not materialized yet: report the static shape from the program
        var = self._pred._program.global_block()._find_var_recursive(self.name)
        if var is not None and var.shape is not None:
            return list(var.shape)
        raise RuntimeError(f"tensor {self.name!r} has no value or static "
                           f"shape yet")


class AnalysisPredictor:
    def __init__(self, config: AnalysisConfig):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid.executor import Scope, scope_guard

        self._config = config
        place = fluid.TPUPlace(0) if config._use_tpu else fluid.CPUPlace()
        self._scope = Scope()
        self._exe = fluid.Executor(place)
        with scope_guard(self._scope):
            if config._model_dir:
                prog, feeds, fetches = fluid.io.load_inference_model(
                    config._model_dir, self._exe)
            else:
                dirname = os.path.dirname(config._prog_file) or "."
                prog, feeds, fetches = fluid.io.load_inference_model(
                    dirname, self._exe,
                    model_filename=os.path.basename(config._prog_file),
                    params_filename=(os.path.basename(config._params_file)
                                     if config._params_file else None))
        fetch_names = [v.name if hasattr(v, "name") else v
                       for v in fetches]
        # graph-optimization passes (FLAGS_graph_passes) on the LOADED
        # program — the serving path motivation: an exported inference
        # program built from the plain layers API gets the fused
        # attention/FFN kernels without a model-level opt-in.  The known
        # fetch list pins keep_vars, so applying here (rather than at
        # first Executor.run) can never fuse a fetch target away.
        from paddle_tpu import passes as _graph_passes

        _graph_passes.apply_graph_passes(prog, lane="serving",
                                         keep_vars=fetch_names)
        if getattr(config, "_ir_optim", True):
            # kernel fusion is XLA's job, but program-level rewrites that
            # still pay (smaller op graphs to trace) run here, mirroring
            # the reference's analysis pass pipeline.  Fetch targets are a
            # name list outside the program, invisible to the pass's
            # use-count — pin them explicitly
            from paddle_tpu.fluid import ir

            ir.apply_pass(prog, "fc_fuse_pass", keep_vars=fetch_names)
        if config._quantizer_enabled:
            from paddle_tpu.fluid.contrib.ptq import quantize_post_training

            with scope_guard(self._scope):
                self._ptq_scales, self._ptq_rewired = \
                    quantize_post_training(self._exe, prog,
                                           config._quantizer_config)
        self._program = prog
        self._feed_names = list(feeds)
        self._fetch_vars = fetches
        self._fetch_names = fetch_names
        self._staged = {}
        self._outputs = {}

    # -- ZeroCopy API ---------------------------------------------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def get_input_tensor(self, name):
        if name not in self._feed_names:
            raise KeyError(f"unknown input {name!r}; have {self._feed_names}")
        return ZeroCopyTensor(self, name, is_input=True)

    def get_output_tensor(self, name):
        if name not in self._fetch_names:
            raise KeyError(f"unknown output {name!r}")
        return ZeroCopyTensor(self, name, is_input=False)

    def zero_copy_run(self):
        from paddle_tpu.fluid.executor import scope_guard

        missing = [n for n in self._feed_names if n not in self._staged]
        if missing:
            raise ValueError(f"inputs not set: {missing}")
        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=dict(self._staged),
                                 fetch_list=self._fetch_names)
        self._outputs = dict(zip(self._fetch_names, outs))
        return True

    # -- PaddleTensor API -----------------------------------------------
    def run(self, inputs):
        """inputs: list of PaddleTensor in get_input_names() order (or
        named).  Returns list of PaddleTensor."""
        if any(not t.name for t in inputs) and \
                len(inputs) != len(self._feed_names):
            # positional feeding only works when the count matches — a
            # longer list used to fall off self._feed_names[i] with a
            # bare IndexError
            raise ValueError(
                f"run() got {len(inputs)} positional inputs but the "
                f"model expects {len(self._feed_names)}: "
                f"{self._feed_names}")
        feed = {}
        for i, t in enumerate(inputs):
            name = t.name or self._feed_names[i]
            if name not in self._feed_names:
                raise ValueError(
                    f"run() got unknown input {name!r}; expected "
                    f"{self._feed_names}")
            if name in feed:
                # two tensors resolving to one input — duplicate names,
                # or a named tensor colliding with a positional slot —
                # must fail typed instead of silently overwriting
                raise ValueError(
                    f"run() fed input {name!r} twice; expected exactly "
                    f"one tensor per input in {self._feed_names}")
            feed[name] = t.data
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise ValueError(
                f"run() is missing inputs {missing}; expected "
                f"{self._feed_names}")
        from paddle_tpu.fluid.executor import scope_guard

        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_names)
        return [PaddleTensor(o, name=n)
                for n, o in zip(self._fetch_names, outs)]

    # -- dict-in/dict-out serving entry ----------------------------------
    def run_feed_dict(self, feed, validate=True):
        """Serving-path entry (paddle_tpu.serving): run the compiled
        program on a complete ``{input_name: array}`` feed and return
        ``{output_name: array}``.  Same executable cache as
        zero_copy_run/run — one compiled XLA executable per feed-shape
        signature, parameters device-resident across calls.

        validate=False skips the edge checks for callers that already
        validated (the serving engine checks every request at submit;
        re-checking each assembled batch would be pure duplicated
        work in the hot path)."""
        missing = [n for n in self._feed_names if n not in feed]
        extra = [n for n in feed if n not in self._feed_names]
        if missing or extra:
            raise ValueError(
                f"run_feed_dict expects exactly {self._feed_names}; "
                f"missing {missing}, unexpected {extra}")
        if validate:
            blk = self._program.global_block()
            for n in self._feed_names:
                # same fail-at-the-edge contract as copy_from_cpu: a bad
                # feed errors HERE with the name on it, not inside XLA
                check_feed_against_var(n, feed[n],
                                       blk._find_var_recursive(n))
        from paddle_tpu.fluid.executor import scope_guard

        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=dict(feed),
                                 fetch_list=self._fetch_names)
        return dict(zip(self._fetch_names, outs))

    def program(self):
        return self._program


def create_paddle_predictor(config: AnalysisConfig) -> AnalysisPredictor:
    """Reference api factory CreatePaddlePredictor<AnalysisConfig>."""
    return AnalysisPredictor(config)
