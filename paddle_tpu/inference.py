"""Inference engine: AnalysisPredictor-style serving API.

Reference: paddle/fluid/inference/ — `AnalysisPredictor`
(api/analysis_predictor.h:46) loads a saved ProgramDesc + params, runs the
Analyzer fusion-pass pipeline, then serves through a NaiveExecutor with
ZeroCopyTensor inputs/outputs (:68); TensorRT/Anakin/nGraph subgraphs offload
pieces of the graph (analysis/ir_pass_manager.cc).

TPU-native redesign: the "engine subgraph offload" side-path of the
reference IS this framework's main path — the whole pruned inference program
compiles to one XLA executable, cached per input-shape signature, with
parameters resident on device across calls (the ZeroCopyRun property: no
per-call weight transfer; only inputs/outputs cross the host boundary).
Fusion passes are XLA's job.  `config.switch_ir_optim` etc. are accepted for
API parity but have no separate pass pipeline to toggle.
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["AnalysisConfig", "AnalysisPredictor", "PaddleTensor",
           "PaddleDType", "create_paddle_predictor", "ZeroCopyTensor"]


class PaddleDType:
    FLOAT32 = "float32"
    INT64 = "int64"
    INT32 = "int32"


class PaddleTensor:
    """Input/output container for the non-zero-copy `run` API
    (reference api/paddle_api.h PaddleTensor)."""

    def __init__(self, data=None, name="", lod=None):
        arr = np.asarray(data) if data is not None else None
        self.name = name
        self.data = arr
        self.dtype = str(arr.dtype) if arr is not None else None
        self.shape = list(arr.shape) if arr is not None else []
        self.lod = lod or []

    def as_ndarray(self):
        return self.data


class AnalysisConfig:
    """Reference api/paddle_analysis_config.h.  Device toggles map to
    Places; pass/engine switches are parity no-ops (XLA compiles and fuses
    the whole graph unconditionally)."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        self._model_dir = model_dir
        self._prog_file = prog_file
        self._params_file = params_file
        self._use_tpu = True
        self._ir_optim = True
        self._enable_memory_optim = False
        self._quantizer_enabled = False
        self._quantizer_config = None

    def set_model(self, model_dir, params_file=None):
        if params_file is None:
            self._model_dir = model_dir
        else:
            self._prog_file = model_dir
            self._params_file = params_file

    def model_dir(self):
        return self._model_dir

    def disable_gpu(self):
        self._use_tpu = False

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # GPU knob accepted for source compatibility; device is the TPU
        self._use_tpu = True

    def switch_ir_optim(self, x=True):
        self._ir_optim = bool(x)

    def enable_memory_optim(self):
        self._enable_memory_optim = True

    def switch_use_feed_fetch_ops(self, x=True):
        pass

    def switch_specify_input_names(self, x=True):
        pass

    # -- post-training int8 quantization (reference EnableMkldnnQuantizer,
    #    inference/api/mkldnn_quantizer.cc) ------------------------------
    def enable_quantizer(self):
        """Calibrate on warmup data at predictor build, then run the
        int8-QDQ rewritten program (fluid/contrib/ptq.py)."""
        from paddle_tpu.fluid.contrib.ptq import PTQConfig

        self._quantizer_enabled = True
        if self._quantizer_config is None:
            self._quantizer_config = PTQConfig()
        return self._quantizer_config

    # reference spelling
    enable_mkldnn_quantizer = enable_quantizer

    def quantizer_enabled(self):
        return self._quantizer_enabled

    mkldnn_quantizer_enabled = quantizer_enabled

    def quantizer_config(self):
        """Pure accessor (the reference's mkldnn_quantizer_config never
        enables quantization as a side effect)."""
        from paddle_tpu.fluid.contrib.ptq import PTQConfig

        if self._quantizer_config is None:
            self._quantizer_config = PTQConfig()
        return self._quantizer_config

    mkldnn_quantizer_config = quantizer_config


class ZeroCopyTensor:
    """Named handle onto a predictor slot (reference ZeroCopyTensor):
    copy_from_cpu stages the next run's input; copy_to_cpu reads the last
    run's output without an extra staging buffer on the Python side."""

    def __init__(self, predictor, name, is_input):
        self._pred = predictor
        self.name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr):
        if not self._is_input:
            raise ValueError(f"{self.name} is an output tensor")
        self._pred._staged[self.name] = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        store = self._pred._staged if self._is_input else self._pred._outputs
        if self.name not in store:
            raise RuntimeError(
                f"tensor {self.name!r} has no value yet — "
                + ("copy_from_cpu() first" if self._is_input
                   else "call zero_copy_run() first"))
        return np.asarray(store[self.name])

    def shape(self):
        store = self._pred._staged if self._is_input else self._pred._outputs
        if self.name in store:
            return list(np.shape(store[self.name]))
        # not materialized yet: report the static shape from the program
        var = self._pred._program.global_block()._find_var_recursive(self.name)
        if var is not None and var.shape is not None:
            return list(var.shape)
        raise RuntimeError(f"tensor {self.name!r} has no value or static "
                           f"shape yet")


class AnalysisPredictor:
    def __init__(self, config: AnalysisConfig):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid.executor import Scope, scope_guard

        self._config = config
        place = fluid.TPUPlace(0) if config._use_tpu else fluid.CPUPlace()
        self._scope = Scope()
        self._exe = fluid.Executor(place)
        with scope_guard(self._scope):
            if config._model_dir:
                prog, feeds, fetches = fluid.io.load_inference_model(
                    config._model_dir, self._exe)
            else:
                dirname = os.path.dirname(config._prog_file) or "."
                prog, feeds, fetches = fluid.io.load_inference_model(
                    dirname, self._exe,
                    model_filename=os.path.basename(config._prog_file),
                    params_filename=(os.path.basename(config._params_file)
                                     if config._params_file else None))
        fetch_names = [v.name if hasattr(v, "name") else v
                       for v in fetches]
        if getattr(config, "_ir_optim", True):
            # kernel fusion is XLA's job, but program-level rewrites that
            # still pay (smaller op graphs to trace) run here, mirroring
            # the reference's analysis pass pipeline.  Fetch targets are a
            # name list outside the program, invisible to the pass's
            # use-count — pin them explicitly
            from paddle_tpu.fluid import ir

            ir.apply_pass(prog, "fc_fuse_pass", keep_vars=fetch_names)
        if config._quantizer_enabled:
            from paddle_tpu.fluid.contrib.ptq import quantize_post_training

            with scope_guard(self._scope):
                self._ptq_scales, self._ptq_rewired = \
                    quantize_post_training(self._exe, prog,
                                           config._quantizer_config)
        self._program = prog
        self._feed_names = list(feeds)
        self._fetch_vars = fetches
        self._fetch_names = fetch_names
        self._staged = {}
        self._outputs = {}

    # -- ZeroCopy API ---------------------------------------------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def get_input_tensor(self, name):
        if name not in self._feed_names:
            raise KeyError(f"unknown input {name!r}; have {self._feed_names}")
        return ZeroCopyTensor(self, name, is_input=True)

    def get_output_tensor(self, name):
        if name not in self._fetch_names:
            raise KeyError(f"unknown output {name!r}")
        return ZeroCopyTensor(self, name, is_input=False)

    def zero_copy_run(self):
        from paddle_tpu.fluid.executor import scope_guard

        missing = [n for n in self._feed_names if n not in self._staged]
        if missing:
            raise ValueError(f"inputs not set: {missing}")
        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=dict(self._staged),
                                 fetch_list=self._fetch_names)
        self._outputs = dict(zip(self._fetch_names, outs))
        return True

    # -- PaddleTensor API -----------------------------------------------
    def run(self, inputs):
        """inputs: list of PaddleTensor in get_input_names() order (or
        named).  Returns list of PaddleTensor."""
        feed = {}
        for i, t in enumerate(inputs):
            name = t.name or self._feed_names[i]
            feed[name] = t.data
        from paddle_tpu.fluid.executor import scope_guard

        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_names)
        return [PaddleTensor(o, name=n)
                for n, o in zip(self._fetch_names, outs)]

    def program(self):
        return self._program


def create_paddle_predictor(config: AnalysisConfig) -> AnalysisPredictor:
    """Reference api factory CreatePaddlePredictor<AnalysisConfig>."""
    return AnalysisPredictor(config)
