"""Program-pass framework: the ONE place program rewrites are ordered,
validated, and attributed.

The reference Fluid routes every program rewrite through an
``ir::Graph`` + ``Pass`` layer (``build_strategy.cc:52-145`` declares
the pipeline; ~60 registered passes).  This framework is its TPU-native
program-level analog and the successor to our four ad-hoc rewriters (DP
transpiler, fused-update rewrite, health transpile, inference
transpiler): passes run BETWEEN program construction and executor
compile on every lane, their order is declared once (``PASS_ORDER``),
and every application records what it changed — op-inventory delta,
matched sites, modeled bytes saved — into ``program._pass_report`` so a
claimed win is attributed, not asserted.

Contracts every ``ProgramPass`` must honor:

- **in-place**: ``apply(program, ctx)`` mutates the program and returns
  a report dict with at least ``{"changed": bool, "sites": int}``.
- **idempotence**: a second ``apply`` on the already-rewritten program
  must be a no-op (``changed=False``).  ``PT_PASS_SELFCHECK=1`` makes
  the manager enforce this after every application (test/CI mode).
- **off = identity**: with the pass disabled (FLAGS_graph_passes) the
  program is bit-identical to today's — passes never run partially.

Selection (``FLAGS_graph_passes``): ``"default"``/``"auto"`` = the
DEFAULT_PASSES pipeline; ``"none"``/``""`` = off; otherwise a
comma-separated ordered list of registered pass names, each optionally
prefixed with ``-`` to drop it from the default set (``"default``
semantics with exclusions: ``-fuse_attention`` runs everything default
except that pass).

Cost attribution: the eager report carries the structural delta (op
inventory, sites, statically-modeled bytes).  ``attribute_costs``
(bench + acceptance tests) measures the REAL per-pass
``cost_analysis`` delta — flops, bytes_accessed, compiled-HLO op
inventory — by compiling each pipeline prefix, and books the measured
bytes reduction on ``pt_pass_bytes_saved_total{pass}``.
"""

from __future__ import annotations

import collections
import os
import warnings

import numpy as np

__all__ = [
    "ProgramPass",
    "PassManager",
    "PassContext",
    "register_program_pass",
    "get_program_pass",
    "list_program_passes",
    "resolve_passes",
    "apply_graph_passes",
    "attribute_costs",
    "op_inventory",
    "DEFAULT_PASSES",
    "PASS_ORDER",
]

# the default pipeline FLAGS_graph_passes="default" expands to
DEFAULT_PASSES = ["fuse_attention", "fuse_bias_act_dropout",
                  "fuse_softmax_cross_entropy"]

# THE ordering contract (docs/PASSES.md): when two entries both appear
# in a pipeline they must run in this relative order.  The transpile
# adapters (paddle_tpu/passes/adapters.py) register here too, so the
# ordering between fusion passes and the DP/health transpiles is
# declared in ONE place instead of implied by runner call sites:
# fusion first (the DP fused-update rewrite must see the final forward
# graph), the collective/fused-update transpile next, the health
# sentinel LAST (its detection point depends on the fused buckets).
PASS_ORDER = [
    "fuse_attention",
    "fuse_bias_act_dropout",
    "fuse_softmax_cross_entropy",
    "int8_weight_storage",       # after fusion: rewrites surviving muls
    "data_parallel_transpile",   # includes the fused-update DP rewrite
    "health_sentinel",
]


class PassContext:
    """What a pass application may know about its caller: the execution
    lane (``single``/``chain``/``dp``/``hybrid``/``gspmd``/``serving``),
    var names that must keep a producer (fetch targets live OUTSIDE the
    program here — the executor pins the first run's fetch list), and
    the loss name where the lane knows it."""

    def __init__(self, lane="single", keep_vars=(), loss_name=None,
                 **extra):
        self.lane = lane
        self.keep_vars = frozenset(keep_vars or ())
        self.loss_name = loss_name
        self.extra = dict(extra)


class ProgramPass:
    """Base pass.  Subclasses set ``name`` and implement
    ``apply(program, ctx) -> report dict``; ``validate(program, ctx)``
    runs after apply and should raise on a broken invariant."""

    name = "program_pass"

    def apply(self, program, ctx):
        raise NotImplementedError

    def validate(self, program, ctx):
        """Post-apply invariant check (override where cheap proofs
        exist).  Default: every op in the program still has a
        registered lowering — a rewrite must never emit an op the
        executor cannot trace."""
        from paddle_tpu.fluid import registry

        for b in program.blocks:
            for op in b.ops:
                if op.type in ("feed", "fetch"):
                    continue
                try:
                    # get_op, not has_op: higher-order grad ops
                    # (recurrent_grad_grad) materialize lazily on first
                    # lookup — absent from the registry dict yet valid
                    registry.get_op(op.type)
                except KeyError:
                    raise AssertionError(
                        f"pass {self.name!r} left unregistered op "
                        f"{op.type!r} in block {b.idx}") from None


_PASS_REGISTRY: dict = {}


def register_program_pass(cls):
    """Class decorator: register a ProgramPass subclass by its ``name``
    (also mirrored into fluid.ir.PassRegistry for enumeration parity
    with the reference-style pass registry)."""
    _PASS_REGISTRY[cls.name] = cls

    from paddle_tpu.fluid import ir as _ir

    class _IrShim(_ir.Pass):
        name = cls.name

        def apply(self, graph):  # pragma: no cover - thin mirror
            PassManager([cls.name]).run(graph.program, PassContext())
            return graph

    if not _ir.PassRegistry.has(cls.name):
        _ir.PassRegistry.register(cls.name, lambda **kw: _IrShim())
    return cls


def get_program_pass(name):
    if name not in _PASS_REGISTRY:
        raise KeyError(f"unknown program pass {name!r}; registered: "
                       f"{sorted(_PASS_REGISTRY)}")
    return _PASS_REGISTRY[name]()


def list_program_passes():
    return sorted(_PASS_REGISTRY)


def resolve_passes(spec=None):
    """Expand a FLAGS_graph_passes selection string into an ordered pass
    name list (see module docstring for the grammar)."""
    if spec is None:
        from paddle_tpu.fluid import flags as _flags

        spec = _flags.flag("graph_passes")
    spec = (spec or "").strip()
    if spec.lower() in ("", "none", "off", "0"):
        return []
    toks = [t.strip() for t in spec.split(",") if t.strip()]
    out, dropped = [], set()
    expand_default = False
    for t in toks:
        if t.lower() in ("default", "auto"):
            expand_default = True
        elif t.startswith("-"):
            dropped.add(t[1:].strip())
            expand_default = True  # exclusions imply the default base
        else:
            out.append(t)
    if expand_default:
        out = [p for p in DEFAULT_PASSES if p not in dropped] + \
            [p for p in out if p not in DEFAULT_PASSES]
    # a typo'd "-name" must fail loudly, not silently leave the pass on
    unknown = sorted(dropped - set(_PASS_REGISTRY)) + \
        [p for p in out if p not in _PASS_REGISTRY]
    if unknown:
        raise KeyError(
            f"FLAGS_graph_passes names unknown pass(es) {unknown}; "
            f"registered: {sorted(_PASS_REGISTRY)}")
    _check_order(out)
    return out


def _check_order(names):
    """Enforce the declared partial order: any two selected passes that
    both appear in PASS_ORDER must run in that relative order."""
    pos = {n: i for i, n in enumerate(PASS_ORDER)}
    ranked = [(n, pos[n]) for n in names if n in pos]
    for (a, ra), (b, rb) in zip(ranked, ranked[1:]):
        if ra > rb:
            raise ValueError(
                f"pass order violation: {a!r} must run after {b!r} "
                f"(declared order: {PASS_ORDER})")


# ops whose lowering draws an op_rng_key: their stream is keyed on the
# TRACE index, which a rewrite upstream of them would silently shift.
# The manager pins each one's pre-pass identity (`rng_op_index`) before
# the first pass runs, so fused and unfused programs draw the same
# streams (the cross-program parity contract; see ops/common.py).
RANDOM_OP_TYPES = frozenset({
    "dropout", "uniform_random", "gaussian_random",
    "truncated_gaussian_random", "randint", "sampling_id",
    "uniform_random_batch_size_like", "gaussian_random_batch_size_like",
    "random_crop", "dpsgd", "sampled_softmax_with_cross_entropy",
    "sample_logits", "fused_bias_act_dropout",
})


def pin_random_streams(program):
    """Stamp ``rng_op_index`` on every block-0 random op that lacks one
    (sub-blocks never shift: passes rewrite block 0 only)."""
    blk = program.global_block()
    for i, op in enumerate(blk.ops):
        if op.type in RANDOM_OP_TYPES and "rng_op_index" not in op.attrs:
            op.attrs["rng_op_index"] = (blk.idx << 16) | i


def op_inventory(program):
    """Op-type -> count over every block (the program-level analog of
    the compiled-HLO inventory the cost probe records)."""
    inv = collections.Counter()
    for b in program.blocks:
        for op in b.ops:
            inv[op.type] += 1
    return dict(inv)


def _inventory_delta(before, after):
    """{op_type: after-before} keeping only nonzero entries."""
    out = {}
    for t in set(before) | set(after):
        d = after.get(t, 0) - before.get(t, 0)
        if d:
            out[t] = d
    return out


def _m_applied():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_pass_applied_total",
        "Graph-optimization pass applications by pass and outcome",
        labels=("pass", "changed"))


def _m_sites():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_pass_sites_total",
        "Subgraph sites rewritten by graph-optimization passes",
        labels=("pass",))


def _m_bytes_saved():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_pass_bytes_saved_total",
        "Per-step bytes_accessed reduction attributed to graph-"
        "optimization passes: statically modeled at apply time where "
        "shapes allow, and the measured cost_analysis delta when a "
        "cost attribution runs (bench/acceptance)",
        labels=("pass",))


class PassManager:
    """Ordered pass pipeline over a Program.

    ``run(program, ctx)`` applies each pass, validates it, records the
    per-pass report entry into ``program._pass_report`` (a list — one
    entry per application, so a bench record or test can read exactly
    what happened), books the pt_pass_* metrics, and enforces the
    idempotence contract when ``selfcheck`` (default: the
    ``PT_PASS_SELFCHECK`` env) is on."""

    def __init__(self, names):
        _check_order(list(names))
        self.names = list(names)

    def run(self, program, ctx=None, selfcheck=None):
        ctx = ctx or PassContext()
        if selfcheck is None:
            selfcheck = os.environ.get("PT_PASS_SELFCHECK", "") not in (
                "", "0")
        report = getattr(program, "_pass_report", None)
        if report is None:
            report = []
            program._pass_report = report
        if self.names:
            pin_random_streams(program)
        for name in self.names:
            p = get_program_pass(name)
            before = op_inventory(program)
            entry = p.apply(program, ctx) or {}
            entry.setdefault("changed", False)
            entry.setdefault("sites", 0)
            entry["pass"] = name
            entry["lane"] = ctx.lane
            after = op_inventory(program)
            entry["op_delta"] = _inventory_delta(before, after)
            p.validate(program, ctx)
            if selfcheck and entry["changed"]:
                second = p.apply(program, ctx) or {}
                if second.get("changed"):
                    raise AssertionError(
                        f"pass {name!r} violated the idempotence "
                        f"contract: second apply still reports changes "
                        f"({second})")
            report.append(entry)
            _m_applied().labels(
                **{"pass": name,
                   "changed": "yes" if entry["changed"] else "no"}).inc()
            if entry["sites"]:
                _m_sites().labels(**{"pass": name}).inc(entry["sites"])
            modeled = entry.get("modeled_bytes_saved")
            if modeled:
                _m_bytes_saved().labels(**{"pass": name}).inc(modeled)
        if self.names and any(e["changed"]
                              for e in report[-len(self.names):]):
            program._bump_version()
        return report


def apply_graph_passes(program, lane="single", spec=None, keep_vars=(),
                       loss_name=None):
    """The one lane entry point: resolve FLAGS_graph_passes and run the
    pipeline once per program (idempotent — re-entry with the same spec
    is a no-op; the guard records the spec so a flag flip between runs
    of the SAME program object surfaces as a loud error instead of a
    silent half-rewritten state).  Returns the pass report (possibly
    empty) or None when passes are off."""
    raw = spec
    if raw is None:
        from paddle_tpu.fluid import flags as _flags

        raw = _flags.flag("graph_passes")
    done = getattr(program, "_graph_passes_done", None)
    if done is not None:
        # hot-path early-out: this runs on EVERY Executor step — an
        # unchanged selection string must cost one attribute compare,
        # not a grammar re-resolution (the ±2% step-overhead bar)
        if raw == getattr(program, "_graph_passes_spec", None):
            return getattr(program, "_pass_report", None)
        names = resolve_passes(raw)
        if done != tuple(names):
            warnings.warn(
                "FLAGS_graph_passes changed after this program was "
                f"already rewritten (was {list(done)}, now {names}); "
                "keeping the original rewrite — build a fresh program "
                "to change pass selection")
        else:  # equivalent spelling: remember it so the fast path hits
            program._graph_passes_spec = raw
        return getattr(program, "_pass_report", None)
    names = resolve_passes(raw)
    if not names:
        # off-configuration: bit-identical program, and remember the
        # decision so a later flag flip cannot rewrite a program that
        # already compiled
        program._graph_passes_done = ()
        program._graph_passes_spec = raw
        return None
    ctx = PassContext(lane=lane, keep_vars=keep_vars, loss_name=loss_name)
    report = PassManager(names).run(program, ctx)
    program._graph_passes_done = tuple(names)
    program._graph_passes_spec = raw
    return report


# ---------------------------------------------------------------------------
# cost attribution: the measured per-pass delta
# ---------------------------------------------------------------------------


def _cost_probe(build_fn, pass_names, feed, fetch_list, place=None,
                want_hlo=False):
    """Build a FRESH program via ``build_fn()``, apply exactly
    ``pass_names``, run one step and return its cost_analysis numbers
    (+ optimized-HLO text when asked).  ``build_fn() -> (main, startup,
    loss_or_none)``; feed/fetch_list as for Executor.run."""
    from paddle_tpu import fluid

    main, startup, _loss = build_fn()
    # pin the selection so the executor's default application cannot
    # stack on top of the probe's explicit prefix
    main._graph_passes_done = ()
    startup._graph_passes_done = ()
    if pass_names:
        main._graph_passes_done = None
        ctx = PassContext(lane="probe",
                          keep_vars=[f if isinstance(f, str) else f.name
                                     for f in fetch_list])
        PassManager(list(pass_names)).run(main, ctx)
        main._graph_passes_done = tuple(pass_names)
    scope = fluid.Scope()
    with fluid.scope_guard(scope), warnings.catch_warnings():
        # pinning a pipeline PREFIX deliberately diverges from the live
        # flag — the mismatch warning is the probe's design, not a bug
        warnings.filterwarnings("ignore",
                                message="FLAGS_graph_passes changed")
        exe = fluid.Executor(place or fluid.CPUPlace())
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=fetch_list)
        cost = exe.cost_analysis(main, feed, fetch_list=fetch_list)
    out = {
        "flops": float(cost["cost"].get("flops", 0.0)),
        "bytes_accessed": float(cost["cost"].get("bytes accessed", 0.0)),
    }
    if want_hlo:
        (cb,) = [c for c in exe.compiled_for(main)]
        hlo = cb._jitted.lower(
            *cb._jit_args(scope, exe._coerce_feed(main, feed),
                          0)).compile().as_text()
        out["hlo"] = hlo
    return out


def attribute_costs(build_fn, feed, fetch_list, spec=None, place=None,
                    want_hlo=False):
    """Measure the REAL per-pass cost_analysis delta: compile each
    pipeline prefix ([], [p1], [p1,p2], ...) of the resolved selection
    against a fresh build and diff consecutive flops / bytes_accessed.

    Returns ``{"baseline": {...}, "per_pass": [{"pass", "flops_delta",
    "bytes_accessed_delta", ...}], "final": {...}}`` and books each
    pass's measured bytes reduction (when positive) on
    ``pt_pass_bytes_saved_total{pass}``.  With ``want_hlo`` the final
    stage's optimized HLO text rides along (the fusion-proof surface).
    CPU-measurable; on-chip MFU capture is the docs/PERF.md placeholder.
    """
    names = resolve_passes(spec)
    stages = [names[:i] for i in range(len(names) + 1)]
    probes = []
    for i, prefix in enumerate(stages):
        probes.append(_cost_probe(
            build_fn, prefix, feed, fetch_list, place=place,
            want_hlo=want_hlo and i == len(stages) - 1))
    per_pass = []
    for name, prev, cur in zip(names, probes, probes[1:]):
        d_bytes = prev["bytes_accessed"] - cur["bytes_accessed"]
        d_flops = prev["flops"] - cur["flops"]
        per_pass.append({
            "pass": name,
            "bytes_accessed_delta": d_bytes,
            "flops_delta": d_flops,
            "bytes_accessed": cur["bytes_accessed"],
            "flops": cur["flops"],
        })
        if d_bytes > 0:
            _m_bytes_saved().labels(**{"pass": name}).inc(int(d_bytes))
    out = {"baseline": {k: v for k, v in probes[0].items() if k != "hlo"},
           "per_pass": per_pass,
           "final": {k: v for k, v in probes[-1].items() if k != "hlo"}}
    if want_hlo and "hlo" in probes[-1]:
        out["final_hlo"] = probes[-1]["hlo"]
    return out


# ---------------------------------------------------------------------------
# shared matcher plumbing for the fusion passes
# ---------------------------------------------------------------------------


def consumer_map(program):
    """var name -> list of ops reading it, across EVERY block (a
    sub-block read must veto fusing the producer away)."""
    cons = collections.defaultdict(list)
    for b in program.blocks:
        for op in b.ops:
            for n in set(op.input_arg_names):
                cons[n].append(op)
    return cons


def is_backward(op):
    return op.attrs.get("op_role") in ("backward", "optimize")


def single_forward_consumer(cons, name, block=None):
    """The unique non-backward consumer of ``name``, or None.  With
    ``block`` given, the consumer must also LIVE in that block — a
    sub-block (while/cond body) consumer means the var escapes the
    rewrite scope, so the chain walk must stop rather than absorb an op
    the matcher's block-0 index doesn't know."""
    fwd = [op for op in cons.get(name, []) if not is_backward(op)]
    if len(fwd) != 1:
        return None
    if block is not None and fwd[0].block is not block:
        return None
    return fwd[0]


def grad_groups(block):
    """fwd op index -> grad ops differentiating it (append_backward
    stamps ``fwd_op_idx`` on every grad desc)."""
    groups = collections.defaultdict(list)
    for op in block.ops:
        idx = op.attrs.get("fwd_op_idx")
        if idx is not None and is_backward(op):
            groups[int(idx)].append(op)
    return groups


def static_numel(block, name):
    """Element count when the var's shape is fully static, else None."""
    v = block._find_var_recursive(name)
    if v is None or v.shape is None or any(
            d is None or d < 0 for d in v.shape):
        return None
    return int(np.prod(v.shape, dtype=np.int64)) if v.shape else 1


def rebuild_block(block, remove_ids, inserts):
    """Rebuild ``block.ops`` removing ops whose id() is in
    ``remove_ids`` and inserting new ops at anchors: ``inserts`` maps
    id(anchor op) -> list of new ops placed AT the anchor's position
    (the anchor itself may also be in remove_ids).  Afterwards every
    retained/inserted op's ``fwd_op_idx`` attr is renumbered to the new
    index of the forward op it references; removed forward indices remap
    through ``fwd_redirect`` (old idx -> anchor op whose new position
    stands in for the fused subgraph) passed inside ``inserts`` via the
    optional second tuple element.

    inserts: {anchor_id: (new_ops, redirected_old_fwd_idxs)} — every
    old fwd index in the redirect set maps to the FIRST new op's final
    position.
    """
    new_ops = []
    old_index_of = {id(op): i for i, op in enumerate(block.ops)}
    # old fwd idx -> marker object whose final position stands in
    redirect_target = {}
    for anchor_id, (ops_new, redirects) in inserts.items():
        for old in redirects:
            redirect_target[old] = id(ops_new[0]) if ops_new else None
    for op in block.ops:
        ins = inserts.get(id(op))
        if ins is not None:
            new_ops.extend(ins[0])
        if id(op) not in remove_ids:
            new_ops.append(op)
    new_index_of = {id(op): i for i, op in enumerate(new_ops)}
    remap = {}
    for oid, old in old_index_of.items():
        if oid in new_index_of:
            remap[old] = new_index_of[oid]
    for old, target in redirect_target.items():
        if target is not None and target in new_index_of:
            remap[old] = new_index_of[target]
    for op in new_ops:
        idx = op.attrs.get("fwd_op_idx")
        if idx is not None and int(idx) in remap:
            op.attrs["fwd_op_idx"] = remap[int(idx)]
    block.ops = new_ops
    block.program._bump_version()
    return remap
