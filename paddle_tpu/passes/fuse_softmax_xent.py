"""fuse_softmax_cross_entropy: pattern-match the softmax→cross_entropy
chain and rewrite it to the ``fused_softmax_cross_entropy`` op.

The classifier-head spelling the book scripts (and the MLM-head-style
graphs that compose ``softmax`` + ``cross_entropy`` instead of calling
``softmax_with_cross_entropy``) emit:

    softmax(logits)            -> probs     [.., C]
    cross_entropy(probs, lbl)  -> loss      [.., 1]

materializes the [positions, C] probability tensor as a program
variable — written by the softmax, re-read by ``cross_entropy`` AND by
both backward ops (the residual) — exactly where XLA's automatic fusion
stops at op boundaries.  The rewrite replaces the ``cross_entropy`` op
(and, on training programs, the ``cross_entropy_grad`` +
``softmax_grad`` backward pair, located by their ``fwd_op_idx`` stamps)
with ONE ``fused_softmax_cross_entropy`` op whose lowering is the
BIT-EXACT composition of the two originals (ops/math_ops.py — same
primitives, same eps clamp), so the 20-step parity gate holds to the
last ULP.

The softmax op itself is RETAINED, now consumer-less: the probability
tensor is the model's user-visible prediction in every book-script
head (``save_inference_model(target_vars=[predict])``, the post-train
parity fetch), so deleting its producer would break any fetch outside
the first run's pinned list.  Per-fetch-signature pruning
(fluid/executor.py ``BlockPlan``) drops the dangling softmax from every
executable that does not fetch it — the training step stops
materializing the [positions, C] tensor, and a program that DOES fetch
probs computes them only then.

Match contract (regression-tested in tests/test_passes.py):

- the softmax reduces over the LAST axis (attrs axis in {-1, rank-1}) —
  the fused lowering forwards the axis to softmax but cross_entropy
  always picks over -1, so any other axis keeps the composed path.
- ``cross_entropy`` is the probability tensor's ONLY forward consumer
  (consumers across ALL blocks counted) — a second reader (an accuracy
  op) would make the backward a partial-gradient accumulation the
  single fused grad cannot replace.
- the backward chain, when present, must be the closed canonical pair —
  an extra reader of the intermediate cotangent vetoes the match.
- ``cross_entropy2`` (with its XShape/MatchX side outputs) does not
  match; ``soft_label`` and ``ignore_index`` ride through as attrs.
"""

from __future__ import annotations

from paddle_tpu.fluid.framework import Operator

from .framework import (ProgramPass, consumer_map, grad_groups,
                        rebuild_block, register_program_pass,
                        single_forward_consumer, static_numel)

_GRAD_TYPES = frozenset({"softmax_grad", "cross_entropy_grad"})


def _var(block, name):
    return block._find_var_recursive(name)


@register_program_pass
class FuseSoftmaxCrossEntropyPass(ProgramPass):
    name = "fuse_softmax_cross_entropy"

    def apply(self, program, ctx):
        block = program.global_block()
        cons = consumer_map(program)
        groups = grad_groups(block)
        claimed = set()
        matches = []
        for op in block.ops:
            if id(op) in claimed:
                continue
            m = self._match(block, cons, op, ctx)
            if m is None:
                continue
            g = self._match_backward(block, cons, groups, m)
            if g is None:
                continue  # a backward chain exists but is not canonical
            m["grad"] = g
            for o in m["chain_ops"] + g["ops"]:
                claimed.add(id(o))
            matches.append(m)
        if not matches:
            return {"changed": False, "sites": 0}
        modeled = self._rewrite(block, matches)
        return {"changed": True, "sites": len(matches),
                "modeled_bytes_saved": modeled,
                "soft_label_sites": sum(1 for m in matches
                                        if m["soft_label"])}

    # -- matching ------------------------------------------------------
    def _match(self, block, cons, op, ctx):
        if op.type != "softmax" \
                or op.attrs.get("op_role") in ("backward", "optimize"):
            return None
        sm_out = op.output("Out")[0]
        v = _var(block, sm_out)
        rank = len(v.shape) if (v is not None and v.shape) else None
        axis = op.attrs.get("axis", -1)
        if axis != -1 and (rank is None or axis != rank - 1):
            return None
        nxt = single_forward_consumer(cons, sm_out, block=block)
        if nxt is None or nxt.type != "cross_entropy" \
                or nxt.input("X") != [sm_out]:
            return None
        return {"chain_ops": [op, nxt], "x": op.input("X")[0],
                "label": nxt.input("Label")[0], "sm_out": sm_out,
                "out": nxt.output("Y")[0],
                "soft_label": bool(nxt.attrs.get("soft_label", False)),
                "ignore_index": nxt.attrs.get("ignore_index", -100),
                "axis": axis,
                "op_role": op.attrs.get("op_role")}

    def _match_backward(self, block, cons, groups, m):
        """The closed canonical pair: cross_entropy_grad feeding
        softmax_grad, nothing else reading their intermediates.
        Returns {"ops": []} for a forward-only program; None vetoes."""
        idx_of = {id(op): i for i, op in enumerate(block.ops)}
        sm_op, ce_op = m["chain_ops"]
        gops = [g for i in (idx_of[id(sm_op)], idx_of[id(ce_op)])
                for g in groups.get(i, [])]
        if not gops:
            return {"ops": []}
        if any(g.type not in _GRAD_TYPES for g in gops) or len(gops) != 2:
            return None
        ce_g = [g for g in gops if g.type == "cross_entropy_grad"]
        sm_g = [g for g in gops if g.type == "softmax_grad"]
        if len(ce_g) != 1 or len(sm_g) != 1:
            return None
        ce_g, sm_g = ce_g[0], sm_g[0]
        out_grad = ce_g.inputs.get("Y@GRAD", [None])[0]
        d_sm = ce_g.outputs.get("X@GRAD", [None])[0]
        xg = sm_g.outputs.get("X@GRAD", [None])[0]
        if out_grad is None or d_sm is None or xg is None:
            return None
        if sm_g.inputs.get("Out@GRAD", [None])[0] != d_sm:
            return None
        # closure: the intermediate cotangent is read only inside the
        # group (the probability tensor's only forward reader is already
        # proven to be the cross_entropy; its producer stays)
        internal_ok = {id(o) for o in m["chain_ops"]} | \
            {id(g) for g in gops}
        for user in cons.get(d_sm, []):
            if id(user) not in internal_ok:
                return None
        return {"ops": [ce_g, sm_g], "out_grad": out_grad, "xg": xg}

    # -- rewriting -----------------------------------------------------
    def _rewrite(self, block, matches):
        idx_of = {id(op): i for i, op in enumerate(block.ops)}
        remove, inserts = set(), {}
        modeled = 0
        for m in matches:
            numel = static_numel(block, m["sm_out"])
            if numel is not None:
                modeled += 8 * numel  # fp32 write + read of the probs
            attrs = {"axis": m["axis"], "soft_label": m["soft_label"],
                     "ignore_index": m["ignore_index"]}
            if m["op_role"] is not None:
                attrs["op_role"] = m["op_role"]
            inputs = {"X": [m["x"]], "Label": [m["label"]]}
            fused = Operator(block, "fused_softmax_cross_entropy",
                             inputs=inputs,
                             outputs={"Out": [m["out"]]}, attrs=attrs)
            out_var = _var(block, m["out"])
            if out_var is not None:
                out_var.op = fused
            # the softmax op is RETAINED (now consumer-less): prediction
            # fetches / save_inference_model keep their producer, and
            # BlockPlan pruning drops it from executables that never
            # fetch the probabilities
            ce_op = m["chain_ops"][1]
            ce_idx = idx_of[id(ce_op)]
            remove.add(id(ce_op))
            inserts[id(ce_op)] = ([fused], [ce_idx])
            g = m["grad"]
            if g["ops"]:
                gin = dict(inputs)
                gin["Out@GRAD"] = [g["out_grad"]]
                gattrs = dict(attrs)
                gattrs["op_role"] = "backward"
                # renumbered to the fused op's final index by
                # rebuild_block's redirect map
                gattrs["fwd_op_idx"] = ce_idx
                gop = Operator(block, "fused_softmax_cross_entropy_grad",
                               inputs=gin,
                               outputs={"X@GRAD": [g["xg"]]},
                               attrs=gattrs)
                earliest = min(g["ops"], key=lambda o: idx_of[id(o)])
                for o in g["ops"]:
                    remove.add(id(o))
                inserts.setdefault(id(earliest), ([], []))[0].append(gop)
        rebuild_block(block, remove, inserts)
        return modeled
