"""paddle_tpu.passes — the graph-optimization pass layer
(docs/PASSES.md).

One program-pass framework between program construction and executor
compile on every lane (single-device Executor, run_steps chains, DP
transpiler, hybrid, GSPMD, serving/inference load path):

- ``framework``      — Pass base class, ordered PassManager, selection
                       (FLAGS_graph_passes), per-pass validation +
                       idempotence contract, ``program._pass_report``,
                       pt_pass_* metrics, measured cost attribution.
- ``fuse_attention`` — the unfused matmul→[bias]→softmax→[dropout]→
                       matmul attention subgraph rewritten to the
                       ``flash_attention`` op (Pallas on TPU).
- ``fuse_bias_act``  — the FFN elementwise_add→gelu→[dropout] chain
                       rewritten to ``fused_bias_act_dropout``
                       (kernels/fused_bias_act.py).
- ``fuse_softmax_xent`` — the classifier/MLM-head softmax→cross_entropy
                       pair rewritten to the bit-exact
                       ``fused_softmax_cross_entropy`` op.
- ``int8_weights``   — opt-in inference rewrite: fp32 matmul weights
                       stored dual-int8 at rest, reconstructed on-chip
                       by ``dequantize_weight_storage``
                       (kernels/primitives/int8.py, docs/KERNELS.md).
- ``adapters``       — the pre-existing rewriters (DP transpile incl.
                       the fused-update rewrite, health sentinel)
                       registered as passes so the ordering contract
                       lives in ONE place (framework.PASS_ORDER).
"""

from __future__ import annotations

from . import adapters  # noqa: F401  (registers the transpile adapters)
from . import fuse_attention  # noqa: F401  (registers fuse_attention)
from . import fuse_bias_act  # noqa: F401  (registers fuse_bias_act_dropout)
from . import fuse_softmax_xent  # noqa: F401  (fuse_softmax_cross_entropy)
from . import int8_weights  # noqa: F401  (registers int8_weight_storage)
from .framework import (DEFAULT_PASSES, PASS_ORDER,  # noqa: F401
                        PassContext, PassManager, ProgramPass,
                        apply_graph_passes, attribute_costs,
                        get_program_pass, list_program_passes,
                        op_inventory, register_program_pass,
                        resolve_passes)

__all__ = [
    "ProgramPass",
    "PassManager",
    "PassContext",
    "register_program_pass",
    "get_program_pass",
    "list_program_passes",
    "resolve_passes",
    "apply_graph_passes",
    "attribute_costs",
    "op_inventory",
    "DEFAULT_PASSES",
    "PASS_ORDER",
]
