"""fuse_attention: pattern-match the unfused attention subgraph and
rewrite it to the ``flash_attention`` op.

The composed path our models emit when ``use_flash_attention=False``
(models/bert.py / gpt.py / transformer.py — the reference's own
dist_transformer composition):

    matmul(Q, K, transpose_Y=True, alpha=1/sqrt(d))      -> scores
    [elementwise_add(scores, bias[B,1,1,S])]             -> scores
    softmax | softmax_mask_fuse_upper_triangle           -> weights
    [dropout(is_test, upscale_in_train)]                 -> weights
    matmul(weights, V)                                   -> ctx

materializes the [B, heads, S, S] score tensor (twice, plus the softmax
output that backward re-reads) — exactly where XLA's automatic fusion
stops (Operator Fusion in XLA, arXiv:2301.13062).  The rewrite collapses
the chain to ONE ``flash_attention`` op: the Pallas blockwise kernel on
TPU (kernels/flash_attention.py — online softmax, no S×S HBM tensor),
the fp32 XLA reference elsewhere.  On training programs the matching
backward chain (grad ops located by their ``fwd_op_idx`` stamp) is
replaced by the single auto-vjp ``flash_attention_grad`` desc.

Match contract (each condition regression-tested):

- Q/K/V are rank-4 with pairwise-equal static shape tuples AND a proven
  common sequence source: each walks up through its projection chain
  (transpose/reshape/bias-add back to the mul/fc) to the SAME input
  activation.  Static tuples alone are not enough — encoder-decoder
  CROSS-attention has identical (-1, n, -1, d) declared shapes while
  the runtime query/key lengths differ (the transformer NMT decoder),
  and the kernel computes self-attention over one [B, n, S, d]; a
  decode-step query against a longer KV cache is rejected the same way.
- an additive bias must broadcast as a KEY bias: rank-4 with dims 1 and
  2 equal to 1 (a full [B, n, S, S] bias is not expressible).
- ``softmax_mask_fuse_upper_triangle`` maps to ``causal=True``.
- a dropout between softmax and the context matmul only matches when it
  is provably the identity (``is_test`` with upscale_in_train) — probs
  dropout is not expressible in the kernel, so TRAINING programs with
  attention dropout keep the exact composed path.
- every intermediate is single-use (consumers across ALL blocks counted;
  grad ops of the matched chain excepted) and neither persistable nor in
  ``ctx.keep_vars`` (fetch targets).
- the backward chain, when present, must be the closed canonical set —
  a wanted BIAS gradient vetoes the match (the fused op declares Bias
  no-grad, matching the models' stop-gradient masks).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.fluid.framework import Operator

from .framework import (ProgramPass, consumer_map, grad_groups,
                        rebuild_block, register_program_pass,
                        single_forward_consumer, static_numel)

_SOFTMAX_TYPES = ("softmax", "softmax_mask_fuse_upper_triangle")
_GRAD_TYPES = frozenset(t + "_grad" for t in (
    "matmul", "elementwise_add", "dropout") + _SOFTMAX_TYPES)


def _var(block, name):
    return block._find_var_recursive(name)


def _shape4(block, name):
    v = _var(block, name)
    if v is None or v.shape is None or len(v.shape) != 4:
        return None
    return tuple(v.shape)


# ops a q/k/v projection chain may pass through on the walk back to its
# mul/fc projection (bias add follows X; layout ops are shape-neutral)
_CHAIN_PASSTHRU = frozenset({"transpose", "transpose2", "reshape",
                             "reshape2", "elementwise_add", "scale",
                             "cast"})
_PROJECTION_TYPES = frozenset({"mul", "fc", "matmul", "matmul_v2"})


def _producer_map(block):
    prod = {}
    for op in block.ops:
        if op.attrs.get("op_role") in ("backward", "optimize"):
            continue
        for n in op.output_arg_names:
            prod[n] = op  # last forward writer wins
    return prod


def _sequence_source(prod, name, limit=8):
    """Walk a q/k/v head tensor up through its projection chain
    (transpose ← reshape ← [bias add] ← mul/fc) and return the
    projection's INPUT activation name — the sequence the head was
    computed from.  None when the walk doesn't land on a projection
    (conservative: no proof of a common source, no match)."""
    cur = name
    for _ in range(limit):
        op = prod.get(cur)
        if op is None:
            return None
        if op.type in _CHAIN_PASSTHRU:
            xs = op.inputs.get("X") or op.inputs.get("Input") or []
            if len(xs) != 1:
                return None
            cur = xs[0]
            continue
        if op.type in _PROJECTION_TYPES:
            xs = op.inputs.get("X") or op.inputs.get("Input") or []
            return xs[0] if xs else None
        return None
    return None


def _is_identity_dropout(op, program):
    return ((op.attrs.get("is_test", False)
             or getattr(program, "_is_test", False))
            and op.attrs.get("dropout_implementation",
                             "downgrade_in_infer") == "upscale_in_train")


@register_program_pass
class FuseAttentionPass(ProgramPass):
    name = "fuse_attention"

    def apply(self, program, ctx):
        block = program.global_block()
        cons = consumer_map(program)
        groups = grad_groups(block)
        self._prod = _producer_map(block)
        claimed = set()
        matches = []
        for idx, op in enumerate(block.ops):
            if id(op) in claimed:
                continue
            m = self._match(program, block, cons, idx, op, ctx, claimed)
            if m is None:
                continue
            g = self._match_backward(block, cons, groups, m)
            if g is None:
                continue  # a backward chain exists but is not canonical
            m["grad"] = g
            for o in m["chain_ops"] + g["ops"]:
                claimed.add(id(o))
            matches.append(m)
        if not matches:
            return {"changed": False, "sites": 0}
        modeled = self._rewrite(program, block, matches)
        return {"changed": True, "sites": len(matches),
                "modeled_bytes_saved": modeled,
                "causal_sites": sum(1 for m in matches if m["causal"]),
                "bias_sites": sum(1 for m in matches if m["bias"])}

    # -- matching ------------------------------------------------------
    def _match(self, program, block, cons, idx, op, ctx, claimed):
        if op.type != "matmul" or not op.attrs.get("transpose_Y") \
                or op.attrs.get("transpose_X"):
            return None
        q, k = op.input("X")[0], op.input("Y")[0]
        qs, ks = _shape4(block, q), _shape4(block, k)
        if qs is None or ks is None or qs != ks:
            return None
        # self-attention proof: q and k must project from the SAME
        # sequence (static -1 dims compare equal for cross-attention too)
        src_q = _sequence_source(self._prod, q)
        if src_q is None or _sequence_source(self._prod, k) != src_q:
            return None
        chain = [op]
        internals = []
        cur = op.output("Out")[0]
        bias = None
        nxt = self._next(cons, cur, ctx, block)
        if nxt is not None and nxt.type == "elementwise_add" \
                and nxt.input("X") == [cur]:
            bshape = _shape4(block, nxt.input("Y")[0])
            if bshape is None or bshape[1] != 1 or bshape[2] != 1:
                return None
            bias = nxt.input("Y")[0]
            chain.append(nxt)
            internals.append(cur)
            cur = nxt.output("Out")[0]
            nxt = self._next(cons, cur, ctx, block)
        if nxt is None or nxt.type not in _SOFTMAX_TYPES \
                or nxt.input("X") != [cur]:
            return None
        causal = nxt.type == "softmax_mask_fuse_upper_triangle"
        if not causal and nxt.attrs.get("axis", -1) not in (-1, 3):
            return None
        chain.append(nxt)
        internals.append(cur)
        cur = nxt.output("Out")[0]
        nxt = self._next(cons, cur, ctx, block)
        if nxt is not None and nxt.type == "dropout" \
                and nxt.input("X") == [cur]:
            if not _is_identity_dropout(nxt, program):
                return None
            mask = nxt.outputs.get("Mask", [])
            if mask and (cons.get(mask[0]) or mask[0] in ctx.keep_vars):
                return None  # someone reads/fetches the mask: keep it
            chain.append(nxt)
            internals.append(cur)
            cur = nxt.output("Out")[0]
            nxt = self._next(cons, cur, ctx, block)
        if nxt is None or nxt.type != "matmul" \
                or nxt.attrs.get("transpose_X") \
                or nxt.attrs.get("transpose_Y") \
                or nxt.attrs.get("alpha", 1.0) != 1.0 \
                or nxt.input("X") != [cur]:
            return None
        v = nxt.input("Y")[0]
        if _shape4(block, v) != ks:
            return None
        if _sequence_source(self._prod, v) != src_q:
            return None
        chain.append(nxt)
        internals.append(cur)
        if any(id(o) in claimed for o in chain):
            return None
        for n in internals:
            if n in ctx.keep_vars:
                return None
            var = _var(block, n)
            if var is not None and var.persistable:
                return None
        return {"chain_ops": chain, "internals": internals,
                "q": q, "k": k, "v": v, "bias": bias, "causal": causal,
                "sm_scale": float(op.attrs.get("alpha", 1.0)),
                "out": chain[-1].output("Out")[0],
                "op_role": chain[0].attrs.get("op_role")}

    def _next(self, cons, name, ctx, block):
        # block-scoped: a sub-block consumer (while/cond body) ends the
        # chain — the matcher's indices and rewrite cover block 0 only
        return single_forward_consumer(cons, name, block=block)

    def _match_backward(self, block, cons, groups, m):
        """Collect the chain's grad ops and verify the closed canonical
        structure.  Returns {"ops": [...], names...}; {"ops": []} for a
        forward-only program; None to veto the whole match."""
        idx_of = {id(op): i for i, op in enumerate(block.ops)}
        fwd_idxs = [idx_of[id(o)] for o in m["chain_ops"]]
        gops = [g for i in fwd_idxs for g in groups.get(i, [])]
        if not gops:
            return {"ops": []}
        if any(g.type not in _GRAD_TYPES for g in gops):
            return None
        first_mm, last_mm = m["chain_ops"][0], m["chain_ops"][-1]
        g_first = [g for g in gops
                   if g.attrs.get("fwd_op_idx") == idx_of[id(first_mm)]]
        g_last = [g for g in gops
                  if g.attrs.get("fwd_op_idx") == idx_of[id(last_mm)]]
        if len(g_first) != 1 or len(g_last) != 1:
            return None
        out_grad = g_last[0].inputs.get("Out@GRAD", [None])[0]
        if out_grad is None:
            return None
        qg = g_first[0].outputs.get("X@GRAD", [None])[0]
        kg = g_first[0].outputs.get("Y@GRAD", [None])[0]
        vg = g_last[0].outputs.get("Y@GRAD", [None])[0]
        # a wanted bias gradient rides the fused op too (the kernel's
        # custom VJP computes db; the models' mask chain is live through
        # the scale/reshape ops even under the stop_gradient stamp)
        bg = None
        for g in gops:
            if g.type == "elementwise_add_grad":
                bg = g.outputs.get("Y@GRAD", [None])[0]
        # closure: everything the group produces is consumed only inside
        # the group, except the exit gradients
        group_ids = {id(g) for g in gops}
        chain_ids = {id(o) for o in m["chain_ops"]}
        exits = {n for n in (qg, kg, vg, bg) if n}
        internal_ok = chain_ids | group_ids
        for g in gops:
            for n in g.output_arg_names:
                if n in exits:
                    continue
                for user in cons.get(n, []):
                    if id(user) not in internal_ok:
                        return None
        # and the forward internals may only be read by the chain+group
        for n in m["internals"]:
            for user in cons.get(n, []):
                if id(user) not in internal_ok:
                    return None
        return {"ops": gops, "out_grad": out_grad,
                "qg": qg, "kg": kg, "vg": vg, "bg": bg}

    # -- rewriting -----------------------------------------------------
    def _rewrite(self, program, block, matches):
        idx_of = {id(op): i for i, op in enumerate(block.ops)}
        remove, inserts = set(), {}
        modeled = 0
        for m in matches:
            for n in m["internals"]:
                numel = static_numel(block, n)
                if numel is not None:
                    modeled += 8 * numel  # fp32 write + read per tensor
            attrs = {"causal": m["causal"], "sm_scale": m["sm_scale"]}
            if m["op_role"] is not None:
                attrs["op_role"] = m["op_role"]
            inputs = {"Q": [m["q"]], "K": [m["k"]], "V": [m["v"]]}
            if m["bias"]:
                inputs["Bias"] = [m["bias"]]
            fused = Operator(block, "flash_attention", inputs=inputs,
                             outputs={"Out": [m["out"]]}, attrs=attrs)
            out_var = _var(block, m["out"])
            if out_var is not None:
                out_var.op = fused
            chain_idxs = [idx_of[id(o)] for o in m["chain_ops"]]
            for o in m["chain_ops"]:
                remove.add(id(o))
            inserts[id(m["chain_ops"][0])] = ([fused], chain_idxs)
            g = m["grad"]
            if g["ops"]:
                gin = dict(inputs)
                gin["Out@GRAD"] = [g["out_grad"]]
                gouts = {}
                for slot, n in (("Q@GRAD", g["qg"]), ("K@GRAD", g["kg"]),
                                ("V@GRAD", g["vg"]),
                                ("Bias@GRAD", g.get("bg"))):
                    if n:
                        gouts[slot] = [n]
                gattrs = dict(attrs)
                gattrs["op_role"] = "backward"
                # renumbered to the fused op's final index by
                # rebuild_block's redirect map
                gattrs["fwd_op_idx"] = chain_idxs[0]
                gop = Operator(block, "flash_attention_grad",
                               inputs=gin, outputs=gouts, attrs=gattrs)
                earliest = min(g["ops"], key=lambda o: idx_of[id(o)])
                for o in g["ops"]:
                    remove.add(id(o))
                prev = inserts.get(id(earliest))
                if prev is None:
                    inserts[id(earliest)] = ([gop], [])
                else:  # anchor shared with another insert (cannot happen
                    prev[0].append(gop)  # across disjoint matches; safe)
        rebuild_block(block, remove, inserts)
        return modeled
