"""fuse_bias_act_dropout: fuse the FFN ``elementwise_add(bias) -> gelu
-> [dropout]`` chain into one ``fused_bias_act_dropout`` op.

The fc layer emits ``mul`` + ``elementwise_add`` + activation as three
ops; with the hidden-dropout that follows in transformer FFN blocks, the
chain materializes up to two activation-sized fp32 intermediates per
block.  The fused op (ops/fused_ops.py -> kernels/fused_bias_act.py)
runs the whole chain in one kernel — Pallas blockwise VMEM tiles on TPU,
a single XLA fusion elsewhere.

Match contract:

- ``elementwise_add`` whose Y is a RANK-1 var sized to X's (static)
  last dim, with the bias-broadcast axis (``axis`` in {-1, x_rank-1} —
  the fc ``append_bias_op`` convention).  Residual adds (rank-N + rank-N)
  never match.
- its single forward consumer is ``gelu``; gelu's single forward
  consumer may be a ``dropout`` (any mode) with
  ``upscale_in_train`` semantics — the absorbed dropout's mask stream
  is pinned via the ``rng_op_index`` attr (ops/common.py op_rng_key) so
  the fused program draws the SAME masks the unfused one would; the
  Mask output is preserved for the backward.
- intermediates are single-use, non-persistable, not in keep_vars.
- training programs: the chain's grad ops (``dropout_grad`` /
  ``gelu_grad`` / ``elementwise_add_grad``, located by ``fwd_op_idx``)
  are replaced by ONE ``fused_bias_act_dropout_grad`` that reapplies
  the SAVED mask — forward/backward agree exactly, like the standalone
  dropout op.
"""

from __future__ import annotations

from paddle_tpu.fluid.framework import Operator

from .framework import (ProgramPass, consumer_map, grad_groups,
                        rebuild_block, register_program_pass,
                        single_forward_consumer, static_numel)

_GRAD_TYPES = frozenset(("elementwise_add_grad", "gelu_grad",
                         "dropout_grad", "sum"))


def _var(block, name):
    return block._find_var_recursive(name)


@register_program_pass
class FuseBiasActDropoutPass(ProgramPass):
    name = "fuse_bias_act_dropout"

    def apply(self, program, ctx):
        block = program.global_block()
        cons = consumer_map(program)
        groups = grad_groups(block)
        claimed = set()
        matches = []
        for idx, op in enumerate(block.ops):
            if id(op) in claimed:
                continue
            m = self._match(program, block, cons, idx, op, ctx, claimed)
            if m is None:
                continue
            g = self._match_backward(block, cons, groups, m)
            if g is None:
                continue
            m["grad"] = g
            for o in m["chain_ops"] + g["ops"]:
                claimed.add(id(o))
            matches.append(m)
        if not matches:
            return {"changed": False, "sites": 0}
        modeled = self._rewrite(program, block, matches)
        return {"changed": True, "sites": len(matches),
                "modeled_bytes_saved": modeled,
                "dropout_sites": sum(1 for m in matches if m["dropout"])}

    # -- matching ------------------------------------------------------
    def _match(self, program, block, cons, idx, op, ctx, claimed):
        if op.type != "elementwise_add":
            return None
        x, b = op.input("X")[0], op.input("Y")[0]
        xv, bv = _var(block, x), _var(block, b)
        if xv is None or bv is None or xv.shape is None \
                or bv.shape is None or len(bv.shape) != 1:
            return None
        if len(xv.shape) < 2 or bv.shape[0] <= 0 \
                or xv.shape[-1] != bv.shape[0]:
            return None
        if op.attrs.get("axis", -1) not in (-1, len(xv.shape) - 1):
            return None
        chain = [op]
        internals = []
        cur = op.output("Out")[0]
        # block-scoped walks: a sub-block consumer ends the chain — the
        # matcher's indices and rewrite cover block 0 only
        nxt = single_forward_consumer(cons, cur, block=block)
        if nxt is None or nxt.type != "gelu" or nxt.input("X") != [cur]:
            return None
        chain.append(nxt)
        internals.append(cur)
        approximate = bool(nxt.attrs.get("approximate", False))
        cur = nxt.output("Out")[0]
        drop = None
        nxt = single_forward_consumer(cons, cur, block=block)
        if nxt is not None and nxt.type == "dropout" \
                and nxt.input("X") == [cur] \
                and nxt.attrs.get("dropout_implementation",
                                  "downgrade_in_infer") \
                == "upscale_in_train":
            # (a fetched Mask stays fetchable: the fused op re-emits it
            # under the same name with the same pinned stream)
            mask = nxt.outputs.get("Mask", [None])[0]
            drop = nxt
            chain.append(nxt)
            internals.append(cur)
            cur = nxt.output("Out")[0]
        if any(id(o) in claimed for o in chain):
            return None
        for n in internals:
            if n in ctx.keep_vars:
                return None
            var = _var(block, n)
            if var is not None and var.persistable:
                return None
        idx_of = {id(o): i for i, o in enumerate(block.ops)}
        return {"chain_ops": chain, "internals": internals,
                "x": x, "bias": b, "out": cur,
                "approximate": approximate, "dropout": drop,
                "mask": (drop.outputs.get("Mask", [None])[0]
                         if drop is not None else None),
                # the absorbed dropout's pre-fusion trace identity (the
                # manager's pin_random_streams stamp): what op_rng_key
                # would have folded in for the unfused program
                "rng_op_index": (drop.attrs.get(
                    "rng_op_index", (block.idx << 16) | idx_of[id(drop)])
                    if drop is not None else None),
                "op_role": chain[0].attrs.get("op_role")}

    def _match_backward(self, block, cons, groups, m):
        idx_of = {id(op): i for i, op in enumerate(block.ops)}
        fwd_idxs = [idx_of[id(o)] for o in m["chain_ops"]]
        gops = [g for i in fwd_idxs for g in groups.get(i, [])]
        if not gops:
            return {"ops": []}
        if any(g.type not in _GRAD_TYPES for g in gops):
            return None
        add_g = [g for g in gops if g.type == "elementwise_add_grad"]
        last = m["chain_ops"][-1]
        last_g = [g for g in gops
                  if g.attrs.get("fwd_op_idx") == idx_of[id(last)]]
        if len(add_g) != 1 or len(last_g) != 1:
            return None
        out_grad = last_g[0].inputs.get("Out@GRAD", [None])[0]
        if out_grad is None:
            return None
        xg = add_g[0].outputs.get("X@GRAD", [None])[0]
        bg = add_g[0].outputs.get("Y@GRAD", [None])[0]
        group_ids = {id(g) for g in gops}
        chain_ids = {id(o) for o in m["chain_ops"]}
        internal_ok = chain_ids | group_ids
        exits = {n for n in (xg, bg) if n}
        for g in gops:
            for n in g.output_arg_names:
                if n in exits:
                    continue
                for user in cons.get(n, []):
                    if id(user) not in internal_ok:
                        return None
        for n in m["internals"]:
            for user in cons.get(n, []):
                if id(user) not in internal_ok:
                    return None
        # the saved mask feeds dropout_grad only (inside the group)
        if m["mask"]:
            for user in cons.get(m["mask"], []):
                if id(user) not in internal_ok:
                    return None
        return {"ops": gops, "out_grad": out_grad, "xg": xg, "bg": bg}

    # -- rewriting -----------------------------------------------------
    def _rewrite(self, program, block, matches):
        idx_of = {id(op): i for i, op in enumerate(block.ops)}
        remove, inserts = set(), {}
        modeled = 0
        for m in matches:
            for n in m["internals"]:
                numel = static_numel(block, n)
                if numel is not None:
                    modeled += 8 * numel
            drop = m["dropout"]
            attrs = {"act": "gelu", "approximate": m["approximate"],
                     "dropout_prob": (float(drop.attrs.get("dropout_prob",
                                                           0.5))
                                      if drop is not None else 0.0),
                     "dropout_implementation": "upscale_in_train"}
            if drop is not None:
                attrs["is_test"] = bool(drop.attrs.get("is_test", False))
                attrs["rng_op_index"] = int(m["rng_op_index"])
                if drop.attrs.get("seed"):
                    attrs["seed"] = drop.attrs["seed"]
            if m["op_role"] is not None:
                attrs["op_role"] = m["op_role"]
            outputs = {"Out": [m["out"]]}
            if m["mask"]:
                outputs["Mask"] = [m["mask"]]
            fused = Operator(block, "fused_bias_act_dropout",
                             inputs={"X": [m["x"]], "Bias": [m["bias"]]},
                             outputs=outputs, attrs=attrs)
            out_var = _var(block, m["out"])
            if out_var is not None:
                out_var.op = fused
            chain_idxs = [idx_of[id(o)] for o in m["chain_ops"]]
            for o in m["chain_ops"]:
                remove.add(id(o))
            inserts[id(m["chain_ops"][0])] = ([fused], chain_idxs)
            g = m["grad"]
            if g["ops"]:
                gin = {"X": [m["x"]], "Bias": [m["bias"]],
                       "Out@GRAD": [g["out_grad"]]}
                if m["mask"]:
                    gin["Mask"] = [m["mask"]]
                gouts = {}
                if g["xg"]:
                    gouts["X@GRAD"] = [g["xg"]]
                if g["bg"]:
                    gouts["Bias@GRAD"] = [g["bg"]]
                gattrs = dict(attrs)
                gattrs["op_role"] = "backward"
                gattrs["fwd_op_idx"] = chain_idxs[0]
                gop = Operator(block, "fused_bias_act_dropout_grad",
                               inputs=gin, outputs=gouts, attrs=gattrs)
                earliest = min(g["ops"], key=lambda o: idx_of[id(o)])
                for o in g["ops"]:
                    remove.add(id(o))
                prev = inserts.get(id(earliest))
                if prev is None:
                    inserts[id(earliest)] = ([gop], [])
                else:
                    prev[0].append(gop)
        rebuild_block(block, remove, inserts)
        return modeled
