"""int8_weight_storage — store inference matmul weights dual-int8 at
rest (docs/KERNELS.md, kernels/primitives/int8.py).

The serving fleet is HBM-bound at rest: every loaded model pins its fp32
weight matrices whole.  This pass rewrites each eligible weight ``W`` to
the dual-int8 layout — ``W__qhi``/``W__qlo`` (int8, same shape) plus a
per-row fp32 ``W__scale`` — and prepends ONE
``dequantize_weight_storage`` op that reconstructs fp32 ``W`` on-chip:

    W = (W__qhi + W__qlo / 254) * W__scale        # ~14.6 significant bits

2x smaller at rest, and (unlike plain int8) enough mantissa that greedy
decode stays token-stable on the models we serve (the drift gate lives
in tests/decode_e2e_checks.py).

Eligibility is deliberately narrow — a weight is rewritten only when it
is persistable fp32, statically 2-D, produced by no op, and EVERY
consumer (across all blocks) is a forward ``mul``/``matmul`` reading it
through the ``Y`` slot.  Anything else — bias vectors, embeddings
(lookup tables read by ``embedding``), norm scales, anything a backward
op touches — keeps full precision.  Inference-only by construction: a
single backward consumer vetoes the weight.

The pass rewrites the PROGRAM; the matching scope-side conversion is
:func:`quantize_scope_weights`, which callers run once after the pass
(weights must already be loaded).  Opt-in: registered in ``PASS_ORDER``
but not ``DEFAULT_PASSES`` — engaged via
``PassManager(["int8_weight_storage"])`` or ``DecodeEngine(...,
int8_weights=True)``.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.fluid.framework import Operator

from .framework import (ProgramPass, consumer_map, is_backward,
                        register_program_pass)

# storage-var suffixes (shared with kernels/primitives/int8.py naming)
SUFFIX_HI, SUFFIX_LO, SUFFIX_SCALE = "__qhi", "__qlo", "__scale"
_SUFFIXES = (SUFFIX_HI, SUFFIX_LO, SUFFIX_SCALE)

_CONSUMER_TYPES = frozenset(("mul", "matmul"))


def storage_var_names(name):
    """The dual-int8 storage triple for weight ``name``."""
    return name + SUFFIX_HI, name + SUFFIX_LO, name + SUFFIX_SCALE


def _eligible_weights(program, ctx):
    """Names of weights the rewrite may claim, in deterministic order."""
    block = program.global_block()
    cons = consumer_map(program)
    produced = set()
    for b in program.blocks:
        for op in b.ops:
            produced.update(op.output_arg_names)
    keep = set(getattr(ctx, "keep_vars", ()) or ())
    out = []
    for name in sorted(block.vars):
        var = block.vars[name]
        if (not var.persistable or name in keep or name in produced
                or name.endswith(_SUFFIXES)):
            continue
        if str(var.dtype) != "float32":
            continue
        shape = var.shape
        if (shape is None or len(shape) != 2
                or any(d is None or d < 0 for d in shape)):
            continue
        users = cons.get(name, [])
        if not users:
            continue
        if all((not is_backward(op)) and op.type in _CONSUMER_TYPES
               and op.input("Y") == [name] and name not in op.input("X")
               for op in users):
            out.append(name)
    return out


@register_program_pass
class Int8WeightStoragePass(ProgramPass):
    """Rewrite eligible fp32 matmul weights to dual-int8 at-rest storage
    plus an on-chip ``dequantize_weight_storage`` reconstruction op."""

    name = "int8_weight_storage"

    def apply(self, program, ctx):
        block = program.global_block()
        targets = _eligible_weights(program, ctx)
        modeled = 0
        new_ops = []
        for name in targets:
            var = block.vars[name]
            r, c = (int(d) for d in var.shape)
            hi_n, lo_n, sc_n = storage_var_names(name)
            hi = block.create_var(name=hi_n, shape=[r, c], dtype="int8",
                                  persistable=True)
            lo = block.create_var(name=lo_n, shape=[r, c], dtype="int8",
                                  persistable=True)
            sc = block.create_var(name=sc_n, shape=[r, 1],
                                  dtype="float32", persistable=True)
            # the weight becomes an in-graph intermediate: the dequant op
            # is now its producer, the int8 triple is what persists
            var.persistable = False
            deq = Operator(block, "dequantize_weight_storage",
                           inputs={"Hi": [hi.name], "Lo": [lo.name],
                                   "Scale": [sc.name]},
                           outputs={"Out": [name]})
            var.op = deq
            new_ops.append(deq)
            # fp32 4rc  ->  2rc int8 + 4r per-row scales
            modeled += 4 * r * c - (2 * r * c + 4 * r)
        if new_ops:
            block.ops = new_ops + block.ops
            program._bump_version()
        return {"changed": bool(new_ops), "sites": len(new_ops),
                "modeled_bytes_saved": int(modeled)}


def quantize_scope_weights(scope, program, book=True):
    """Scope-side half of the rewrite: quantize each claimed weight into
    its dual-int8 triple and DROP the fp32 array from the scope.

    Run once after :class:`Int8WeightStoragePass` on a scope that already
    holds the model parameters.  Idempotent — weights whose triple is
    already installed are skipped (the fp32 copy, if any survives, is
    still dropped).  Books the realized saving on
    ``pt_int8_bytes_saved_total{kind="weights"}`` unless ``book=False``.
    """
    from paddle_tpu.kernels import primitives as prims

    converted, saved = 0, 0
    for op in program.global_block().ops:
        if op.type != "dequantize_weight_storage":
            continue
        name = op.output("Out")[0]
        hi_n, lo_n, sc_n = op.input("Hi")[0], op.input("Lo")[0], \
            op.input("Scale")[0]
        if scope.get(hi_n) is None:
            w = scope.get(name)
            if w is None:
                raise KeyError(
                    f"int8_weight_storage: weight '{name}' is claimed by "
                    f"the program rewrite but absent from the scope — run "
                    f"quantize_scope_weights after parameters are loaded")
            w = np.asarray(w, np.float32)
            hi, lo, sc = prims.quantize_lastdim(w)
            scope.set(hi_n, np.asarray(hi))
            scope.set(lo_n, np.asarray(lo))
            scope.set(sc_n, np.asarray(sc))
            converted += 1
            saved += prims.bytes_saved(w.size, w.shape[-1])
        scope._vars.pop(name, None)
    if book and saved:
        prims.book_bytes_saved("weights", saved)
    return {"weights": converted, "bytes_saved": int(saved)}
