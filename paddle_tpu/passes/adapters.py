"""Transpile adapters: the pre-existing program rewriters registered as
passes, so ordering against the fusion passes is declared in ONE place
(framework.PASS_ORDER) instead of implied by runner call sites.

These are THIN adapters — semantics unchanged, the runners keep calling
the underlying transpiles directly (they need constructor kwargs and
return values the pass interface doesn't carry).  What registration
buys: the pass registry can enumerate every sanctioned program
rewriter, `PassManager` enforces the relative order when a pipeline
names them explicitly, and tools/lint_passes.py treats exactly this
inventory (plus the modules behind it) as the sanctioned
program-mutation surface.

- ``data_parallel_transpile``: parallel.data_parallel.
  transpile_data_parallel — includes the fused dequant→update→requant
  DP rewrite (the `fused_update` leg), which is why it is ordered AFTER
  the fusion passes: the bucket/eligibility scan must see the final
  forward graph, not one that a later fusion would rewrite under it.
- ``health_sentinel``: health.transpile.insert_health_sentinel —
  ordered LAST: its detection point (raw Grad inputs vs the fused
  buckets' QScale vector) depends on what the DP rewrite produced.
"""

from __future__ import annotations

from .framework import ProgramPass, register_program_pass


@register_program_pass
class DataParallelTranspilePass(ProgramPass):
    """Adapter over transpile_data_parallel (multi-devices graph rewrite
    + quant bucketing + the fused-update rewrite).  Pipeline use needs
    ``loss_name`` on the ctx; ``num_devices`` defaults to the local
    device count.  Idempotent via the transpile summary attr."""

    name = "data_parallel_transpile"

    def apply(self, program, ctx):
        if getattr(program, "_collective_bytes_per_step", None) is not None:
            return {"changed": False, "sites": 0}
        import jax

        from paddle_tpu.parallel.data_parallel import (
            transpile_data_parallel)

        if ctx.loss_name is None:
            raise ValueError(
                "data_parallel_transpile needs ctx.loss_name")
        n = ctx.extra.get("num_devices") or jax.device_count()
        transpile_data_parallel(
            program, ctx.loss_name, n,
            quant_grads=bool(ctx.extra.get("quant_grads", False)))
        plan = getattr(program, "_quant_allreduce_plan", None) or {}
        return {"changed": True,
                "sites": len(plan.get("buckets", [])),
                "fused_update_sites": sum(
                    1 for b in plan.get("buckets", [])
                    if b.get("fused_update"))}


@register_program_pass
class HealthSentinelPass(ProgramPass):
    """Adapter over health.transpile.insert_health_sentinel (already
    idempotent via ``program._health_plan``)."""

    name = "health_sentinel"

    def apply(self, program, ctx):
        from paddle_tpu.health import insert_health_sentinel

        before = getattr(program, "_health_plan", None)
        plan = insert_health_sentinel(program, loss_name=ctx.loss_name)
        return {"changed": plan is not None and before is None,
                "sites": 1 if plan else 0}
