"""The one in-graph skip mechanism, shared by every execution lane.

`wrap_body` wraps a traced step body (the `(donated, readonly, feeds,
step) -> (fetches, out_writes)` convention every compiled block uses) so
that when the program's ``@HEALTH@found_inf`` scalar fires, every
in-place state write — parameters, optimizer moments, BN running stats:
exactly the donated buffers — reverts to its pre-step value.  This is a
TRUE step skip (adaptive moments do not decay toward zero, the
documented deviation of the reference's grad-zeroing gate vanishes),
selected per step by an on-device `where`, so it works inside
`run_steps` chains and costs nothing when the step is healthy.

Health-owned state (the ``@HEALTH@`` vars: loss scale, good/bad-step
counters, the cumulative bad-step total, fault-injection countdowns) is
exempt — a bad step must still halve the loss scale and advance the
counters, which is the whole point of dynamic loss scaling.

Applied OUTERMOST in each lane (after the hybrid runner's ZeRO-gather /
fused-gather wrappers, inside any fori_loop chain wrapper), so a
parameter whose write was replaced by a gathered quantized image is
gated too.  Programs without a health plan get the body back untouched.
"""

from __future__ import annotations

__all__ = ["wrap_body"]


def wrap_body(program, body):
    """Wrap `body` with the found_inf state gate; identity when the
    program carries no health plan."""
    plan = getattr(program, "_health_plan", None)
    if not plan or not plan.get("gate"):
        return body
    found_var = plan["found_var"]
    from .transpile import HEALTH_PREFIX

    def gated(donated, readonly, feeds, step):
        import jax.numpy as jnp

        fetches, out_writes = body(donated, readonly, feeds, step)
        if found_var not in out_writes:
            # forward-only fetch pruned the optimizer leg (and with it
            # the check op): nothing to gate
            return fetches, out_writes
        found = jnp.reshape(
            jnp.asarray(out_writes[found_var]).astype(jnp.float32),
            ()) > 0
        gated_writes = {}
        for name, new in out_writes.items():
            old = donated.get(name)
            if old is None or name.startswith(HEALTH_PREFIX):
                gated_writes[name] = new
                continue
            try:
                ov, nv = jnp.asarray(old), jnp.asarray(new)
            except TypeError:  # structured value (tensor array): pass
                gated_writes[name] = new
                continue
            if ov.shape != nv.shape or ov.dtype != nv.dtype:
                # not an in-place state update (shape/dtype changed):
                # reverting would break the write-back contract
                gated_writes[name] = new
                continue
            gated_writes[name] = jnp.where(found, ov, nv)
        return fetches, gated_writes

    return gated
