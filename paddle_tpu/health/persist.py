"""Durable rollback windows — persist the health sentinel's on-device
snapshot ring across preemption.

PR 10's rollback window (sentinel.py) is a rolling deque of on-device
`jnp.copy` snapshots: it dies with the process, so a preempted job could
only resume at its last FULL checkpoint even when the window held
per-step states far past it.  This module folds the window into the
checkpoint story (`fluid.incubate.checkpoint.AutoCheckpoint(sentinel=)`):

- **Async device→host offload** (`WindowPersister`): the training loop
  hands over *references* to the window's donation-safe device copies
  (cheap — no sync, no host round trip under the step); a single worker
  thread materializes them to host (`np.asarray` is the D2H copy) and
  writes the ring on a time cadence (FLAGS_rollback_persist_interval_s)
  or on demand (full checkpoint saves, the preemption signal path).  An
  offload that arrives while the worker is busy replaces the pending
  payload — the persister never queues unboundedly and always writes
  the newest ring it was handed.

- **Temp+rename durability, versioned manifest**: the payload lands as
  a generation-stamped ``window-<gen>.npz`` named by
  ``window_manifest.json`` (format ``PTHWIN1``, the peer of the native
  PS snapshot's ``PTSCKPT2`` versioning), each written to a temp name
  and renamed; the manifest rename is the commit point and it names the
  exact payload it was written with, so a kill at ANY instant leaves
  the previous (manifest, payload) pair intact and consistent.

- **Bit-exact re-arm**: `load_window` + `HealthSentinel.restore_state`
  restore the window entries (still valid PRE-step states), the
  @HEALTH@ scope vars — the dynamic loss scale resumes at its pre-kill
  value instead of re-warming from init — and the host detector state
  (loss EMA, warmup, cumulative-counter baseline).  A restarted job can
  therefore resume AT the newest window entry (past the last full
  checkpoint) and still roll back through the older entries when the
  replayed step goes bad.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

__all__ = ["WindowPersister", "save_window", "load_window",
           "manifest_step", "WINDOW_FORMAT"]

WINDOW_FORMAT = "PTHWIN1"
_MANIFEST = "window_manifest.json"
_PAYLOAD = "window.npz"
_META_KEYS = ("ema", "emvar", "good_samples", "bad_total_seen",
              "steps_seen", "keep")


def _m_persists():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_rollback_window_persists_total",
        "Durable offloads of the health sentinel's rollback window "
        "(async device->host + temp+rename write), by trigger",
        labels=("trigger",))


def _m_restores():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_rollback_window_restores_total",
        "Restarted processes that re-armed a persisted rollback window "
        "(AutoCheckpoint.resume past the last full checkpoint)")


def _materialize(state):
    """Device→host: np.asarray every window tensor (the jnp.copy refs
    the export handed over).  Runs on the persister's worker thread —
    this is the blocking D2H transfer the step loop never pays."""
    return {
        "window": [{n: np.asarray(v) for n, v in snap.items()}
                   for snap in state.get("window", ())],
        "scope_health": {n: np.asarray(v)
                         for n, v in state.get("scope_health", {}).items()},
        **{k: state.get(k) for k in _META_KEYS},
    }


def save_window(dirname, state, step, trigger="explicit"):
    """Write one materialized sentinel state as the durable ring: a
    GENERATION-stamped payload (``window-<gen>.npz``) first, then
    ``window_manifest.json`` naming it — both temp+rename, the manifest
    rename as the commit point.  The manifest must name the exact
    payload it was written with: overwriting one shared payload file
    would let a kill between the two renames pair the OLD manifest's
    step with the NEW payload's state, and the restored job would
    silently re-run steps on parameters that already contain them.
    Superseded payload generations are swept AFTER the commit.  Returns
    the manifest dict."""
    state = _materialize(state)
    os.makedirs(dirname, exist_ok=True)
    arrays, entries = {}, []
    for i, snap in enumerate(state["window"]):
        names = sorted(snap)
        entries.append(names)
        for j, n in enumerate(names):
            arrays[f"w{i}.{j}"] = snap[n]
    health_names = sorted(state["scope_health"])
    for j, n in enumerate(health_names):
        arrays[f"h.{j}"] = state["scope_health"][n]
    prev = _read_manifest(dirname)
    gen = (int(prev.get("generation", 0)) + 1) if prev else 1
    payload_name = f"window-{gen:012d}.npz"
    payload = os.path.join(dirname, payload_name)
    tmp = f"{payload}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, payload)
    manifest = {
        "format": WINDOW_FORMAT,
        "step": int(step),
        "generation": gen,
        "payload": payload_name,
        "time": time.time(),  # observability: allow — manifest stamp
        "entries": entries,           # per-entry var names, oldest first
        "health_names": health_names,
        "meta": {k: (None if state.get(k) is None
                     else float(state[k]) if k in ("ema", "emvar",
                                                   "bad_total_seen")
                     else int(state[k]))
                 for k in _META_KEYS},
    }
    mpath = os.path.join(dirname, _MANIFEST)
    tmp = f"{mpath}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, mpath)
    # committed: sweep superseded generations (and orphaned temps from
    # kills mid-write) so repeated preemption cannot fill the volume
    for name in os.listdir(dirname):
        if name in (payload_name, _MANIFEST):
            continue
        if name.startswith("window-") or ".tmp" in name:
            try:
                os.unlink(os.path.join(dirname, name))
            except OSError:
                from paddle_tpu.distributed import resilience

                resilience.record("window_sweep_failures")
    _m_persists().labels(trigger=trigger).inc()
    return manifest


def _read_manifest(dirname):
    try:
        with open(os.path.join(dirname, _MANIFEST)) as f:
            m = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if m.get("format") != WINDOW_FORMAT:
        return None  # a future format is not guessable — treat as absent
    return m


def manifest_step(dirname):
    """The step stamped on the persisted ring (the step whose PRE-state
    is the newest window entry), or None when no usable ring exists."""
    m = _read_manifest(dirname)
    return None if m is None else int(m["step"])


def load_window(dirname):
    """-> (state, manifest) re-armable via
    ``HealthSentinel.restore_state``, or (None, None) when absent or
    torn (a torn ring is WORSE than none: resume falls back to the last
    full checkpoint instead of trusting a half-written window)."""
    m = _read_manifest(dirname)
    if m is None:
        return None, None
    try:
        with np.load(os.path.join(dirname,
                                  m.get("payload", _PAYLOAD))) as z:
            window = [
                {n: z[f"w{i}.{j}"] for j, n in enumerate(names)}
                for i, names in enumerate(m["entries"])]
            scope_health = {n: z[f"h.{j}"]
                            for j, n in enumerate(m["health_names"])}
    except (OSError, KeyError, ValueError):
        return None, None
    state = {"window": window, "scope_health": scope_health,
             **m.get("meta", {})}
    return state, m


class WindowPersister:
    """The async offload pump between a live `HealthSentinel` and the
    durable ring on disk.  One worker thread, one pending slot: the hot
    path (`maybe_offload` per step) only checks the time cadence and
    snapshots references; a busy worker means the NEXT offload simply
    replaces the pending payload."""

    def __init__(self, dirname, sentinel, interval_s=None):
        from paddle_tpu.fluid import flags as _flags

        self.dirname = str(dirname)
        self.sentinel = sentinel
        self.interval_s = float(
            _flags.flag("rollback_persist_interval_s")
            if interval_s is None else interval_s)
        # REENTRANT on purpose: AutoCheckpoint's SIGTERM handler runs on
        # the main thread and calls save() -> offload(wait=True); the
        # interrupted frame may be inside offload() holding this lock —
        # a plain Lock would deadlock the process on exactly the
        # preemption path this module exists for (the handler's
        # pending-slot write simply wins, which is the latest-ring
        # semantics anyway)
        self._lock = threading.RLock()
        # serializes the ACTUAL disk writes between the worker and the
        # synchronous (wait=True) path, and orders them by sequence —
        # held only around save_window, never while queueing, so the
        # signal handler waits at most one in-flight write (ms), never
        # on a frame it interrupted
        self._io_lock = threading.Lock()
        self._pending = None          # (state, step, trigger, seq)
        self._seq = 0                 # assigned per offload, monotonic
        self._written_seq = 0         # last sequence durably on disk
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._stop = False
        self._thread = None
        self._last = 0.0              # monotonic time of the last offload
        self.persisted_steps = 0

    # -- hot path --------------------------------------------------------
    def due(self):
        return (self.interval_s > 0
                and time.monotonic() - self._last >= self.interval_s)

    def maybe_offload(self, scope, step):
        """Per-step hook: offload when the time cadence elapsed."""
        if self.due():
            self.offload(scope, step, trigger="interval")

    def offload(self, scope, step, trigger="explicit", wait=False):
        """Offload the sentinel's current state.  The export under the
        caller is reference-cheap.  ``wait=False`` queues for the worker
        thread; ``wait=True`` writes SYNCHRONOUSLY on the calling
        thread and returns with the ring durably on disk — the full-
        checkpoint save and the preemption signal handler use it, and
        the handler may be running above an interrupted frame that
        holds ``self._lock``, so it must not depend on the worker
        (which needs that lock to drain the pending slot) making
        progress before the process dies."""
        if self.sentinel is None:
            return False
        state = self.sentinel.export_state(scope)
        self._last = time.monotonic()
        if wait:
            with self._lock:
                self._seq += 1
                seq = self._seq
                # any queued payload is OLDER than this export: drop it
                # (a write of it racing in the worker is sequence-gated)
                self._pending = None
                self._idle.set()
            return self._write(state, int(step), trigger, seq)
        with self._lock:
            self._seq += 1
            self._pending = (state, int(step), trigger, self._seq)
            self._idle.clear()
            self._ensure_thread()
        self._wake.set()
        return True

    # -- worker ----------------------------------------------------------
    def _write(self, state, step, trigger, seq):
        """One serialized, sequence-gated disk write: an older payload
        must never land AFTER a newer one (the worker may still be
        mid-write of a stale item when the signal path writes inline)."""
        with self._io_lock:
            if seq <= self._written_seq:
                return True  # a newer ring is already on disk
            try:
                save_window(self.dirname, state, step, trigger=trigger)
            except Exception:  # resilience: allow — durability is
                # best-effort; a full disk must not kill the train loop
                from paddle_tpu.distributed import resilience

                resilience.record("window_persist_failures")
                return False
            self._written_seq = seq
            self.persisted_steps = step
        return True

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="pt-window-persist", daemon=True)
            self._thread.start()

    def _run(self):
        while True:
            self._wake.wait(timeout=1.0)
            with self._lock:
                item, self._pending = self._pending, None
                self._wake.clear()
                if item is None:
                    self._idle.set()
                    if self._stop:
                        return
                    continue
            self._write(*item)
            with self._lock:
                if self._pending is None:
                    self._idle.set()

    def close(self, flush=True):
        """Drain the pending offload (when `flush`) and stop the
        worker."""
        if flush:
            self._idle.wait(timeout=60)
        with self._lock:
            self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- restore ---------------------------------------------------------
    def manifest_step(self):
        return manifest_step(self.dirname)

    def restore_into(self, scope, sentinel=None, rearm_scope=True):
        """Re-arm `sentinel` (default: the one this persister serves)
        from the durable ring; with `rearm_scope` the newest window
        entry ALSO restores the stateful program vars into `scope` —
        the resume-past-the-checkpoint path.  Returns the manifest ONLY
        when the scope was actually restored; None otherwise — an EMPTY
        ring (the sentinel only pushes window snapshots under
        action="rollback", so a skip-action run persists health state
        with no entries) must never advance the caller's resume step
        past state it did not restore.  The loss-scale/detector re-arm
        still happens on that path."""
        sentinel = self.sentinel if sentinel is None else sentinel
        state, m = load_window(self.dirname)
        if state is None or sentinel is None:
            return None
        window = state["window"]
        restored_scope = False
        if rearm_scope and window:
            # the newest entry is the PRE-state of manifest["step"]: it
            # BECOMES the live scope state (the caller re-runs that
            # step, whose pre_step re-pushes it), while the OLDER
            # entries re-arm the window for post-restart rollback
            newest = window[-1]
            for n, v in newest.items():
                scope.set(n, np.array(v, copy=True))
            state = dict(state, window=window[:-1])
            restored_scope = True
        sentinel.restore_state(state, scope, rearm_scope=rearm_scope)
        if not restored_scope:
            return None
        # booked only on the resume-past-the-checkpoint path — the
        # documented meaning of the counter
        _m_restores().inc()
        return m
