"""Training health sentinel (docs/DISTRIBUTED.md §6 "Numeric fault
tolerance").

A numeric fault — a NaN/Inf gradient, an Inf loss, a loss spike — used to
either crash the job (FLAGS_check_nan_inf raises from a host-side scan)
or silently poison every replica through the gradient collective.  This
package is the one audited implementation of numeric-health logic for
the whole stack (tools/lint_resilience.py flags raw isnan/isinf/isfinite
checks anywhere else):

- `detect`    — the fused on-device finite check (one scalar per step,
                computed in-graph from the post-reduction gradients, so
                detection costs no extra collective and no host scan)
                plus the classic Executor's host-side scan, now a thin
                wrapper the FLAGS_check_nan_inf path delegates to.
- `transpile` — `insert_health_sentinel(program)`: folds the check into
                the program before the optimizer ops, gates every
                in-place state write on the `found_inf` scalar (a bad
                step's parameter/moment updates are masked IN-GRAPH),
                wires dynamic loss scaling (`update_loss_scaling`
                semantics) end to end, and plants deterministic numeric
                fault injectors from the FaultPlan grammar
                (`nan:grad:step:N`, `inf:loss:step:N`,
                `spike:loss:step:N`).
- `sentinel`  — the host-side response policy (`FLAGS_health_action` =
                raise | skip | rollback): books
                ``pt_health_bad_steps_total{kind,action}`` /
                ``pt_health_rollbacks_total`` / ``pt_health_loss_scale``,
                runs the rolling-EMA loss-spike detector, keeps the
                rolling snapshot window and drives restore + replay.
- `gating`    — the body wrapper every execution lane (single-device
                Executor, transpiler DP, hybrid ZeRO-1, GSPMD executor)
                applies so the skip/rollback state masking is one shared
                mechanism, not four.
- `persist`   — the DURABLE rollback window: async device→host offload
                of the sentinel's snapshot ring on a time cadence
                (FLAGS_rollback_persist_interval_s), temp+rename
                durability with a versioned manifest (``PTHWIN1``), and
                bit-exact re-arm on restart — folded into
                `fluid.incubate.checkpoint.AutoCheckpoint(sentinel=)`
                so a preempted job resumes at the newest window entry
                and can still roll back past a pre-kill bad step.

Enable with FLAGS_health_sentinel=1; all runner lanes attach it
automatically (`health.attach`).
"""

from __future__ import annotations

from . import detect  # noqa: F401
from . import persist  # noqa: F401
from .gating import wrap_body  # noqa: F401
from .persist import WindowPersister  # noqa: F401
from .sentinel import HealthSentinel, attach, run_guarded  # noqa: F401
from .transpile import (FOUND_INF_VAR, LOSS_SCALE_VAR,  # noqa: F401
                        insert_health_sentinel)

__all__ = [
    "attach",
    "run_guarded",
    "HealthSentinel",
    "WindowPersister",
    "insert_health_sentinel",
    "wrap_body",
    "detect",
    "persist",
    "FOUND_INF_VAR",
    "LOSS_SCALE_VAR",
]
