"""Fold the health sentinel into a Program.

`insert_health_sentinel(program)` rewrites the program in place, after
whatever lane-specific transpile already ran (DP bucket pass, hybrid
fused-gather rewrite) and before compilation:

1. **On-device detection**: one `check_finite_and_unscale` op over the
   gradients the optimizer ops actually consume — the raw `Grad` inputs,
   or (for the DP fused keep-quant buckets) the bucket's per-block
   `QScale` vector, which is where a NaN/Inf in any member lands after
   quantization (`max|x|` per block propagates non-finites into the
   fp32 scales), so one tiny tensor check covers the whole bucket.
   The op writes the ``@HEALTH@found_inf`` scalar and unscales the
   gradients by the live loss scale (divide by 1.0 when scaling is
   off).  Computed on POST-collective values, which are replica-
   identical — detection adds no collective launch and never leaves the
   device.

2. **In-graph response**: a `health_accum` op keeps a monotonic
   ``@HEALTH@bad_steps_total`` counter (correct under on-device step
   chains, where only the final step's `found_inf` survives to the
   host), and `update_loss_scaling` (the reference AMP op) halves
   ``@HEALTH@loss_scale`` on a bad step / grows it after N good steps
   when FLAGS_health_loss_scaling is on; the loss-gradient seed is
   multiplied by the scale via a `scale` op so bf16/fp16 AMP self-tunes
   end to end.  The optimizer-update *masking* itself happens at the
   body level (`health.gating.wrap_body`): every lane wraps its step
   body so ALL in-place state writes (params, moments, BN stats —
   everything donated) revert to their pre-step values when
   ``found_inf`` fires, which is a true skip (moments do not decay, the
   reference's documented AMP deviation disappears).

3. **Deterministic numeric fault injection**: FaultPlan rules
   ``nan:grad:step:N`` / ``inf:loss:step:N`` / ``spike:loss:step:N``
   (distributed/fault_injection.py) plant a `health_fault_inject` op
   that corrupts the tensor INSIDE the compiled step at exactly the Nth
   executed step of this program — each rule counts down its own
   persistable ``@HEALTH@fault_<i>`` counter, so the count is
   per-program-lane (immune to shared executor step offsets) and a
   rollback REPLAY of the same step does not re-fire.

The rewrite is idempotent (keyed on ``program._health_plan``) and
returns the plan dict, or None when the program has no optimizer ops to
guard.
"""

from __future__ import annotations

import warnings

import numpy as np

__all__ = ["insert_health_sentinel", "FOUND_INF_VAR", "LOSS_SCALE_VAR",
           "BAD_TOTAL_VAR", "HEALTH_PREFIX"]

HEALTH_PREFIX = "@HEALTH@"
FOUND_INF_VAR = HEALTH_PREFIX + "found_inf"
LOSS_SCALE_VAR = HEALTH_PREFIX + "loss_scale"
BAD_TOTAL_VAR = HEALTH_PREFIX + "bad_steps_total"
GOOD_STEPS_VAR = HEALTH_PREFIX + "good_steps"
BAD_STEPS_VAR = HEALTH_PREFIX + "bad_steps"

_GRAD_SUFFIX = "@GRAD"

# DP fused keep-quant optimizer ops: the gradient never exists as an
# fp32 tensor — the wire-format QScale vector is the detection surface
_WIRE_FORMAT_OPT_OPS = frozenset({
    "fused_sgd_quant_grad", "fused_adam_quant_grad",
    "fused_adamw_quant_grad", "fused_momentum_quant_grad"})


def _optimizer_ops(ops):
    out = []
    for i, op in enumerate(ops):
        if op.attrs.get("op_role") != "optimize":
            continue
        if op.type in _WIRE_FORMAT_OPT_OPS or "Grad" in op.inputs:
            out.append((i, op))
    return out


def _check_inputs(opt_ops):
    """The distinct tensors the finite check covers, in first-use order:
    QScale for wire-format ops (shared per bucket — deduped), Grad for
    everything else."""
    seen, names = set(), []
    for _i, op in opt_ops:
        slot = "QScale" if op.type in _WIRE_FORMAT_OPT_OPS else "Grad"
        for n in op.inputs.get(slot, []):
            if n not in seen:
                seen.add(n)
                names.append(n)
    return names


def _raw_grads(program, opt_ops):
    raw = {g for _, g in getattr(program, "_params_grads", [])}
    if not raw:
        raw = {op.inputs["Grad"][0] for _, op in opt_ops
               if "Grad" in op.inputs}
    return raw


def _find_seed(ops, loss_name):
    """The backward seed: the fill_constant writing `<loss>@GRAD`.
    Returns (index, seed_var_name, loss_var_name) or (None, None, None).
    With loss_name unknown (hybrid/gspmd runners), the FIRST
    @GRAD-writing fill_constant is the seed — append_backward always
    emits it before any other backward op."""
    for i, op in enumerate(ops):
        if op.type != "fill_constant" or len(op.output_arg_names) != 1:
            continue
        out = op.output_arg_names[0]
        if not out.endswith(_GRAD_SUFFIX):
            continue
        loss = out[: -len(_GRAD_SUFFIX)]
        if loss_name is not None and loss != loss_name:
            continue
        return i, out, loss
    return None, None, None


def _last_producer(ops, name, before):
    idx = None
    for i, op in enumerate(ops[:before]):
        if name in op.output_arg_names:
            idx = i
    return idx


def insert_health_sentinel(program, loss_name=None, loss_scaling=None,
                           fault_plan=None):
    """Rewrite `program` in place for the health sentinel; idempotent.
    Returns the plan dict stored on ``program._health_plan`` (also the
    contract `gating.wrap_body` and `sentinel.HealthSentinel` read), or
    None when the program has no optimizer ops to guard."""
    existing = getattr(program, "_health_plan", None)
    if existing is not None:
        return existing

    from paddle_tpu.fluid import flags as _flags
    from paddle_tpu.fluid.framework import Operator

    if loss_scaling is None:
        loss_scaling = _flags.flag("health_loss_scaling")
    loss_scaling = bool(loss_scaling)

    block = program.global_block()
    ops = block.ops
    opt_ops = _optimizer_ops(ops)
    if not opt_ops:
        # warn only for programs that LOOK like training (a backward
        # exists but the optimizer does not — the PS-transpiled trainer
        # case); startup/inference programs pass silently
        if any(n.endswith(_GRAD_SUFFIX) for op in ops
               for n in op.output_arg_names):
            warnings.warn(
                "health sentinel: program has gradients but no local "
                "optimizer ops to guard (PS-transpiled trainer "
                "program?) — sentinel not inserted")
        return None
    check_names = _check_inputs(opt_ops)
    first_opt = opt_ops[0][0]
    seed_idx, seed_var, inferred_loss = _find_seed(ops, loss_name)
    loss_var = loss_name or inferred_loss

    state = {}

    def health_var(name, dtype, shape, default):
        block.create_var(name=name, dtype=dtype, shape=list(shape),
                         persistable=True)
        if default is not None:
            state[name] = np.asarray(default)

    scale_init = (float(_flags.flag("health_loss_scale_init"))
                  if loss_scaling else 1.0)
    health_var(FOUND_INF_VAR, "bool", [1], None)  # pure in-graph write
    health_var(LOSS_SCALE_VAR, "float32", [1],
               np.array([scale_init], np.float32))
    health_var(BAD_TOTAL_VAR, "float32", [1],
               np.array([0.0], np.float32))

    # -- the check + bookkeeping block, inserted before the first
    #    optimizer op (after every gradient collective: backward-role
    #    collectives precede optimize-role ops in program order).  With
    #    loss scaling ON the check IS the unscale
    #    (check_finite_and_unscale rewrites the gradients in place);
    #    with it OFF the read-only form saves a full-size
    #    divide-by-1.0 write-back pass over every gradient ------------
    if loss_scaling:
        check_op = Operator(
            block, "check_finite_and_unscale",
            inputs={"X": list(check_names), "Scale": [LOSS_SCALE_VAR]},
            outputs={"Out": list(check_names),
                     "FoundInfinite": [FOUND_INF_VAR]},
            attrs={"op_role": "optimize"})
    else:
        check_op = Operator(
            block, "health_check",
            inputs={"X": list(check_names)},
            outputs={"FoundInfinite": [FOUND_INF_VAR]},
            attrs={"op_role": "optimize"})
    sentinel_ops = [
        check_op,
        Operator(block, "health_accum",
                 inputs={"FoundInf": [FOUND_INF_VAR],
                         "CumIn": [BAD_TOTAL_VAR]},
                 outputs={"CumOut": [BAD_TOTAL_VAR]},
                 attrs={"op_role": "optimize"}),
    ]
    if loss_scaling:
        health_var(GOOD_STEPS_VAR, "int32", [1],
                   np.array([0], np.int32))
        health_var(BAD_STEPS_VAR, "int32", [1], np.array([0], np.int32))
        sentinel_ops.append(Operator(
            block, "update_loss_scaling",
            inputs={"PrevLossScaling": [LOSS_SCALE_VAR],
                    "FoundInfinite": [FOUND_INF_VAR],
                    "InGoodSteps": [GOOD_STEPS_VAR],
                    "InBadSteps": [BAD_STEPS_VAR]},
            outputs={"LossScaling": [LOSS_SCALE_VAR],
                     "OutGoodSteps": [GOOD_STEPS_VAR],
                     "OutBadSteps": [BAD_STEPS_VAR]},
            attrs={"op_role": "optimize",
                   "incr_every_n_steps":
                       int(_flags.flag("health_scale_growth_steps")),
                   # the issue contract: halve on EVERY bad step
                   "decr_every_n_nan_or_inf": 1,
                   "incr_ratio": 2.0, "decr_ratio": 0.5}))

    inserts = [(first_opt, sentinel_ops)]

    # -- loss-scale application: multiply the backward seed ------------
    if loss_scaling:
        if seed_idx is None:
            warnings.warn(
                "health sentinel: FLAGS_health_loss_scaling is on but "
                "no backward seed (fill_constant -> <loss>@GRAD) was "
                "found — gradients stay unscaled; the unscale divide "
                "by the live scale still applies")
        else:
            inserts.append((seed_idx + 1, [Operator(
                block, "scale",
                inputs={"X": [seed_var],
                        "ScaleTensor": [LOSS_SCALE_VAR]},
                outputs={"Out": [seed_var]},
                attrs={"op_role": "backward"})]))

    # -- deterministic numeric fault injection -------------------------
    if fault_plan is None:
        from paddle_tpu.distributed import fault_injection

        fault_plan = fault_injection.active()
    rules = fault_plan.numeric_rules() if fault_plan is not None else []
    injected = []
    raw = _raw_grads(program, opt_ops)
    grad_site = None  # (insert-after index, grad name): first producer
    for i, op in enumerate(ops[:first_opt]):
        hit = raw.intersection(op.output_arg_names)
        if hit:
            grad_site = (i, sorted(hit)[0])
            break
    loss_site = (_last_producer(ops, loss_var, first_opt)
                 if loss_var else None)
    for k, rule in enumerate(rules):
        if rule["target"] == "grad":
            site = grad_site
        else:
            site = (loss_site, loss_var) if loss_site is not None else None
        if site is None:
            warnings.warn(
                f"health sentinel: no injection site for numeric fault "
                f"rule {rule['kind']}:{rule['target']} — skipped")
            continue
        at, target = site
        counter = f"{HEALTH_PREFIX}fault_{k}"
        health_var(counter, "float32", [1],
                   np.array([float(rule["step"])], np.float32))
        injected.append(dict(rule, target_var=target, counter=counter))
        inserts.append((at + 1, [Operator(
            block, "health_fault_inject",
            inputs={"X": [target], "Counter": [counter]},
            outputs={"Out": [target], "CounterOut": [counter]},
            attrs={"kind": rule["kind"],
                   "spike_scale": float(rule["scale"] or 1000.0)})]))

    # splice highest position first so earlier indices stay valid
    new_ops = list(ops)
    for pos, extra in sorted(inserts, key=lambda t: t[0], reverse=True):
        new_ops[pos:pos] = extra
    block.ops = new_ops

    plan = {
        "found_var": FOUND_INF_VAR,
        "scale_var": LOSS_SCALE_VAR,
        "bad_total_var": BAD_TOTAL_VAR,
        "loss_var": loss_var,
        "loss_scaling": loss_scaling,
        "check_inputs": check_names,
        "state": state,
        "injected": injected,
        "gate": True,
    }
    program._health_plan = plan
    program._bump_version()
    return plan
