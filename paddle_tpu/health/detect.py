"""The one audited finite-check implementation.

Every numeric-health test in the tree routes through these two
functions (tools/lint_resilience.py's `raw-numeric-check` lint enforces
it): `all_finite` is the fused ON-DEVICE reduction the sentinel's
in-graph check and the AMP ops lower to; `host_scan` is the classic
Executor's FLAGS_check_nan_inf behavior (reference operator.cc:953-984),
kept as the fail-fast fallback the executor path now merely wraps.
"""

from __future__ import annotations

__all__ = ["all_finite", "found_inf", "host_scan"]


def _float_arrays(xs):
    import jax.numpy as jnp

    out = []
    for x in xs:
        if x is None:
            continue
        try:
            a = jnp.asarray(x)
        except TypeError:  # non-array (struct value / python object)
            continue
        if jnp.issubdtype(a.dtype, jnp.floating):
            out.append(a)
    return out


def all_finite(xs):
    """ONE boolean scalar: True iff every float tensor in `xs` is fully
    finite.  Traced into the step graph, this is a tree of `is_finite` +
    `reduce_and` ops XLA fuses into the surrounding computation — no
    host round trip, and (computed on post-reduction gradients, which
    are replica-identical) no extra collective launch.  Non-float and
    non-array inputs are ignored; an empty input set is vacuously
    finite."""
    import jax.numpy as jnp

    arrs = _float_arrays(xs)
    if not arrs:
        return jnp.asarray(True)
    ok = jnp.asarray(True)
    for a in arrs:
        ok = ok & jnp.all(jnp.isfinite(a.astype(jnp.float32)))
    return ok


def found_inf(xs):
    """`all_finite` inverted, as the float32 [1] scalar the program's
    ``@HEALTH@found_inf`` variable carries (float so every lane's
    write-back/sharding path treats it like any other stat)."""
    import jax.numpy as jnp

    return jnp.reshape((~all_finite(xs)).astype(jnp.float32), (1,))


def host_scan(named_values, label):
    """Host-side scan over (name, value) pairs; raises RuntimeError
    naming the first non-finite float variable.  The classic Executor's
    FLAGS_check_nan_inf contract (detect-and-crash) — superseded by the
    in-graph sentinel for the runner lanes, kept for op-by-op debugging
    parity."""
    import jax.numpy as jnp

    for name, val in named_values:
        try:
            arr = jnp.asarray(val)
        except TypeError:  # non-array fetch
            continue
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        if not bool(all_finite([arr])):
            raise RuntimeError(
                f"FLAGS_check_nan_inf: variable {name!r} contains "
                f"NaN/Inf after {label}")
