"""Host-side response policy: raise | skip | rollback.

The in-graph side (transpile.py + gating.py) already detected the bad
step and masked its state writes; the `HealthSentinel` is the per-runner
host object that reads the two scalars the step left behind
(``@HEALTH@found_inf``, ``@HEALTH@bad_steps_total``), runs the
rolling-EMA loss-spike detector on the fetched loss, books the
``pt_health_*`` metrics, and drives the configured action:

  raise     — preserve the fail-fast contract: RuntimeError naming the
              step, exactly like FLAGS_check_nan_inf used to (but from
              an on-device scalar, not a host scan of every tensor).
  skip      — nothing more to do for a NaN/Inf step (the in-graph gate
              already masked the update; the loss scale already
              halved); the step is booked and training continues.
  rollback  — restore params + optimizer state from the rolling
              in-memory snapshot window (FLAGS_health_rollback_keep
              steps deep) and tell the runner to REPLAY the same feed:
              the fault-injection counters are health-owned state that
              advanced through the gate, so a deterministic injected
              fault does not re-fire on the replay, and with loss
              scaling on the replay runs at the halved scale.  A replay
              that is STILL bad degrades to skip (no livelock).

Snapshots are device-resident copies (``jnp.copy`` — donation-safe,
no host round trip) taken only when the action is ``rollback``; under
ZeRO-1 / GSPMD the copied arrays keep their sharding, so each process
copies only its addressable shards (the dp-sharded moment shards stay
sharded — ZeRO-aware by construction).  Loss-spike detection under
``skip`` books the event and lets the (already-applied) update stand;
reverting a spike needs ``rollback``.
"""

from __future__ import annotations

import collections

import numpy as np

__all__ = ["HealthSentinel", "attach", "run_guarded"]

_ACTIONS = ("raise", "skip", "rollback")
_EMA_BETA = 0.9
_EPS = 1e-12


def _m_bad_steps():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_health_bad_steps_total",
        "Training steps the health sentinel flagged, by detection kind "
        "(grad=non-finite gradient, loss=non-finite loss, "
        "spike=loss-spike z-score) and the action applied",
        labels=("kind", "action"))


def _m_rollbacks():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_health_rollbacks_total",
        "State restores performed by the health sentinel's rollback "
        "action (each followed by a same-feed replay)")


def _m_loss_scale():
    from paddle_tpu import observability as obs

    return obs.gauge(
        "pt_health_loss_scale",
        "Live dynamic loss scale (@HEALTH@loss_scale) observed after "
        "the most recent step, per runner lane", labels=("lane",))


def run_guarded(sentinel, scope, fetch_names, attempt, chain=False):
    """THE sentinel step protocol, shared by every dispatch site
    (single-device run/run_steps, DP, hybrid, GSPMD): seed state,
    snapshot, run one attempt, evaluate — and re-run the SAME attempt
    once when the sentinel rolled back.  ``attempt()`` is the lane's
    dispatch closure (timing/metrics included, so a replay books as the
    executed step it is); identity pass-through when ``sentinel`` is
    None."""
    if sentinel is None:
        return attempt()
    for _try in range(2):
        sentinel.ensure_state(scope)
        sentinel.pre_step(scope)
        fetches = attempt()
        if sentinel.post_step(scope, fetch_names, fetches,
                              chain=chain) != "replay":
            break
        # the replay dispatch that follows books as its own step (the
        # attempt closure includes timing/metrics); mark the restore in
        # the flight ring so the postmortem shows restore -> replay
        from paddle_tpu.observability import profiling as _profiling

        _profiling.flight_recorder().record(
            {"kind": "health", "event": "rollback_replay",
             "lane": sentinel.lane})
    return fetches


def attach(program, loss_name=None, lane="default", enable=None):
    """The one hook every runner lane calls at construction: inserts the
    sentinel program rewrite (idempotent) and returns a HealthSentinel,
    or None when FLAGS_health_sentinel is off or the program has nothing
    to guard."""
    from paddle_tpu.fluid import flags as _flags

    if enable is None:
        enable = _flags.flag("health_sentinel")
    if not enable:
        return None
    from .transpile import insert_health_sentinel

    plan = insert_health_sentinel(program, loss_name=loss_name)
    if plan is None:
        return None
    return HealthSentinel(program, lane=lane)


class HealthSentinel:
    """Per-runner host controller; see module docstring.

    Runner protocol (shared by all lanes)::

        for _attempt in range(2):
            sent.ensure_state(scope)
            sent.pre_step(scope)
            out = <dispatch one step / one chain>
            if sent.post_step(scope, fetch_names, out) != "replay":
                break
    """

    def __init__(self, program, lane="default", action=None, keep=None,
                 spike_zscore=None, spike_warmup=None):
        from paddle_tpu.fluid import flags as _flags

        self.program = program
        self.plan = program._health_plan
        self.lane = lane
        self.action = action or _flags.flag("health_action")
        if self.action not in _ACTIONS:
            raise ValueError(
                f"FLAGS_health_action must be one of {_ACTIONS}, got "
                f"{self.action!r}")
        self.keep = max(1, int(keep if keep is not None
                               else _flags.flag("health_rollback_keep")))
        self.spike_zscore = float(
            spike_zscore if spike_zscore is not None
            else _flags.flag("health_spike_zscore"))
        self.spike_warmup = int(
            spike_warmup if spike_warmup is not None
            else _flags.flag("health_spike_warmup"))
        self._window = collections.deque(maxlen=self.keep)
        self._ema = None
        self._emvar = 0.0
        self._good_samples = 0
        self._replaying = False
        self._bad_total_seen = 0.0
        self._cum_scope = None  # scope the seen-counter is synced to
        self._snapshot_names = None
        self._steps_seen = 0

    # -- state -----------------------------------------------------------
    def ensure_state(self, scope):
        """Seed the @HEALTH@ scope vars the program reads (loss scale,
        counters, fault countdowns) — must run before the first compile
        against this scope."""
        for name, default in self.plan["state"].items():
            if scope.get(name) is None:
                scope.set(name, np.array(default, copy=True))
        if self._cum_scope is not scope:
            # sync the cumulative-counter baseline to THIS scope: a
            # fresh sentinel (new runner/Executor on a scope with prior
            # bad-step history, or one sentinel serving a second scope)
            # must not read the persisted total as a delta and book a
            # phantom bad step on a clean chain
            self._cum_scope = scope
            cum = self._scalar(scope, self.plan["bad_total_var"])
            self._bad_total_seen = cum if cum is not None else 0.0

    def _stateful_names(self, scope):
        """Persistable program vars present in the scope — params,
        optimizer accumulators, BN stats; health-owned state excluded
        (a restore must not undo the scale halving or re-arm a fired
        fault injector)."""
        if self._snapshot_names is None:
            from .transpile import HEALTH_PREFIX

            block = self.program.global_block()
            self._snapshot_names = [
                n for n, v in block.vars.items()
                if v.persistable and not n.startswith(HEALTH_PREFIX)]
        return [n for n in self._snapshot_names
                if scope.get(n) is not None]

    @staticmethod
    def _copy(value):
        try:
            import jax
            import jax.numpy as jnp

            if isinstance(value, jax.Array):
                # on-device, sharding-preserving, donation-safe copy
                return jnp.copy(value)
        except ImportError:  # pragma: no cover - jax is a hard dep
            pass  # resilience: allow — numpy fallback below IS the handling
        return np.array(value, copy=True)

    def pre_step(self, scope):
        """Push a snapshot onto the rolling window (rollback action
        only — skip/raise never need to restore)."""
        if self.action != "rollback":
            return
        snap = {n: self._copy(scope.get(n))
                for n in self._stateful_names(scope)}
        self._window.append(snap)

    def restore(self, scope):
        """Restore the most recent snapshot (the pre-step state of the
        step being rolled back); consecutive failures walk deeper into
        the window as entries are consumed."""
        if not self._window:
            return False
        snap = self._window.pop()
        for n, v in snap.items():
            scope.set(n, v)
        _m_rollbacks().inc()
        return True

    # -- durable window (health/persist.py + AutoCheckpoint) -------------
    def export_state(self, scope):
        """Snapshot of everything a restarted process needs to re-arm
        this sentinel bit-exactly: the rollback window (REFERENCES to
        the on-device jnp.copy snapshots — cheap under the step loop;
        the device→host materialization happens in the persister's
        worker thread), the @HEALTH@ scope vars (loss scale, counters —
        tiny, read here), and the host-side detector state (loss EMA,
        warmup counter, cumulative-counter baseline)."""
        names = set(self.plan["state"]) | {
            self.plan["found_var"], self.plan["scale_var"],
            self.plan["bad_total_var"]}
        scope_health = {}
        for n in sorted(names):
            v = scope.get(n)
            if v is not None:
                scope_health[n] = np.asarray(v).copy()
        return {
            "window": [dict(snap) for snap in self._window],
            "scope_health": scope_health,
            "ema": self._ema,
            "emvar": self._emvar,
            "good_samples": self._good_samples,
            "bad_total_seen": self._bad_total_seen,
            "steps_seen": self._steps_seen,
            "keep": self.keep,
        }

    def restore_state(self, state, scope, rearm_scope=True):
        """Re-arm from an `export_state` payload (materialized to host
        arrays by the persister): refill the rolling window oldest→
        newest, restore the @HEALTH@ scope state (with rearm_scope —
        the dynamic loss scale resumes at its pre-kill value instead of
        re-warming from init), and the host detector state.  The window
        entries stay valid PRE-STEP states, so a post-restart rollback
        can walk past a bad step that happened before the kill."""
        self._window = collections.deque(
            (dict(snap) for snap in state.get("window", ())),
            maxlen=self.keep)
        if rearm_scope:
            for n, v in state.get("scope_health", {}).items():
                scope.set(n, np.array(v, copy=True))
        ema = state.get("ema")
        self._ema = None if ema is None else float(ema)
        self._emvar = float(state.get("emvar", 0.0))
        self._good_samples = int(state.get("good_samples", 0))
        self._bad_total_seen = float(state.get("bad_total_seen", 0.0))
        self._steps_seen = int(state.get("steps_seen", 0))
        if rearm_scope:
            # the cumulative-counter baseline above is the one synced to
            # THIS scope's restored bad_steps_total — ensure_state must
            # not re-sync it back and erase the restored delta math
            self._cum_scope = scope
        else:
            # ring-only re-arm (the window is OLDER than the restored
            # checkpoint): the scope's bad_steps_total is NOT the ring's
            # — force ensure_state to re-sync the baseline to the live
            # scope, or the first detect would book the checkpoint-vs-
            # ring delta as phantom bad steps
            self._cum_scope = None
        return len(self._window)

    # -- scalar reads ----------------------------------------------------
    @staticmethod
    def _scalar(scope, name):
        v = scope.get(name)
        if v is None:
            return None
        return float(np.asarray(v).reshape(-1)[0])

    def _loss_value(self, fetch_names, fetches):
        loss_var = self.plan.get("loss_var")
        if not loss_var or not fetch_names:
            return None
        for n, v in zip(fetch_names, fetches):
            if n == loss_var:
                try:
                    return float(np.mean(np.asarray(v, np.float32)))
                except (TypeError, ValueError):
                    return None
        return None

    # -- the decision ----------------------------------------------------
    def _classify(self, scope, loss, chain):
        """(kind, n_events) of this step — None when healthy.  For a
        run_steps chain the in-graph cumulative counter is consulted
        (only the final iteration's found_inf survives to the host); a
        single step skips that extra host read — found_inf alone is the
        exact answer."""
        found = self._scalar(scope, self.plan["found_var"])
        delta = 0
        if chain or (found is not None and found > 0):
            cum = self._scalar(scope, self.plan["bad_total_var"])
            if cum is not None:
                delta = max(0, int(round(cum - self._bad_total_seen)))
                self._bad_total_seen = cum
        if delta or (found is not None and found > 0):
            return "grad", max(1, delta)
        if loss is not None and not np.isfinite(loss):
            return "loss", 1
        if (loss is not None and self.spike_zscore > 0
                and self._ema is not None
                and self._good_samples >= self.spike_warmup):
            z = abs(loss - self._ema) / ((self._emvar + _EPS) ** 0.5)
            if z > self.spike_zscore:
                return "spike", 1
        return None, 0

    def _observe_good(self, loss):
        self._good_samples += 1
        if loss is None:
            return
        if self._ema is None:
            self._ema, self._emvar = loss, 0.0
            return
        dev = loss - self._ema
        self._ema += (1.0 - _EMA_BETA) * dev
        self._emvar = _EMA_BETA * (self._emvar
                                   + (1.0 - _EMA_BETA) * dev * dev)

    def post_step(self, scope, fetch_names=None, fetches=None,
                  chain=False):
        """Evaluate the step (or, with chain=True, the run_steps chain)
        that just ran.  Returns "ok", "skip" or "replay"; raises
        RuntimeError under action=raise on a bad step.  The caller
        re-dispatches the SAME feed once on "replay"."""
        self._steps_seen += 1
        loss = self._loss_value(fetch_names, fetches or [])
        if self.plan.get("loss_scaling"):
            scale = self._scalar(scope, self.plan["scale_var"])
            if scale is not None:
                _m_loss_scale().labels(lane=self.lane).set(scale)
        kind, n_events = self._classify(scope, loss, chain)
        replaying, self._replaying = self._replaying, False
        if kind is None:
            self._observe_good(loss)
            return "ok"
        _m_bad_steps().labels(kind=kind, action=self.action).inc(
            max(1, n_events))
        # flight-recorder evidence (observability/profiling.py): the bad
        # step lands in the attribution ring and triggers the JSONL
        # postmortem dump, so a poisoned run can be reconstructed from
        # the last N steps' phase breakdowns
        from paddle_tpu.observability import profiling as _profiling

        _profiling.note_health_event(kind, self.action, self.lane,
                                     step=self._steps_seen,
                                     replay=replaying)
        from paddle_tpu.observability import events

        if events.enabled():
            events.emit("health_bad_step", kind=kind, action=self.action,
                        lane=self.lane, step=self._steps_seen,
                        loss=loss, replay=replaying)
        if self.action == "raise":
            raise RuntimeError(
                f"health sentinel: non-finite/anomalous step detected "
                f"(kind={kind}, lane={self.lane}) — "
                f"FLAGS_health_action=raise preserves the "
                f"FLAGS_check_nan_inf fail-fast contract")
        if self.action == "rollback" and not replaying:
            if self.restore(scope):
                self._replaying = True
                return "replay"
        # skip — or a replay that is still bad, or an empty window:
        # the in-graph gate already masked a grad-kind update; a spike
        # under skip is booked and stands (reverting needs rollback)
        return "skip"
