"""Structured JSONL event log: step / round / lifecycle events with a
shared schema across trainers and pservers.

Each line is one JSON object:

    {"ts": <wall seconds>, "mono": <monotonic seconds>, "event": <name>,
     "run_id": ..., "trace_id": ..., "pid": ..., "role": ..., "rank": ...,
     ...caller fields}

Opt-in: nothing is written unless `FLAGS_event_log_dir` (or the
``PT_EVENT_LOG_DIR`` env var, which wins — the launcher sets it for
children) points at a directory.  Each process appends to its own file
(``events_<role><rank>_<pid>.jsonl``) so concurrent writers never
interleave partial lines; `tools/merge_traces.py` and offline analysis
read the per-process files side by side keyed on trace_id.

`emit()` is safe to call unconditionally from hot paths: when disabled it
is one attribute check; when enabled it is one json.dumps + buffered
write under a lock.  IO failures disable the log with a warning — losing
telemetry must never kill training.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings

from . import tracing

__all__ = ["EventLog", "emit", "enabled", "configure", "get_log",
           "read_events"]

_lock = threading.Lock()
_log = None          # active EventLog, None = disabled
_configured = False  # lazy env/flag probe ran


class EventLog:
    """One process's append-only JSONL event stream."""

    def __init__(self, path):
        self.path = str(path)
        self._lock = threading.Lock()
        self._fh = open(self.path, "a", encoding="utf-8")
        self._identity = tracing.process_identity()
        self._run_id = tracing.run_id()

    def emit(self, event, **fields):
        rec = {"ts": time.time(), "mono": time.monotonic(),
               "event": str(event), "run_id": self._run_id,
               **self._identity, **fields}
        line = json.dumps(rec, default=str)
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def _resolve_dir():
    """PT_EVENT_LOG_DIR env wins (launcher contract); else the flag —
    read lazily and tolerantly, so this module imports without fluid."""
    d = os.environ.get("PT_EVENT_LOG_DIR")
    if d:
        return d
    try:
        from paddle_tpu.fluid import flags
        return flags.flag("event_log_dir")
    except Exception:
        return ""


def configure(path=None):
    """(Re)configure the process event log.  path=None re-probes the env/
    flag surface; an empty resolution disables.  Returns the active log
    (or None)."""
    global _log, _configured
    with _lock:
        _configured = True
        if _log is not None:
            _log.close()
            _log = None
        try:
            if path is None:
                d = _resolve_dir()
                if not d:
                    return None
                os.makedirs(d, exist_ok=True)
                ident = tracing.process_identity()
                path = os.path.join(
                    d, f"events_{ident['role']}{ident['rank']}_"
                       f"{ident['pid']}.jsonl")
            _log = EventLog(path)
        except OSError as e:
            # losing telemetry must never kill training: an uncreatable
            # dir (read-only FS, bad PT_EVENT_LOG_DIR) disables the log
            warnings.warn(f"event log disabled ({e})")
            _log = None
        return _log


def get_log():
    """The active EventLog, probing the env/flag surface on first call."""
    if not _configured:
        configure()
    return _log


def enabled() -> bool:
    return get_log() is not None


def emit(event, **fields):
    """Write one event if the log is enabled; never raises."""
    log = get_log()
    if log is None:
        return
    try:
        log.emit(event, **fields)
    except Exception as e:
        global _log
        warnings.warn(f"event log write failed, disabling ({e})")
        with _lock:
            _log = None


def read_events(path):
    """Parse one JSONL event file -> list of dicts (analysis/tests)."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
