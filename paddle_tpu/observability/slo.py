"""Declarative SLOs + multi-window multi-burn-rate alerts over the
metrics registry.

An `SLOSpec` names an objective over families that ALREADY exist in the
registry — no new instrumentation required to put an SLO on a surface:

  availability   bad / total counter selectors (label-filtered sums),
                 e.g. bad=pt_serve_failovers_total{router="drill"} over
                 total=pt_serve_requests_total
  latency        a histogram + threshold: "bad" is every observation
                 above the smallest bucket bound >= the threshold (the
                 tightest judgement a fixed-bucket histogram supports),
                 "total" is the observation count

`SLOEngine.evaluate()` snapshots the registry, appends (t, bad, total)
per spec to a sample ring, and computes the error RATE over each alert
window from the ring deltas.  Burn rate = (bad/total over the window) /
(1 - objective) — burn 1.0 spends the budget exactly at the objective;
the SRE-workbook multi-window pairs fire when BOTH the short and long
window burn above the pair's threshold, and an active alert clears when
the SHORT window drops back below it (hysteresis: the long window alone
must not hold an alert up after the bleeding stopped):

  page    5 m /  1 h   burn > 14.4   (2% of a 30-day budget in 1 h)
  ticket  30 m /  6 h  burn >  6.0   (5% of a 30-day budget in 6 h)

``window_scale`` shrinks every window proportionally — how the fault
drill (serving/drill.py) runs the same arithmetic at second scale and
asserts the availability alert FIRES during a replica kill and CLEARS
after failover recovery.

Surfaces: `pt_slo_burn_rate{slo,window}` + `pt_slo_error_budget_
remaining{slo}` gauges, `pt_slo_alerts_total{slo,severity}` counter,
JSONL `slo_alert` events, and the `/sloz` exposition page.
`FLAGS_slo_specs` (see `parse_spec`) + `FLAGS_slo_eval_interval_s`
drive the flag-configured background evaluator (`ensure_from_flags`).

Stdlib-only; injectable clock for deterministic tests.
"""

from __future__ import annotations

import math
import threading
import time

from . import events as _events
from . import metrics as _metrics

__all__ = ["SLOSpec", "SLOEngine", "BurnWindow", "parse_spec",
           "parse_specs", "DEFAULT_WINDOWS", "sloz_payload",
           "ensure_from_flags", "stop_flag_engine"]


class BurnWindow:
    """One multi-window alert rule: fire when burn(short) AND burn(long)
    exceed ``threshold``; clear when burn(short) falls below it."""

    __slots__ = ("severity", "short_s", "long_s", "threshold")

    def __init__(self, severity, short_s, long_s, threshold):
        self.severity = str(severity)
        self.short_s = float(short_s)
        self.long_s = float(long_s)
        self.threshold = float(threshold)

    def scaled(self, scale):
        return BurnWindow(self.severity, self.short_s * scale,
                          self.long_s * scale, self.threshold)


# the SRE-workbook pairs for a 30-day budget
DEFAULT_WINDOWS = (
    BurnWindow("page", 300.0, 3600.0, 14.4),
    BurnWindow("ticket", 1800.0, 21600.0, 6.0),
)


def _sum_matching(fam, filters):
    """Sum a family's counter samples whose labels satisfy ``filters``
    (a {label: value} subset match)."""
    if not fam:
        return 0.0
    names = tuple(fam.get("label_names", ()))
    total = 0.0
    for key, val in fam.get("samples", {}).items():
        labels = dict(zip(names, key))
        if all(labels.get(k) == v for k, v in filters.items()):
            total += float(val)
    return total


def _hist_bad_total(fam, filters, threshold_s):
    """(bad, total) for a latency objective: observations above the
    smallest bucket bound >= threshold vs all observations."""
    if not fam:
        return 0.0, 0.0
    names = tuple(fam.get("label_names", ()))
    bad = total = 0.0
    for key, sample in fam.get("samples", {}).items():
        labels = dict(zip(names, key))
        if not all(labels.get(k) == v for k, v in filters.items()):
            continue
        count = float(sample.get("count") or 0)
        total += count
        under = 0.0
        for le, cum in sample.get("buckets") or ():
            if le >= threshold_s and not math.isinf(le):
                under = float(cum)
                break
        else:
            under = count  # threshold beyond the last finite bound
        bad += max(count - under, 0.0)
    return bad, total


class SLOSpec:
    """One objective.  ``kind="availability"``: ``bad``/``total`` are
    ``(family_name, {label: value})`` counter selectors.
    ``kind="latency"``: ``hist`` is a histogram selector and
    ``threshold_s`` the latency bound; objective applies to the fraction
    under the bound."""

    def __init__(self, name, kind, objective, bad=None, total=None,
                 hist=None, threshold_s=None):
        if not 0.0 < float(objective) < 1.0:
            raise ValueError(
                f"slo {name!r}: objective must be in (0, 1), got "
                f"{objective}")
        self.name = str(name)
        self.kind = str(kind)
        self.objective = float(objective)
        if self.kind == "availability":
            if not bad or not total:
                raise ValueError(
                    f"slo {name!r}: availability needs bad= and total= "
                    f"counter selectors")
            self.bad = (str(bad[0]), dict(bad[1] or {}))
            self.total = (str(total[0]), dict(total[1] or {}))
            self.hist = None
            self.threshold_s = None
        elif self.kind == "latency":
            if not hist or threshold_s is None:
                raise ValueError(
                    f"slo {name!r}: latency needs hist= and threshold_s=")
            self.hist = (str(hist[0]), dict(hist[1] or {}))
            self.threshold_s = float(threshold_s)
            self.bad = self.total = None
        else:
            raise ValueError(
                f"slo {name!r}: kind must be 'availability' or "
                f"'latency', got {kind!r}")

    def counts(self, snapshot):
        """(bad, total) cumulative counts from a registry snapshot."""
        if self.kind == "availability":
            return (_sum_matching(snapshot.get(self.bad[0]), self.bad[1]),
                    _sum_matching(snapshot.get(self.total[0]),
                                  self.total[1]))
        return _hist_bad_total(snapshot.get(self.hist[0]), self.hist[1],
                               self.threshold_s)

    def describe(self):
        if self.kind == "availability":
            return {"name": self.name, "kind": self.kind,
                    "objective": self.objective,
                    "bad": [self.bad[0], self.bad[1]],
                    "total": [self.total[0], self.total[1]]}
        return {"name": self.name, "kind": self.kind,
                "objective": self.objective,
                "hist": [self.hist[0], self.hist[1]],
                "threshold_s": self.threshold_s}


# ---------------------------------------------------------------------------
# spec grammar (FLAGS_slo_specs)
# ---------------------------------------------------------------------------


def _parse_selector(text):
    """'family{label=value,label2=value2}' -> (family, {label: value})."""
    text = text.strip()
    if "{" not in text:
        return text, {}
    fam, _, rest = text.partition("{")
    body = rest.rstrip("}")
    filters = {}
    for pair in filter(None, (p.strip() for p in body.split(","))):
        k, sep, v = pair.partition("=")
        if not sep or not k.strip():
            raise ValueError(f"bad selector filter {pair!r} in {text!r}")
        filters[k.strip()] = v.strip().strip('"')
    return fam.strip(), filters


def parse_spec(text):
    """One spec from the FLAGS_slo_specs grammar — '|'-separated fields:

      name|availability|bad=<sel>|total=<sel>|objective=0.999
      name|latency|hist=<sel>|threshold=0.25|objective=0.99

    where <sel> is ``family`` or ``family{label=value,...}``."""
    parts = [p.strip() for p in text.split("|") if p.strip()]
    if len(parts) < 3:
        raise ValueError(f"slo spec needs name|kind|fields..., got "
                         f"{text!r}")
    name, kind = parts[0], parts[1]
    fields = {}
    for p in parts[2:]:
        k, sep, v = p.partition("=")
        if not sep:
            raise ValueError(f"bad slo spec field {p!r} in {text!r}")
        fields[k.strip()] = v.strip()
    objective = float(fields.pop("objective", 0.999))

    def _need(key):
        try:
            return fields.pop(key)
        except KeyError:
            raise ValueError(f"slo spec {name!r} ({kind}) is missing "
                             f"the {key}= field: {text!r}") from None

    if kind == "availability":
        spec = SLOSpec(name, kind, objective,
                       bad=_parse_selector(_need("bad")),
                       total=_parse_selector(_need("total")))
    elif kind == "latency":
        spec = SLOSpec(name, kind, objective,
                       hist=_parse_selector(_need("hist")),
                       threshold_s=float(_need("threshold")))
    else:
        raise ValueError(f"slo spec kind must be availability|latency, "
                         f"got {kind!r}")
    if fields:
        raise ValueError(f"unknown slo spec fields {sorted(fields)} in "
                         f"{text!r}")
    return spec


def parse_specs(text):
    """';'-separated multi-spec form of `parse_spec` (the flag value)."""
    return [parse_spec(chunk) for chunk in text.split(";")
            if chunk.strip()]


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


def _m_burn():
    return _metrics.gauge(
        "pt_slo_burn_rate",
        "Error-budget burn rate per SLO and alert window (1.0 spends "
        "the budget exactly at the objective)", labels=("slo", "window"))


def _m_budget():
    return _metrics.gauge(
        "pt_slo_error_budget_remaining",
        "Fraction of the SLO's error budget remaining over the longest "
        "alert window (1 = untouched, <=0 = spent)", labels=("slo",))


def _m_alerts():
    return _metrics.counter(
        "pt_slo_alerts_total",
        "Multi-window burn-rate alerts fired, by SLO and severity",
        labels=("slo", "severity"))


class SLOEngine:
    """Periodic evaluator over a set of SLOSpecs.  `evaluate()` may be
    driven by the built-in background thread (`start()`), by a caller's
    loop (the drill), or manually with an injected ``now`` (tests)."""

    _MAX_SAMPLES = 4096

    def __init__(self, specs=(), windows=DEFAULT_WINDOWS,
                 window_scale=1.0, registry=None, clock=None):
        scale = float(window_scale)
        if scale <= 0:
            raise ValueError(f"window_scale must be > 0, got {scale}")
        self.windows = tuple(w.scaled(scale) for w in windows)
        self.specs = list(specs)
        self._registry = registry or _metrics.REGISTRY
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        # per spec name: deque of (t, bad, total) cumulative samples
        self._samples = {s.name: [] for s in self.specs}
        # (spec, severity) -> alert state
        self._alerts = {
            (s.name, w.severity): {
                "active": False, "fired_total": 0,
                "t_fired": None, "t_cleared": None,
                "burn_short": 0.0, "burn_long": 0.0,
            }
            for s in self.specs for w in self.windows}
        self._thread = None
        self._stop = threading.Event()

    def add(self, spec):
        with self._lock:
            self.specs.append(spec)
            self._samples[spec.name] = []
            for w in self.windows:
                self._alerts[(spec.name, w.severity)] = {
                    "active": False, "fired_total": 0,
                    "t_fired": None, "t_cleared": None,
                    "burn_short": 0.0, "burn_long": 0.0,
                }
        return spec

    # -- arithmetic --------------------------------------------------------

    @staticmethod
    def _window_ratio(samples, now, window_s):
        """Error ratio over [now - window_s, now] from cumulative
        (t, bad, total) samples: delta bad / delta total, with an
        all-bad 1.0 when bad moved but total did not (a failure path
        that admits nothing still burns budget)."""
        if not samples:
            return 0.0
        cutoff = now - window_s
        base = samples[0]
        for s in samples:
            if s[0] <= cutoff:
                base = s
            else:
                break
        head = samples[-1]
        d_bad = max(head[1] - base[1], 0.0)
        d_total = max(head[2] - base[2], 0.0)
        if d_total <= 0.0:
            return 1.0 if d_bad > 0 else 0.0
        return min(d_bad / d_total, 1.0)

    def evaluate(self, now=None):
        """One evaluation pass: sample the registry, update burn gauges
        and alert state.  Returns {spec: {severity: alert_state}}."""
        now = self._clock() if now is None else float(now)
        snap = self._registry.snapshot()
        burn_g, budget_g, alerts_c = _m_burn(), _m_budget(), _m_alerts()
        out = {}
        with self._lock:
            specs = list(self.specs)
        for spec in specs:
            bad, total = spec.counts(snap)
            with self._lock:
                samples = self._samples[spec.name]
                samples.append((now, bad, total))
                del samples[:-self._MAX_SAMPLES]
                samples = list(samples)
            budget = 1.0 - spec.objective
            longest = max(w.long_s for w in self.windows)
            ratio_long = self._window_ratio(samples, now, longest)
            budget_g.labels(slo=spec.name).set(
                1.0 - ratio_long / budget)
            out[spec.name] = {}
            for w in self.windows:
                b_short = self._window_ratio(samples, now,
                                             w.short_s) / budget
                b_long = self._window_ratio(samples, now,
                                            w.long_s) / budget
                burn_g.labels(slo=spec.name,
                              window=f"{w.severity}_short").set(b_short)
                burn_g.labels(slo=spec.name,
                              window=f"{w.severity}_long").set(b_long)
                with self._lock:
                    st = self._alerts[(spec.name, w.severity)]
                    st["burn_short"], st["burn_long"] = b_short, b_long
                    fire = (not st["active"] and b_short > w.threshold
                            and b_long > w.threshold)
                    clear = st["active"] and b_short < w.threshold
                    if fire:
                        st["active"] = True
                        st["fired_total"] += 1
                        st["t_fired"] = now
                        st["t_cleared"] = None
                    elif clear:
                        st["active"] = False
                        st["t_cleared"] = now
                    state = dict(st)
                if fire:
                    alerts_c.labels(slo=spec.name,
                                    severity=w.severity).inc()
                    _events.emit("slo_alert", slo=spec.name,
                                 severity=w.severity, state="fired",
                                 burn_short=b_short, burn_long=b_long,
                                 threshold=w.threshold)
                elif clear:
                    _events.emit("slo_alert", slo=spec.name,
                                 severity=w.severity, state="cleared",
                                 burn_short=b_short, burn_long=b_long,
                                 threshold=w.threshold)
                out[spec.name][w.severity] = state
        return out

    def alert_state(self, slo, severity):
        with self._lock:
            return dict(self._alerts[(slo, severity)])

    # -- background thread -------------------------------------------------

    def start(self, interval_s=None):
        if interval_s is None:
            from paddle_tpu.fluid import flags as _flags

            interval_s = float(_flags.flag("slo_eval_interval_s"))
        interval_s = max(float(interval_s), 0.01)
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()

            def _loop():
                while not self._stop.wait(timeout=interval_s):
                    try:
                        self.evaluate()
                    except Exception:
                        pass  # an eval hiccup must not kill the loop

            self._thread = threading.Thread(
                target=_loop, daemon=True, name="pt-slo-eval")
            self._thread.start()
        return self

    def stop(self):
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=5)

    def payload(self):
        """The /sloz JSON payload for this engine."""
        with self._lock:
            specs = [s.describe() for s in self.specs]
            alerts = {f"{name}/{sev}": dict(st)
                      for (name, sev), st in self._alerts.items()}
        return {
            "specs": specs,
            "windows": [{"severity": w.severity, "short_s": w.short_s,
                         "long_s": w.long_s, "threshold": w.threshold}
                        for w in self.windows],
            "alerts": alerts,
        }


# ---------------------------------------------------------------------------
# /sloz + flag wiring
# ---------------------------------------------------------------------------

# engines visible on /sloz (every constructed-and-registered engine; the
# flag-driven one registers itself)
_engines: list = []
_engines_lock = threading.Lock()
_flag_engine = None


def track(engine):
    with _engines_lock:
        if engine not in _engines:
            _engines.append(engine)
    return engine


def untrack(engine):
    with _engines_lock:
        if engine in _engines:
            _engines.remove(engine)


def sloz_payload():
    with _engines_lock:
        engines = list(_engines)
    return {"engines": [e.payload() for e in engines],
            "n_engines": len(engines)}


def ensure_from_flags():
    """Start the flag-configured SLO evaluator once per process when
    FLAGS_slo_specs is non-empty.  Never fatal: a bad spec warns and
    disables (a typo must not take the serving process down)."""
    global _flag_engine
    if _flag_engine is not None:
        return _flag_engine
    try:
        from paddle_tpu.fluid import flags as _flags

        text = str(_flags.flag("slo_specs"))
    except Exception:
        return None
    if not text.strip():
        return None
    with _engines_lock:
        if _flag_engine is not None:
            return _flag_engine
        try:
            specs = parse_specs(text)
        except Exception as e:
            import warnings

            warnings.warn(f"FLAGS_slo_specs: {e}; SLO evaluator disabled")
            return None
        engine = SLOEngine(specs)
        _flag_engine = engine
        _engines.append(engine)
    _flag_engine.start()
    return _flag_engine


def stop_flag_engine():
    global _flag_engine
    with _engines_lock:
        engine, _flag_engine = _flag_engine, None
        if engine in _engines:
            _engines.remove(engine)
    if engine is not None:
        engine.stop()


try:
    from . import exposition as _exposition

    _exposition.register_page("/sloz", sloz_payload)
except Exception:
    pass
