"""Step-time attribution: phase-decomposed step timing, MFU/roofline
accounting per compiled signature, and a bounded flight recorder
(docs/OBSERVABILITY.md "Step-time attribution").

Before this module, ``pt_step_seconds`` was one opaque histogram: a slow
step could be host feed staging, Python dispatch, device compute,
collective wait, or fetch sync, and nothing could say which.  This is
the ONE audited timing implementation for the stack
(tools/lint_observability.py flags raw ``time.time()``/``perf_counter``
pairs anywhere else):

- phase timing   every execution lane (single-device Executor, run_steps
                 chain, transpiler DP, hybrid, GSPMD, serving) wraps its
                 dispatch in `step_phases(lane, label)` and brackets the
                 four canonical phases — ``feed_prep`` (scope staging +
                 device_put), ``dispatch`` (the jitted call; trace on a
                 signature's first run), ``device_wait``
                 (`block_until_ready` delta = device execution the host
                 had to wait out), ``fetch_sync`` (scope write-back +
                 host ops).  Exported as
                 ``pt_step_phase_seconds{phase,lane}`` histograms and
                 per-phase chrome-trace spans (kind ``phase``) merged
                 into the PT_TRACE timeline.  FLAGS_profile_phases
                 gates the per-phase work (and the per-step
                 `block_until_ready` the device_wait phase needs); with
                 it off the recorder still times the step total so
                 per-signature stats and the flight recorder stay live.

- MFU/roofline   `note_cost` (fed by `_JitExecutable.cost_analysis`) and
                 `note_collectives` (fed by compiled-HLO inspection)
                 join the measured device seconds with a per-platform
                 peak table (`device_peaks`, FLAGS_device_peak_*
                 overrides) into ``pt_mfu{signature}`` and
                 ``pt_roofline_bound{signature,bound}`` gauges: the
                 compute/memory/comm time lower bounds
                 (flops/peak_flops, bytes/peak_bw, comm_bytes/peak_ici)
                 name which wall the signature sits against — the
                 Tensor Processing Primitives (arXiv:2104.05755)
                 roofline framing as a scraped verdict.

- HLO inventory  `hlo_inventory` / `hlo_collective_bytes` /
                 `hlo_collective_counts`: the per-category accounting of
                 an optimized HLO module's cross-device collectives
                 (promoted here from parallel/gspmd/executor.py — the
                 gspmd lane re-exports them).

- flight record  a bounded ring (FLAGS_flight_recorder_steps) of the
                 last N steps' phase breakdowns + queue depths + health
                 events.  `dump_flight_record()` writes a JSONL
                 postmortem; automatic dumps fire on a slow-step
                 z-score over the per-lane rolling EMA
                 (FLAGS_profile_slow_step_zscore) and on health-sentinel
                 bad steps (`note_health_event`, wired from
                 health/sentinel.py) — a wedged or anomalous run leaves
                 evidence instead of one opaque histogram.

- /profilez      a JSON status page on every MetricsServer: per-signature
                 MFU + roofline verdict, per-lane phase p50/p95, the
                 feed-bound verdict (prefetcher stall vs step time), and
                 flight-recorder state.  `attribution_digest()` is the
                 same payload compacted for BENCH_*.json records.

Import cost is stdlib-only (the observability-package contract); jax,
fluid.flags and fluid.profiler are imported lazily inside functions.
"""

from __future__ import annotations

import collections
import json
import os
import re
import sys
import threading
import time
import warnings

from . import metrics as _metrics
from . import tracing as _tracing

__all__ = [
    "step_phases", "NullRecorder", "note_step", "note_cost",
    "note_collectives",
    "note_health_event", "device_peaks", "roofline",
    "hlo_inventory", "hlo_collective_bytes", "hlo_collective_counts",
    "flight_recorder", "dump_flight_record", "profilez_payload",
    "attribution_digest", "signature_stats", "reset",
    "PHASES",
]

# the canonical phase decomposition of one executed step, in order
PHASES = ("feed_prep", "dispatch", "device_wait", "fetch_sync")

# phase durations span ~100 us (feed staging) to multi-second compiles:
# extend the default latency buckets downward so sub-ms phases resolve
_PHASE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                  0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_EMA_BETA = 0.9
_EPS = 1e-12


# ---------------------------------------------------------------------------
# metric accessors (lazy idempotent registration — the registry contract)
# ---------------------------------------------------------------------------


def _m_phase():
    return _metrics.histogram(
        "pt_step_phase_seconds",
        "Wall time of one step decomposed into named phases: feed_prep "
        "(scope staging + device transfer), dispatch (the jitted call), "
        "device_wait (block_until_ready delta = device execution the "
        "host waited out), fetch_sync (scope write-back + host ops)",
        labels=("phase", "lane"), buckets=_PHASE_BUCKETS)


def _m_mfu():
    return _metrics.gauge(
        "pt_mfu",
        "Model FLOPs utilization of the most recent steps per compiled "
        "signature: cost-model flops / (device seconds x platform peak "
        "flops, FLAGS_device_peak_flops override)",
        labels=("signature",))


def _m_roofline():
    return _metrics.gauge(
        "pt_roofline_bound",
        "Roofline verdict per compiled signature: 1 on the bound "
        "(compute|memory|comm) whose peak-rate time lower bound "
        "dominates, 0 elsewhere", labels=("signature", "bound"))


def _m_flight_dumps():
    return _metrics.counter(
        "pt_flight_dumps_total",
        "Flight-recorder JSONL postmortems written, by trigger reason "
        "(slow_step / health / explicit)", labels=("reason",))


# ---------------------------------------------------------------------------
# flags (read lazily and tolerantly — this module must import without fluid)
# ---------------------------------------------------------------------------


def _flag(name, default):
    try:
        from paddle_tpu.fluid import flags as _flags

        return _flags.flag(name)
    except Exception:
        return default


def _phases_enabled():
    return bool(_flag("profile_phases", False))


# ---------------------------------------------------------------------------
# phase recorder
# ---------------------------------------------------------------------------

_tls = threading.local()


class _PhaseSpan:
    __slots__ = ("_rec", "_name", "_t0")

    def __init__(self, rec, name):
        self._rec = rec
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb):
        dur = time.perf_counter() - self._t0
        self._rec._spans.append((self._name, self._t0, dur))
        return False


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class StepPhaseRecorder:
    """Times one executed step.  With FLAGS_profile_phases on, `phase()`
    brackets record the four named sub-phases and `wait()` blocks on the
    dispatched arrays so the device_wait phase measures real device
    time; with it off both are no-ops and only the step total (and the
    signature label) is deposited for `note_step` — per-signature stats
    and the flight recorder keep working at zero sync cost, preserving
    async dispatch pipelining."""

    __slots__ = ("lane", "label", "detailed", "_spans", "_t0")

    def __init__(self, lane, label, detailed):
        self.lane = lane
        self.label = label
        self.detailed = detailed
        self._spans = []  # (phase, start, dur)

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def phase(self, name):
        if not self.detailed:
            return _NULL_SPAN
        return _PhaseSpan(self, name)

    def wait(self, arrays):
        """Block until the dispatched device work completes — called
        inside the ``device_wait`` phase bracket.  A no-op with phases
        off: the per-step sync would serialize the donated-buffer
        dispatch pipeline the fetch-free training loop relies on."""
        if not self.detailed:
            return
        try:
            import jax

            jax.block_until_ready(arrays)
        except Exception:  # non-jax values (host-op outputs)
            pass

    def __exit__(self, et, ev, tb):
        if et is not None:
            return False
        total = time.perf_counter() - self._t0
        phases = {}
        for name, _start, dur in self._spans:
            phases[name] = phases.get(name, 0.0) + dur
        if self._spans:
            try:
                from paddle_tpu.fluid import profiler as _prof

                for name, start, dur in self._spans:
                    _prof._record("phase", f"{self.lane}:{name}", dur,
                                  start=start)
            except Exception:
                pass
            fam = _m_phase()
            for name, dur in phases.items():
                fam.labels(phase=name, lane=self.lane).observe(dur)
        # hand the breakdown to note_step (same thread, the lane books
        # its pt_step_seconds sample immediately after run() returns)
        _tls.pending = (self.lane, self.label,
                        phases if self._spans else None, total)
        return False


class NullRecorder:
    """Recorder-shaped no-op: nothing timed, nothing deposited.  For
    dispatches that must stay OUT of the attribution surface entirely —
    the serving lane's warmup batches (their duration is compile time,
    which would poison the serve-lane phase histograms and EMA exactly
    the way it is already kept out of the latency SLO histogram)."""

    detailed = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def phase(self, name):
        return _NULL_SPAN

    def wait(self, arrays):
        pass


def step_phases(lane, label, enabled=True):
    """The one entry point every execution lane wraps its dispatch in.
    ``enabled=False`` returns the NullRecorder (warmup/precompile
    dispatches that must not enter the attribution stats)."""
    if not enabled:
        return NullRecorder()
    return StepPhaseRecorder(lane, label, _phases_enabled())


def _pop_pending(lane):
    pending = getattr(_tls, "pending", None)
    if pending is not None and pending[0] == lane:
        _tls.pending = None
        return pending
    return None


# ---------------------------------------------------------------------------
# per-signature stats + MFU/roofline
# ---------------------------------------------------------------------------

_lock = threading.RLock()
_signatures: dict = {}  # label -> stats dict
_lane_ema: dict = {}    # lane -> [ema, emvar, samples]


def _sig(label):
    s = _signatures.get(label)
    if s is None:
        s = _signatures[label] = {
            "label": label, "lane": None, "steps": 0,
            "total_s": 0.0, "ema_step_s": None,
            "device_s_sum": 0.0, "device_steps": 0,
            "flops": None, "bytes_accessed": None,
            "transcendentals": None, "collective_bytes": None,
            "collective_counts": None,
        }
    return s


_TPU_PEAKS = (
    # device_kind substring -> (bf16 flops/s, HBM bytes/s, ICI bytes/s)
    # public per-chip specs, approximate where vendors publish ranges;
    # first match wins so "v5e"/"lite" must precede the bare "v5" (v5p)
    ("v6", (918e12, 1640e9, 448e9)),
    ("v5p", (459e12, 2765e9, 600e9)),
    ("v5e", (197e12, 819e9, 200e9)),
    ("lite", (197e12, 819e9, 200e9)),
    ("v5", (459e12, 2765e9, 600e9)),
    ("v4", (275e12, 1228e9, 300e9)),
    ("v3", (123e12, 900e9, 87e9)),
    ("v2", (45e12, 700e9, 62e9)),
)

# order-of-magnitude placeholders for the CPU container (documented in
# docs/OBSERVABILITY.md): MFU against a CPU "peak" is a smoke-test
# number, not a claim — override via FLAGS_device_peak_* for anything
# that matters
_CPU_PEAKS = (1e11, 2.5e10, 1e9)


def device_peaks():
    """(platform, peak_flops/s, peak_hbm_bytes/s, peak_ici_bytes/s) for
    the process's device 0.  FLAGS_device_peak_flops /
    FLAGS_device_peak_bandwidth / FLAGS_device_peak_ici_bandwidth
    (nonzero) override the table entry-wise.  Reads jax only when it is
    ALREADY imported — a /profilez scrape must never initialize a TPU
    runtime."""
    platform, peaks = "cpu", _CPU_PEAKS
    jx = sys.modules.get("jax")
    if jx is not None:
        try:
            dev = jx.devices()[0]
            platform = dev.platform
            if platform == "tpu":
                kind = getattr(dev, "device_kind", "").lower()
                for pat, p in _TPU_PEAKS:
                    if pat in kind:
                        peaks = p
                        break
        except Exception:
            pass
    flops = float(_flag("device_peak_flops", 0) or 0) or peaks[0]
    bw = float(_flag("device_peak_bandwidth", 0) or 0) or peaks[1]
    ici = float(_flag("device_peak_ici_bandwidth", 0) or 0) or peaks[2]
    return platform, flops, bw, ici


def roofline(flops, bytes_accessed, collective_bytes, peaks=None):
    """The roofline verdict for one step: time lower bounds at peak
    compute/memory/comm rates and which dominates.  `peaks` defaults to
    `device_peaks()`; any missing numerator contributes 0 (an
    unmeasured axis can never be named the bound)."""
    if peaks is None:
        _, pf, pbw, pici = device_peaks()
    else:
        pf, pbw, pici = peaks
    t = {
        "compute": (flops or 0.0) / max(pf, _EPS),
        "memory": (bytes_accessed or 0.0) / max(pbw, _EPS),
        "comm": (collective_bytes or 0.0) / max(pici, _EPS),
    }
    bound = max(t, key=t.get)
    return {"bound": bound if t[bound] > 0 else None,
            "t_compute_s": t["compute"], "t_memory_s": t["memory"],
            "t_comm_s": t["comm"]}


def _update_mfu(s):
    """Refresh the pt_mfu / pt_roofline_bound gauges for one signature
    (called under _lock whenever timing or cost changes)."""
    if not s["device_steps"] or not s["flops"]:
        return
    device_s = s["device_s_sum"] / s["device_steps"]
    if device_s <= 0:
        return
    _, pf, pbw, pici = device_peaks()
    mfu = s["flops"] / device_s / pf
    s["mfu"] = mfu
    _m_mfu().labels(signature=s["label"]).set(mfu)
    rl = roofline(s["flops"], s["bytes_accessed"],
                  s["collective_bytes"], peaks=(pf, pbw, pici))
    s["roofline"] = rl
    fam = _m_roofline()
    for bound in ("compute", "memory", "comm"):
        fam.labels(signature=s["label"], bound=bound).set(
            1.0 if rl["bound"] == bound else 0.0)


def note_cost(label, cost, collective_bytes=None):
    """Record a signature's XLA cost-model numbers (fed by
    `_JitExecutable.cost_analysis`).  `cost` is the cost_analysis dict
    ({"flops": ..., "bytes accessed": ...})."""
    get = cost.get if hasattr(cost, "get") else (lambda *_: None)
    with _lock:
        s = _sig(label)
        for key, field in (("flops", "flops"),
                           ("bytes accessed", "bytes_accessed"),
                           ("transcendentals", "transcendentals")):
            v = get(key)
            if v is not None:
                s[field] = float(v)
        if collective_bytes is not None:
            s["collective_bytes"] = float(collective_bytes)
        _update_mfu(s)


def note_collectives(label, hlo_bytes, counts=None):
    """Record a signature's compiled-HLO collective inventory (fed by
    the GSPMD executor's HLO capture)."""
    with _lock:
        s = _sig(label)
        s["collective_bytes"] = float(hlo_bytes)
        if counts is not None:
            s["collective_counts"] = dict(counts)
        _update_mfu(s)


def signature_stats():
    """Snapshot of the per-signature attribution table (tests + the
    /profilez render)."""
    with _lock:
        return {k: dict(v) for k, v in _signatures.items()}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded ring of the last N steps' attribution records plus health
    events.  Dumps a JSONL postmortem on demand or automatically (slow
    step, health bad step); auto-dumps are rate-limited to once per half
    ring so an anomaly storm cannot write unbounded files."""

    def __init__(self, keep=None):
        self._lock = threading.Lock()
        # an explicit keep pins the size; the flag-sized default tracks
        # FLAGS_flight_recorder_steps live (a set_flags mid-run resizes
        # on the next record)
        self._keep_from_flags = keep is None
        self.keep = int(keep if keep is not None
                        else _flag("flight_recorder_steps", 256))
        self._ring = collections.deque(maxlen=max(1, self.keep))
        self._seq = 0
        self._since_dump = 0
        self._attempts = 0  # filename counter; advances on failures too
        self.dumps = 0      # successful writes only
        self.last_dump_path = None
        self.last_dump_reason = None

    def _resize_from_flags(self):
        if not self._keep_from_flags:
            return
        keep = int(_flag("flight_recorder_steps", self.keep))
        if keep != self.keep and keep >= 1:
            self.keep = keep
            self._ring = collections.deque(self._ring, maxlen=keep)

    def record(self, rec):
        with self._lock:
            self._resize_from_flags()
            self._seq += 1
            self._since_dump += 1
            rec = dict(rec, seq=self._seq, ts=round(time.time(), 6))
            self._ring.append(rec)

    def snapshot(self):
        with self._lock:
            return list(self._ring)

    def maybe_auto_dump(self, reason, detail=None):
        """Auto-trigger path: dump unless one already fired within the
        last keep//2 records (the postmortem window would mostly repeat
        itself)."""
        with self._lock:
            if self._since_dump < max(1, self.keep // 2) and self.dumps:
                return None
        return self.dump(reason=reason, detail=detail)

    def _resolve_dir(self):
        d = _flag("flight_recorder_dir", "")
        if d:
            return d
        d = os.environ.get("PT_EVENT_LOG_DIR") or _flag("event_log_dir",
                                                        "")
        # final fallback is the system tempdir, NOT the cwd: auto-dumps
        # fire from library code (a health bad step mid-test-suite), and
        # postmortems must never litter a caller's working tree
        import tempfile

        return d or tempfile.gettempdir()

    def dump(self, path=None, reason="explicit", detail=None):
        """Write the ring as a JSONL postmortem: one meta header line,
        then one line per record (oldest first).  Returns the path, or
        None when writing failed (losing a postmortem must never kill
        the run).  The dumps counter and the auto-dump rate-limit window
        commit only AFTER a successful write — a full disk must neither
        suppress the next trigger's attempt nor report phantom dumps on
        /profilez."""
        with self._lock:
            records = list(self._ring)
            # attempt counter (always advances): filename uniqueness
            # even across failed writes
            self._attempts += 1
            n_dump = self._attempts
        try:
            if path is None:
                d = self._resolve_dir()
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d, f"flight_{os.getpid()}_{n_dump:03d}.jsonl")
            meta = {"flight_record": 1, "reason": reason,
                    "ts": round(time.time(), 6), "keep": self.keep,
                    "records": len(records),
                    **_tracing.process_identity()}
            if detail:
                meta["detail"] = detail
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(json.dumps(meta, default=str) + "\n")
                for rec in records:
                    fh.write(json.dumps(rec, default=str) + "\n")
        except OSError as e:
            warnings.warn(f"flight-recorder dump failed: {e}")
            return None
        with self._lock:
            self.dumps += 1
            self._since_dump = 0
            self.last_dump_path = path
            self.last_dump_reason = reason
        _m_flight_dumps().labels(reason=reason).inc()
        try:
            from . import events as _events

            if _events.enabled():
                _events.emit("flight_record_dump", reason=reason,
                             path=path, records=len(records))
        except Exception:
            pass
        return path

    def status(self):
        with self._lock:
            return {"keep": self.keep, "size": len(self._ring),
                    "steps_seen": self._seq, "dumps": self.dumps,
                    "last_dump_path": self.last_dump_path,
                    "last_dump_reason": self.last_dump_reason}


_flight = FlightRecorder()


def flight_recorder():
    return _flight


def dump_flight_record(path=None, reason="explicit"):
    """Explicitly write the flight-record postmortem (ops entry point)."""
    return _flight.dump(path=path, reason=reason)


def read_flight_record(path):
    """Parse one flight-record JSONL file -> (meta, records)."""
    with open(path, encoding="utf-8") as fh:
        lines = [json.loads(ln) for ln in fh if ln.strip()]
    if not lines:
        return {}, []
    return lines[0], lines[1:]


def _queue_depth_sample():
    """Best-effort prefetch queue depth at this step (None when the
    prefetcher never registered)."""
    fam = _metrics.REGISTRY.get("pt_prefetch_queue_depth")
    if fam is None:
        return None
    try:
        samples = fam._snapshot()["samples"]
        if not samples:
            return None
        return float(next(iter(samples.values())))
    except Exception:
        return None


# ---------------------------------------------------------------------------
# the step sink (fed by fluid.executor._record_step from every lane)
# ---------------------------------------------------------------------------


def note_step(lane, seconds=None, first_run=False):
    """Book one executed step into the attribution layer: per-signature
    stats (+ MFU refresh), the slow-step detector, and the flight
    recorder.  Consumes the phase breakdown the lane's
    `step_phases(...)` recorder deposited on this thread (if any);
    ``seconds=None`` uses the recorder's own step total."""
    pending = _pop_pending(lane)
    label, phases = lane, None
    if pending is not None:
        _plane, label, phases, total = pending
        if seconds is None:
            seconds = total
    if seconds is None:
        return
    ensure_profilez_page()
    slow = None
    with _lock:
        s = _sig(label)
        s["lane"] = lane
        s["steps"] += 1
        s["total_s"] += seconds
        if not first_run:
            # a signature's first run includes the lazy XLA compile —
            # folding it into the EMA/MFU would poison both
            prev = s["ema_step_s"]
            s["ema_step_s"] = (seconds if prev is None else
                               prev + (1.0 - _EMA_BETA) * (seconds - prev))
            device_s = seconds
            if phases:
                # device time = dispatch + device_wait: the span from
                # handing the step to jax to the computation's completion
                device_s = (phases.get("dispatch", 0.0)
                            + phases.get("device_wait", 0.0)) or seconds
            s["device_s_sum"] += device_s
            s["device_steps"] += 1
            _update_mfu(s)
            # slow-step z-score over the per-lane rolling EMA (the PR-10
            # EMA machinery applied to wall time)
            zthresh = float(_flag("profile_slow_step_zscore", 8.0) or 0)
            ema = _lane_ema.setdefault(lane, [None, 0.0, 0])
            if ema[0] is None:
                ema[0] = seconds
            else:
                dev = seconds - ema[0]
                z = abs(dev) / ((ema[1] + _EPS) ** 0.5)
                if (zthresh > 0 and ema[2] >= 8 and dev > 0
                        and z > zthresh):
                    slow = {"z": round(z, 2), "ema_s": round(ema[0], 6)}
                ema[0] += (1.0 - _EMA_BETA) * dev
                ema[1] = _EMA_BETA * (ema[1]
                                      + (1.0 - _EMA_BETA) * dev * dev)
            ema[2] += 1
    rec = {"kind": "step", "lane": lane, "label": label,
           "seconds": round(seconds, 6), "first_run": bool(first_run)}
    if phases:
        rec["phases"] = {k: round(v, 6) for k, v in phases.items()}
    qd = _queue_depth_sample()
    if qd is not None:
        rec["prefetch_queue_depth"] = qd
    if slow is not None:
        rec["slow_step"] = slow
    _flight.record(rec)
    if slow is not None:
        _flight.maybe_auto_dump(
            "slow_step", detail={"lane": lane, "seconds": seconds, **slow})


def note_health_event(kind, action, lane, step=None, replay=False):
    """Health-sentinel hook (health/sentinel.py books its bad-step
    metric through here too): the event lands in the flight ring and
    triggers the postmortem dump — a poisoned run leaves evidence."""
    _flight.record({"kind": "health", "event": "bad_step",
                    "detect": kind, "action": action, "lane": lane,
                    "step": step, "replay": bool(replay)})
    _flight.maybe_auto_dump(
        "health", detail={"detect": kind, "action": action, "lane": lane})


# ---------------------------------------------------------------------------
# HLO inventory (promoted from parallel/gspmd/executor.py)
# ---------------------------------------------------------------------------

_HLO_ITEMSIZE = {"s8": 1, "u8": 1, "pred": 1, "bf16": 2, "f16": 2,
                 "s16": 2, "u16": 2, "f32": 4, "s32": 4, "u32": 4,
                 "f64": 8, "s64": 8, "u64": 8}

_COLLECTIVE_KINDS = ("all-to-all", "all-gather", "collective-permute",
                     "all-reduce", "reduce-scatter")

_COLLECTIVE_RE = re.compile(
    r"=\s+(\([^)]*\)|[a-z0-9]+\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(" + "|".join(_COLLECTIVE_KINDS) + r")(-start)?\(")


def _shape_bytes(tok):
    m = re.match(r"([a-z0-9]+)\[([0-9,]*)\]", tok)
    if m is None:
        return 0
    dt, dims = m.groups()
    size = 1
    for d in dims.split(","):
        if d:
            size *= int(d)
    return size * _HLO_ITEMSIZE.get(dt, 4)


def hlo_inventory(hlo):
    """Per-category inventory of an optimized per-device SPMD HLO
    module's cross-device collectives: ``{kind: {"count": n, "bytes":
    b}}`` plus a ``total`` entry.  Async ``-start`` forms (TPU's
    start/done pairs) report a tuple that ALIASES the operand beside the
    result, so their tuple bytes are halved — else on-chip numbers would
    double-count against the sync-form CPU ones and every A/B that gates
    on them would be incomparable."""
    out = {}
    total_bytes = total_count = 0
    for m in _COLLECTIVE_RE.finditer(hlo):
        nbytes = sum(_shape_bytes(t)
                     for t in re.findall(r"[a-z0-9]+\[[0-9,]*\]",
                                         m.group(1)))
        if m.group(3):  # "-start": (operand alias, result) tuple
            nbytes //= 2
        kind = m.group(2)
        ent = out.setdefault(kind, {"count": 0, "bytes": 0})
        ent["count"] += 1
        ent["bytes"] += nbytes
        total_bytes += nbytes
        total_count += 1
    out["total"] = {"count": total_count, "bytes": total_bytes}
    return out


def hlo_collective_bytes(hlo):
    """Total output bytes of every cross-device collective instruction —
    the wire payload the executable moves per step (the accounting the
    ring wire-bytes cross-check and ``pt_gspmd_resharding_bytes`` use)."""
    return hlo_inventory(hlo)["total"]["bytes"]


def hlo_collective_counts(hlo):
    """{collective kind: instruction count} over an optimized HLO module."""
    inv = hlo_inventory(hlo)
    return {k: v["count"] for k, v in inv.items() if k != "total"}


# ---------------------------------------------------------------------------
# /profilez + the bench digest
# ---------------------------------------------------------------------------


def _phase_quantiles():
    """{lane: {phase: {p50, p95, count}}} from the registry histogram."""
    fam = _metrics.REGISTRY.get("pt_step_phase_seconds")
    if fam is None:
        return {}
    out = {}
    snap = fam._snapshot()
    for key, h in snap["samples"].items():
        labels = dict(zip(snap["label_names"], key))
        lane = labels.get("lane", "?")
        phase = labels.get("phase", "?")
        out.setdefault(lane, {})[phase] = {
            "p50": _rq(_metrics.hist_quantile(h, 0.50)),
            "p95": _rq(_metrics.hist_quantile(h, 0.95)),
            "sum": round(h["sum"], 6),
            "count": h["count"],
        }
    return out


def _rq(v):
    return None if v is None else round(float(v), 6)


def _sig4(v):
    """4 significant figures at any magnitude — a tiny model's 1e-8 MFU
    must not round to 0 the way a fixed-decimal round would."""
    return None if v is None else float(f"{float(v):.4g}")


def _family_sum(name):
    fam = _metrics.REGISTRY.get(name)
    if fam is None:
        return 0.0
    total = 0.0
    snap = fam._snapshot()
    for sample in snap["samples"].values():
        total += sample["sum"] if isinstance(sample, dict) else sample
    return total


def feed_verdict():
    """The ROADMAP "feed is never the bottleneck" claim as a number:
    consumer stall seconds (pt_prefetch_stall_seconds_total — blocked on
    an empty queue AFTER the pipeline filled) over executed step seconds
    (pt_step_seconds sum).  feed_bound names stall fractions above 10%
    — the feed is eating step time, not hiding behind it."""
    stall = _family_sum("pt_prefetch_stall_seconds_total")
    steps = _family_sum("pt_step_seconds")
    frac = stall / steps if steps > 0 else 0.0
    return {"stall_seconds_total": round(stall, 6),
            "step_seconds_total": round(steps, 6),
            "stall_fraction": round(frac, 6),
            "feed_bound": bool(steps > 0 and frac > 0.10)}


def _signature_payload(s):
    out = {"lane": s["lane"], "steps": s["steps"],
           "avg_step_s": _rq(s["total_s"] / s["steps"])
           if s["steps"] else None,
           "ema_step_s": _rq(s["ema_step_s"])}
    if s["device_steps"]:
        out["device_s_avg"] = _rq(s["device_s_sum"] / s["device_steps"])
    for k in ("flops", "bytes_accessed", "transcendentals",
              "collective_bytes"):
        if s.get(k) is not None:
            out[k] = s[k]
    if s.get("collective_counts"):
        out["collective_counts"] = s["collective_counts"]
    if s.get("mfu") is not None:
        out["mfu"] = _sig4(s["mfu"])
    if s.get("roofline"):
        rl = s["roofline"]
        out["roofline"] = {"bound": rl["bound"],
                           "t_compute_s": _sig4(rl["t_compute_s"]),
                           "t_memory_s": _sig4(rl["t_memory_s"]),
                           "t_comm_s": _sig4(rl["t_comm_s"])}
    return out


def profilez_payload():
    """The /profilez body: the whole attribution surface as JSON."""
    platform, pf, pbw, pici = device_peaks()
    return {
        "device": {"platform": platform, "peak_flops": pf,
                   "peak_hbm_bytes_per_s": pbw,
                   "peak_ici_bytes_per_s": pici,
                   "phases_enabled": _phases_enabled()},
        "signatures": {label: _signature_payload(s)
                       for label, s in signature_stats().items()},
        "phase_seconds": _phase_quantiles(),
        "feed": feed_verdict(),
        "flight_recorder": _flight.status(),
    }


def attribution_digest():
    """The compact attribution record every BENCH_*.json embeds: phase
    quantiles, per-signature MFU + roofline verdict, and the feed-bound
    fraction — so a perf record names WHERE its step time went and
    `tools/perf_compare.py` can diff it mechanically."""
    sigs = {}
    for label, s in signature_stats().items():
        ent = {"lane": s["lane"], "steps": s["steps"]}
        if s.get("mfu") is not None:
            ent["mfu"] = _sig4(s["mfu"])
        if s.get("roofline"):
            ent["roofline_bound"] = s["roofline"]["bound"]
        if s["device_steps"]:
            ent["device_s_avg"] = _rq(s["device_s_sum"]
                                      / s["device_steps"])
        sigs[label] = ent
    return {"phase_seconds": _phase_quantiles(),
            "signatures": sigs,
            "feed": feed_verdict(),
            "flight_recorder": _flight.status()}


_page_registered = False
_page_lock = threading.Lock()


def ensure_profilez_page():
    """Register /profilez on the process exposition servers (idempotent;
    called from the step sink so any process that runs steps serves the
    page)."""
    global _page_registered
    if _page_registered:
        return
    with _page_lock:
        if _page_registered:
            return
        try:
            from . import exposition as _expo

            _expo.register_page("/profilez", profilez_payload)
            _page_registered = True
        except ValueError:
            # a foreign renderer owns the path — leave it; never fatal
            _page_registered = True


def reset():
    """Drop all attribution state (tests)."""
    global _flight
    with _lock:
        _signatures.clear()
        _lane_ema.clear()
    _flight = FlightRecorder()
    _tls.pending = None
