"""Request-scoped serving traces (Dapper-style) with tail-based sampling.

The training side became attributable in PR 4/11 (phase brackets,
cross-process chrome-trace merge, flight recorder); this module does the
same for the serving path.  One request = one TRACE:

  request  root span — minted at the Frontend (`x-pt-trace` request
           header joins an upstream trace) or at the Router/engine
           admission edge for direct callers
  attempt  one Router dispatch (retry / hedge / failover); the hedge
           loser finishes ``status="cancelled"``, the winner ``"ok"``
  serve    the engine-side life of the request (admission → future
           resolution); carries TTFT/TPOT/token attrs on the decode lane
  batch    one shared batch-execute / decode-step; every request span
           that rode the batch LINKS to it (fan-in: N spans → 1 batch
           span), so per-request time decomposes over the actual device
           steps it shared with strangers

Spans are cheap plain objects behind one module lock; the hot path when
``FLAGS_reqtrace`` is off is a single flag read returning None.  Span
finish exemplar-tags the latency histograms (`metrics._Child.observe
(value, exemplar=...)` → OpenMetrics exposition) and, when the profiler
is running, lands a chrome-trace span with ``args.trace``/``args.span``
ids so `tools/merge_traces.py` can stitch a drill's per-replica traces
into one request-attributable timeline.

Tail-based sampling (flight-recorder precedent): EVERY completed trace
enters a bounded ring (``FLAGS_reqtrace_ring``); traces that error or
exceed the ring's live p99 latency are marked KEPT and exported through
the JSONL event log (`events.emit("reqtrace", ...)`).  `/tracez` (on
every exposition server) renders the slowest recent traces with their
span trees; `get_trace(trace_id)` is the programmatic lookup.

Propagation is a thread-local context: the Frontend/Router `attach()`
the active span around the synchronous engine-call edge, the engine
reads `current_span()` at admission and pins it to its request object —
no call-signature change anywhere, so duck-typed fakes keep working.

Stdlib-only, like the rest of the observability package.
"""

from __future__ import annotations

import collections
import concurrent.futures as _futures
import sys
import threading
import time

from . import events as _events
from . import tracing as _tracing

__all__ = [
    "Span", "enabled", "start_request", "start_span", "start_batch",
    "attach", "current_span", "current_trace_id", "finish_future",
    "get_trace", "completed", "request_quantiles", "tracez_payload",
    "ring_stats", "reset",
]

_lock = threading.RLock()
_tls = threading.local()

# trace_id -> {"trace_id", "name", "t_start", "spans": [Span, ...]}
_live: dict = {}
# completed trace dicts, oldest first; maxlen follows FLAGS_reqtrace_ring
_ring: collections.deque = collections.deque(maxlen=256)
_ring_maxlen = 256
# finished batch spans by span id (requests link to these across traces);
# sized past the trace ring so links in retained traces stay resolvable
_batch: "collections.OrderedDict[str, dict]" = collections.OrderedDict()
_BATCH_KEEP_FACTOR = 4

# below this many completed traces the live p99 is noise: only errors
# are tail-kept until the ring has history
_MIN_P99_HISTORY = 8

# sorting the full ring costs ~40us; the tail-keep threshold tolerates
# slack, so the sorted value is reused for this many completions
_P99_REFRESH = 32
_p99_cache = None
_p99_countdown = 0

_flags_mod = None


def _flag(name, default):
    global _flags_mod
    if _flags_mod is None:
        try:
            from paddle_tpu.fluid import flags as _flags

            _flags_mod = _flags
        except Exception:
            return default
    try:
        return _flags_mod.flag(name)
    except Exception:
        return default


def enabled() -> bool:
    return bool(_flag("reqtrace", True))


def _ring_cap() -> int:
    global _ring, _ring_maxlen
    cap = max(int(_flag("reqtrace_ring", 256)), 1)
    if cap != _ring_maxlen:
        with _lock:
            if cap != _ring_maxlen:
                _ring = collections.deque(_ring, maxlen=cap)
                _ring_maxlen = cap
    return _ring_maxlen


class Span:
    """One span of a request trace.  Never constructed directly — use
    `start_request` / `start_span` / `start_batch`."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind",
                 "t_start", "_t0", "duration_s", "status", "attrs",
                 "links", "_root")

    def __init__(self, trace_id, name, kind, parent_id=None, attrs=None,
                 root=False):
        self.trace_id = trace_id
        self.span_id = _tracing.new_span_id()
        self.parent_id = parent_id
        self.name = str(name)
        self.kind = str(kind)
        self.t_start = time.time()
        self._t0 = time.perf_counter()
        self.duration_s = None   # None while open
        self.status = None       # "ok" | "error" | "cancelled"
        self.attrs = dict(attrs) if attrs else {}
        self.links = []          # span ids of shared batch spans
        self._root = bool(root)

    def set_attr(self, key, value):
        self.attrs[str(key)] = value
        return self

    def link(self, span_or_id):
        """Fan-in link to a shared batch span (by Span or span id)."""
        sid = getattr(span_or_id, "span_id", span_or_id)
        if sid not in self.links:
            self.links.append(sid)
        return self

    def finish(self, status="ok", error=None, **attrs):
        """Close the span (idempotent — the first finish wins: a hedge
        loser marked cancelled must not be flipped 'ok' by its own late
        future callback).  Only the status gate sits under the lock;
        the winner past the gate owns the span exclusively."""
        t_done = time.perf_counter()
        with _lock:
            if self.status is not None:
                return self
            self.status = str(status)
        self.duration_s = max(t_done - self._t0, 0.0)
        if error is not None:
            self.attrs["error"] = repr(error)
        if attrs:
            self.attrs.update(attrs)
        _emit_profiler_span(self)
        if self.kind == "batch":
            _retire_batch(self)
        elif self._root:
            _complete_trace(self)
        return self

    def as_dict(self):
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "trace_id": self.trace_id,
            "name": self.name,
            "kind": self.kind,
            "t_start": self.t_start,
            "duration_s": self.duration_s,
            "status": self.status,
            "attrs": dict(self.attrs),
            "links": list(self.links),
        }


def _emit_profiler_span(span):
    """Land the span in the chrome trace (when a profiler session is
    running) with the trace/span ids in args — the merge_traces hook.
    sys.modules probe, not an import: tracing a request must neither
    pull in the fluid package nor pay import machinery per span."""
    _profiler = sys.modules.get("paddle_tpu.fluid.profiler")
    if _profiler is None or not _profiler._STATE["enabled"]:
        return
    try:
        args = {"trace": span.trace_id, "span": span.span_id,
                "kind": span.kind}
        if span.parent_id:
            args["parent"] = span.parent_id
        if span.links:
            args["links"] = list(span.links)
        _profiler._record("serve", f"span:{span.name}",
                          span.duration_s or 0.0, start=span._t0,
                          args=args)
    except Exception:
        pass  # a profiler hiccup must never fail a request


# ---------------------------------------------------------------------------
# span creation + thread-local propagation
# ---------------------------------------------------------------------------


def start_request(name, trace_id=None, attrs=None, kind="request"):
    """Mint a new trace rooted at one request span.  Returns None when
    FLAGS_reqtrace is off (every consumer handles the None span).
    ``trace_id`` joins an upstream trace (the `x-pt-trace` header)."""
    if not enabled():
        return None
    tid = str(trace_id) if trace_id else _tracing.new_span_id().replace(
        "-", "") + format(int(time.time() * 1e6) & 0xffffff, "x")
    span = Span(tid, name, kind, attrs=attrs, root=True)
    # dict store is atomic under the GIL — submit runs on every client
    # thread concurrently, so the hot path takes no lock here
    _live[tid] = {"trace_id": tid, "name": span.name,
                  "t_start": span.t_start, "spans": [span]}
    return span


def start_span(name, kind="span", parent=None, attrs=None):
    """Child span under ``parent`` (default: the thread's current span).
    Returns None when disabled or there is no parent trace to join."""
    if not enabled():
        return None
    parent = parent if parent is not None else current_span()
    if parent is None:
        return None
    span = Span(parent.trace_id, name, kind, parent_id=parent.span_id,
                attrs=attrs)
    rec = _live.get(parent.trace_id)  # get/append: atomic under the GIL
    if rec is not None:
        rec["spans"].append(span)
    return span


def start_batch(name, attrs=None):
    """A shared batch-execute/decode-step span.  It belongs to no single
    trace — participating request spans `link()` to it, and it is kept
    in a bounded side ring after finish so retained traces can resolve
    the fan-in."""
    if not enabled():
        return None
    return Span("", name, "batch", attrs=attrs)


def _retire_batch(span):
    with _lock:
        _batch[span.span_id] = span.as_dict()
        cap = _ring_cap() * _BATCH_KEEP_FACTOR
        while len(_batch) > cap:
            _batch.popitem(last=False)


class _Attach:
    __slots__ = ("_span",)

    def __init__(self, span):
        self._span = span

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self._span)
        return self._span

    def __exit__(self, *exc):
        _tls.stack.pop()
        return False


def attach(span):
    """Context manager making ``span`` the thread's current span across
    a synchronous call edge (Router → engine submit).  ``attach(None)``
    is a transparent no-op so call sites never branch on enablement."""
    return _Attach(span)


def current_span():
    stack = getattr(_tls, "stack", None)
    for span in reversed(stack or ()):
        if span is not None:
            return span
    return None


def current_trace_id():
    span = current_span()
    return span.trace_id if span is not None else None


def finish_future(span, fut, **attrs):
    """Finish ``span`` from a resolved future's state: cancelled /
    error / ok.  The standard done-callback hook for spans whose
    completion edge IS a future.  One state query: each Future accessor
    takes the future's condition lock, and this runs inside the
    engine's future-resolution loop."""
    if span is None:
        return
    try:
        exc = fut.exception()
    except _futures.CancelledError:
        span.finish("cancelled", **attrs)
        return
    if exc is not None:
        span.finish("error", error=exc, **attrs)
    else:
        span.finish("ok", **attrs)


# ---------------------------------------------------------------------------
# completion, tail-keep policy, export
# ---------------------------------------------------------------------------


def _live_p99():
    """Ring p99 with the sort amortised over ``_P99_REFRESH``
    completions (caller holds ``_lock``): the tail-keep threshold only
    needs to be *recent*, not exact-per-completion, and the full-ring
    sort is the single most expensive step on the request hot path."""
    global _p99_cache, _p99_countdown
    if _p99_cache is not None and _p99_countdown > 0:
        _p99_countdown -= 1
        return _p99_cache
    durs = sorted(t["latency_s"] for t in _ring
                  if t.get("latency_s") is not None)
    if len(durs) < _MIN_P99_HISTORY:
        return None
    _p99_cache = durs[min(int(0.99 * (len(durs) - 1)), len(durs) - 1)]
    _p99_countdown = _P99_REFRESH
    return _p99_cache


def _complete_trace(root):
    """Book the finished trace into the ring.  This runs once per
    served request (on the engine thread, inside the future-resolution
    loop), so it does the bare minimum: the live record ITSELF becomes
    the ring entry — Span objects and all — stamped with the outcome
    and the tail-keep verdict.  Readers materialise span dicts, the
    batch fan-in, and TTFT/TPOT lazily via `_public_trace`; reads are
    rare (/tracez, tests) while completions are the hot path."""
    _ring_cap()
    rec = _live.pop(root.trace_id, None)  # dict.pop: atomic, no lock
    if rec is None:
        return
    rec["latency_s"] = root.duration_s
    rec["status"] = root.status
    with _lock:
        p99 = _live_p99()
        # tail-keep: errors always; slow outliers once the ring has
        # enough history for a meaningful live p99
        kept = root.status != "ok" or (
            p99 is not None and root.duration_s is not None
            and root.duration_s > p99)
        rec["kept"] = bool(kept)
        _ring.append(rec)
        if kept:
            trace = _public_trace(rec)
    if kept:
        _events.emit("reqtrace", trace_id=trace["trace_id"],
                     name=trace["name"], status=trace["status"],
                     latency_s=trace["latency_s"],
                     ttft_s=trace["ttft_s"], tpot_s=trace["tpot_s"],
                     n_spans=trace["n_spans"], spans=trace["spans"])


def _public_trace(t):
    """The reader-facing trace dict: span dicts materialised, the batch
    fan-in resolved, TTFT/TPOT lifted from serve-span attrs.  Caller
    holds ``_lock``.  Batch spans are resolved at read: `_batch` keeps
    ``_BATCH_KEEP_FACTOR``× the trace ring, so a ring trace's linked
    batches are still present."""
    span_objs = t["spans"]
    spans = [s.as_dict() for s in span_objs]
    ttft = tpot = None
    linked = []
    for s in span_objs:
        ttft = s.attrs.get("ttft_s", ttft)
        tpot = s.attrs.get("tpot_s", tpot)
        for sid in s.links:
            if sid not in linked:
                linked.append(sid)
    for sid in linked:
        b = _batch.get(sid)
        if b is not None:
            spans.append(b)
    return {"trace_id": t["trace_id"], "name": t["name"],
            "t_start": t["t_start"], "latency_s": t.get("latency_s"),
            "status": t.get("status"), "ttft_s": ttft, "tpot_s": tpot,
            "n_spans": len(spans), "kept": t.get("kept", False),
            "spans": spans}


def get_trace(trace_id):
    """Completed (ring) or still-live trace by id; None if evicted."""
    with _lock:
        for t in reversed(_ring):
            if t["trace_id"] == trace_id:
                return _public_trace(t)
        rec = _live.get(trace_id)
        if rec is not None:
            return {"trace_id": trace_id, "name": rec["name"],
                    "t_start": rec["t_start"], "status": "live",
                    "latency_s": None, "kept": False,
                    "spans": [s.as_dict() for s in rec["spans"]]}
    return None


def completed(n=None):
    """The last ``n`` completed traces (ring order, oldest first)."""
    with _lock:
        traces = list(_ring)
        if n is not None:
            traces = traces[-int(n):]
        return [_public_trace(t) for t in traces]


def ring_stats():
    with _lock:
        kept = sum(1 for t in _ring if t.get("kept"))
        return {"size": len(_ring), "capacity": _ring_cap(),
                "kept": kept, "live": len(_live),
                "batch_spans": len(_batch)}


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[i]


def request_quantiles(qs=(0.5, 0.99)):
    """Per-request latency / TTFT / TPOT quantiles computed from the
    COMPLETED-TRACE ring (the span tree, not the aggregate histogram)
    — what the bench rungs embed as trace-derived truth."""
    with _lock:
        snap = [(t.get("latency_s"), t["spans"]) for t in _ring
                if t.get("status") == "ok"]
    vals = {"latency_s": [], "ttft_s": [], "tpot_s": []}
    for latency, span_objs in snap:
        if latency is not None:
            vals["latency_s"].append(latency)
        ttft = tpot = None
        for s in span_objs:
            ttft = s.attrs.get("ttft_s", ttft)
            tpot = s.attrs.get("tpot_s", tpot)
        if ttft is not None:
            vals["ttft_s"].append(ttft)
        if tpot is not None:
            vals["tpot_s"].append(tpot)
    out = {"count": len(snap)}
    for key, vs in vals.items():
        vs.sort()
        out[key] = {f"p{int(q * 100)}": _quantile(vs, q) for q in qs} \
            if vs else None
    return out


def reset():
    """Drop all trace state (tests)."""
    global _p99_cache, _p99_countdown
    with _lock:
        _live.clear()
        _ring.clear()
        _batch.clear()
        _p99_cache = None
        _p99_countdown = 0
    _tls.stack = []


# ---------------------------------------------------------------------------
# /tracez
# ---------------------------------------------------------------------------


def _render_span_tree(spans, lines):
    by_parent: dict = {}
    by_id = {s["span_id"]: s for s in spans}
    roots = []
    for s in spans:
        pid = s.get("parent_id")
        if pid and pid in by_id:
            by_parent.setdefault(pid, []).append(s)
        else:
            roots.append(s)

    def walk(span, depth):
        dur = span.get("duration_s")
        dur_txt = f"{dur * 1e3:9.3f} ms" if dur is not None else "     open"
        links = ""
        if span.get("links"):
            links = "  links=" + ",".join(span["links"])
        attrs = span.get("attrs") or {}
        attr_txt = "".join(
            f" {k}={attrs[k]}" for k in sorted(attrs) if k != "error")
        if "error" in attrs:
            attr_txt += f" error={attrs['error']}"
        lines.append(f"    {'  ' * depth}{span['kind']}:{span['name']} "
                     f"[{span.get('status')}] {dur_txt}"
                     f"{attr_txt}{links}")
        for child in by_parent.get(span["span_id"], ()):
            walk(child, depth + 1)

    for r in roots:
        walk(r, 0)


def tracez_payload(limit=20):
    """Human-readable /tracez: ring stats then the slowest recent
    completed traces, each with its span tree."""
    stats = ring_stats()
    with _lock:
        traces = [_public_trace(t) for t in sorted(
            _ring, key=lambda t: (t.get("latency_s") or 0.0),
            reverse=True)[:int(limit)]]
    lines = [
        "reqtrace — request-scoped serving traces "
        "(docs/OBSERVABILITY.md)",
        f"ring: {stats['size']}/{stats['capacity']} completed, "
        f"{stats['kept']} tail-kept, {stats['live']} live, "
        f"{stats['batch_spans']} batch spans",
        f"enabled: {enabled()}",
        "",
        f"slowest {len(traces)} completed traces:",
    ]
    for t in traces:
        lat = t.get("latency_s")
        lat_txt = f"{lat * 1e3:.3f} ms" if lat is not None else "?"
        kept = " KEPT" if t.get("kept") else ""
        lines.append(f"  {t['trace_id']}  {t['name']}  "
                     f"[{t['status']}]  {lat_txt}{kept}")
        _render_span_tree(t.get("spans") or (), lines)
    return "\n".join(lines) + "\n", "text/plain; charset=utf-8"


def _tracez_page():
    return tracez_payload()


try:  # page registration is idempotent for the same renderer
    from . import exposition as _exposition

    _exposition.register_page("/tracez", _tracez_page)
except Exception:
    pass
