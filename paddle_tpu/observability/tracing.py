"""Cross-process trace identity: per-job trace id, per-RPC span ids, and
the (role, rank) identity every telemetry surface tags records with.

A distributed job (launch_ps / launch / supervised ProcGroup) is ONE
trace: the launcher mints a job trace id and exports it as ``PT_TRACE_ID``
so every pserver/trainer incarnation — including supervised relaunches —
lands its spans and JSONL events under the same id.  A process that finds
no ``PT_TRACE_ID`` mints its own and writes it back into ``os.environ``,
so children it spawns later still join its trace.

Span ids are cheap process-local hex tokens minted per RPC attempt; they
are recorded in the client's JSONL `rpc` events, so a retry storm is
enumerable attempt by attempt next to the chrome-trace `rpc:<cmd>` spans
(correlated by trace id + timestamps; carrying span ids inside the trace
args and the RPC wire frame is ROADMAP telemetry phase-2).  The wire
protocol itself is untouched: the job id rides the launcher env
contract, the same channel PADDLE_TRAINER_ID uses.

Stdlib-only — imported by `native` and `distributed`, which must stay
importable without jax.
"""

from __future__ import annotations

import itertools
import os
import uuid

__all__ = ["job_trace_id", "new_span_id", "new_wire_span", "format_wire_span",
           "run_id", "process_role", "process_rank", "process_identity"]

_TRACE_ENV = "PT_TRACE_ID"
_RUN_ENV = "PT_RUN_ID"
_ROLE_ENV = "PT_TRACE_ROLE"

# itertools.count: next() is a single C call, atomic under the GIL — the
# serving hot path mints several ids per request, so no lock here
_span_counter = itertools.count(1)


def job_trace_id() -> str:
    """The job-wide trace id (mint-once, env-propagated to children)."""
    tid = os.environ.get(_TRACE_ENV)
    if not tid:
        tid = uuid.uuid4().hex[:16]
        os.environ[_TRACE_ENV] = tid
    return tid


def run_id() -> str:
    """This run's id — like the trace id but NOT shared across restarts:
    the launcher re-exports a fresh one per incarnation when it wants
    restart-granular event streams, else it behaves like job_trace_id."""
    rid = os.environ.get(_RUN_ENV)
    if not rid:
        rid = job_trace_id()
        os.environ[_RUN_ENV] = rid
    return rid


def new_span_id() -> str:
    """Process-unique span id: pid-prefixed counter (cheap, ordered,
    unique across the job because pids differ per process)."""
    return f"{os.getpid():x}-{next(_span_counter):x}"


def new_wire_span():
    """Mint one span id in BOTH encodings: the u64 that rides the PS RPC
    frame (`(pid << 32) | counter`) and the `pid-counter` hex string every
    other telemetry surface uses — the same id, so a client-side `rpc`
    event and the server's journaled handling record correlate exactly.
    Returns (wire_u64, span_str)."""
    n = next(_span_counter)
    pid = os.getpid()
    return ((pid & 0xffffffff) << 32) | (n & 0xffffffff), f"{pid:x}-{n:x}"


def format_wire_span(wire: int) -> str:
    """The `pid-counter` string form of a u64 wire span id (the server's
    span journal hands back raw u64s)."""
    return f"{(wire >> 32) & 0xffffffff:x}-{wire & 0xffffffff:x}"


def process_role() -> str:
    """'trainer' / 'pserver' / ... — PT_TRACE_ROLE when the launcher (or
    runner script) set it, else inferred from the PADDLE_* env contract."""
    role = os.environ.get(_ROLE_ENV)
    if role:
        return role
    if os.environ.get("PADDLE_TRAINER_ID"):
        return "trainer"
    return "proc"


def process_rank() -> int:
    """This process's rank within its role: PT_TRACE_RANK when the
    launcher set it (pservers have no PADDLE_TRAINER_ID — launch_ps
    exports the shard index instead), else the trainer id from the
    PADDLE_* env contract; 0 when standalone."""
    for var in ("PT_TRACE_RANK", "PADDLE_TRAINER_ID"):
        v = os.environ.get(var)
        if v:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def process_identity() -> dict:
    """The tags every exported artifact (chrome trace, JSONL event,
    /statusz) carries so a merge tool can attribute records."""
    return {"pid": os.getpid(), "role": process_role(),
            "rank": process_rank(), "trace_id": job_trace_id(),
            "restart_count": int(
                os.environ.get("PADDLE_RESTART_COUNT", "0") or 0)}
