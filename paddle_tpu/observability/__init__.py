"""paddle_tpu.observability — unified telemetry for the whole stack.

One schema, four surfaces:

  metrics     thread-safe Counter/Gauge/Histogram registry with labels
              (Prometheus data model); every layer — executor, parallel
              runners, PS client/server, resilience, reader — reports
              into the process-wide default registry
  exposition  Prometheus text format, JSON, and the opt-in /metricsz +
              /statusz + /healthz HTTP endpoint (FLAGS_metrics_port)
  events      structured JSONL step/round lifecycle log (run id, pid,
              role/rank, trace id, wall + monotonic timestamps)
  tracing     per-job trace id (env-propagated through launchers) and
              per-RPC span ids; chrome traces exported per process are
              merged across ranks by tools/merge_traces.py
  reqtrace    request-scoped serving traces: span trees per request
              (attempts, shared batch fan-in), tail-based sampling ring,
              latency-histogram exemplars, /tracez
  slo         declarative SLOs over registry families with multi-window
              multi-burn-rate alerts (pt_slo_*, /sloz)

Metric naming: ``pt_<layer>_<what>[_total|_seconds|_bytes]`` with labels
for the variable dimensions — see docs/OBSERVABILITY.md for the full
inventory.  Import cost is stdlib-only: `native`, `distributed` and the
launchers can import this package without pulling in jax.
"""

from . import events  # noqa: F401
from . import exposition  # noqa: F401
from . import metrics  # noqa: F401
from . import profiling  # noqa: F401
from . import reqtrace  # noqa: F401
from . import slo  # noqa: F401
from . import tracing  # noqa: F401
from .exposition import (MetricsServer, ensure_from_flags, parse_text,
                         register_page, render_json, render_text,
                         unregister_page)
from .metrics import (DEFAULT_BUCKETS, REGISTRY, Counter, Gauge, Histogram,
                      MetricsRegistry, counter, gauge, hist_quantile,
                      histogram, reset, snapshot)
from .tracing import job_trace_id, new_span_id, process_identity

__all__ = [
    "metrics", "exposition", "events", "tracing", "profiling",
    "reqtrace", "slo",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "counter", "gauge", "histogram", "snapshot", "reset", "hist_quantile",
    "DEFAULT_BUCKETS",
    "render_text", "render_json", "parse_text", "MetricsServer",
    "ensure_from_flags", "register_page", "unregister_page",
    "job_trace_id", "new_span_id", "process_identity",
]
