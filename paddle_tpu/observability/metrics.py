"""Framework-wide metrics registry: Counter / Gauge / Histogram with labels.

Modeled on the Prometheus client data model (reference analog:
platform/profiler.cc kept per-op timing tables; the distributed lanes grew
ad-hoc dict counters — `resilience_stats()`, `PSServer.stats()` — with no
common schema).  This module is the one schema every layer reports into:

  - Counter    monotonically increasing float (events, bytes, seconds)
  - Gauge      last-write-wins float (queue depth, flops of a signature)
  - Histogram  cumulative fixed-bucket latency/size distribution

Each metric family has a name, help text, and a tuple of label NAMES;
children are keyed by label VALUES (``family.labels(cmd="send_grad")``).
Registering the same (name, type, labels) twice returns the existing
family — instruments are created lazily at call sites all over the stack
and must converge on one object.  A name re-registered with a different
type or label set raises: one schema per name, process-wide.

Zero-dependency (stdlib only) and thread-safe: the registry and every
family share one re-entrant lock, so `snapshot()` is a consistent cut.
Import cost matters — this module is pulled in by `distributed.resilience`
and `native`, which must stay importable without jax.
"""

from __future__ import annotations

import math
import threading

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "counter", "gauge", "histogram", "snapshot", "reset",
    "hist_quantile", "DEFAULT_BUCKETS",
]

# Prometheus client_golang defaults: spans 5 ms .. 10 s, the useful range
# for both RPC latencies and TPU step times; +Inf is implicit
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)

_INF = float("inf")


class _Child:
    """One labeled time series of a family."""

    __slots__ = ("_family", "_value", "_bucket_counts", "_sum", "_count",
                 "_exemplars")

    def __init__(self, family):
        self._family = family
        self._value = 0.0
        if family.type == "histogram":
            self._bucket_counts = [0] * (len(family.buckets) + 1)  # +Inf
            self._sum = 0.0
            self._count = 0
            # last exemplar per bucket: None | (labels_dict, value) —
            # the OpenMetrics attachment reqtrace uses to pin a trace id
            # onto the observation that landed in each bucket
            self._exemplars = [None] * (len(family.buckets) + 1)

    # -- counter / gauge -------------------------------------------------
    def inc(self, amount=1.0):
        if self._family.type == "counter" and amount < 0:
            raise ValueError(
                f"counter {self._family.name} cannot decrease "
                f"(inc({amount}))")
        with self._family._lock:
            self._value += float(amount)

    def dec(self, amount=1.0):
        if self._family.type != "gauge":
            raise TypeError(f"{self._family.type} has no dec()")
        with self._family._lock:
            self._value -= float(amount)

    def set(self, value):
        if self._family.type != "gauge":
            raise TypeError(f"{self._family.type} has no set()")
        with self._family._lock:
            self._value = float(value)

    @property
    def value(self):
        with self._family._lock:
            return self._value

    # -- histogram -------------------------------------------------------
    def observe(self, value, exemplar=None):
        """Record one observation.  ``exemplar`` (optional) attaches an
        OpenMetrics exemplar to the bucket this observation lands in: a
        trace-id string (stored as ``{"trace_id": ...}``) or a label
        dict.  Last writer per bucket wins — exemplars are pointers to
        representative traces, not a second histogram."""
        if self._family.type != "histogram":
            raise TypeError(f"{self._family.type} has no observe()")
        v = float(value)
        if exemplar is not None and not isinstance(exemplar, dict):
            exemplar = {"trace_id": str(exemplar)}
        with self._family._lock:
            # first bucket whose upper bound contains v (le semantics);
            # falls through to the +Inf bucket
            idx = len(self._family.buckets)
            for i, ub in enumerate(self._family.buckets):
                if v <= ub:
                    idx = i
                    break
            self._bucket_counts[idx] += 1
            self._sum += v
            self._count += 1
            if exemplar is not None:
                self._exemplars[idx] = (dict(exemplar), v)

    def hist_data(self):
        """-> {"buckets": [(le, CUMULATIVE count)], "sum": s, "count": n}
        (Prometheus exposition semantics: each bucket includes all lower
        ones; the +Inf bucket equals count).  When any bucket carries an
        exemplar, an ``"exemplars"`` key maps that bucket's ``le`` to
        ``(labels_dict, observed_value)`` — absent otherwise, so
        exemplar-free histograms keep their exact legacy shape."""
        with self._family._lock:
            cum, out, ex = 0, [], {}
            for ub, c, e in zip((*self._family.buckets, _INF),
                                self._bucket_counts, self._exemplars):
                cum += c
                out.append((ub, cum))
                if e is not None:
                    ex[ub] = (dict(e[0]), e[1])
            data = {"buckets": out, "sum": self._sum, "count": self._count}
            if ex:
                data["exemplars"] = ex
            return data


class _Family:
    """A named metric with a fixed label-name schema."""

    def __init__(self, registry, name, help_text, type_, label_names,
                 buckets=None):
        self.name = name
        self.help = help_text
        self.type = type_
        self.label_names = tuple(label_names)
        self.buckets = tuple(sorted(buckets)) if type_ == "histogram" else ()
        self._lock = registry._lock
        self._children: dict[tuple, _Child] = {}

    def labels(self, **label_values):
        if set(label_values) != set(self.label_names):
            raise ValueError(
                f"{self.name} expects labels {self.label_names}, "
                f"got {tuple(label_values)}")
        key = tuple(str(label_values[n]) for n in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _Child(self)
            return child

    def _default_child(self):
        if self.label_names:
            raise ValueError(
                f"{self.name} has labels {self.label_names}; "
                f"use .labels(...)")
        return self.labels()

    # label-free conveniences so `counter(...).inc()` reads naturally
    def inc(self, amount=1.0):
        self._default_child().inc(amount)

    def dec(self, amount=1.0):
        self._default_child().dec(amount)

    def set(self, value):
        self._default_child().set(value)

    def observe(self, value, exemplar=None):
        self._default_child().observe(value, exemplar=exemplar)

    @property
    def value(self):
        return self._default_child().value

    def clear(self):
        """Drop every child series (used by back-compat reset views)."""
        with self._lock:
            self._children.clear()

    def _snapshot(self):
        with self._lock:
            samples = {}
            for key, child in self._children.items():
                if self.type == "histogram":
                    samples[key] = child.hist_data()
                else:
                    samples[key] = child._value
            return {"type": self.type, "help": self.help,
                    "label_names": self.label_names, "samples": samples}


class Counter(_Family):
    pass


class Gauge(_Family):
    pass


class Histogram(_Family):
    pass


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Process-wide home of metric families; `snapshot()` is the read API
    every exposition surface (text / JSON / events) renders from."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: dict[str, _Family] = {}
        self._epoch = 0

    def _register(self, type_, name, help_text, labels, buckets=None):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.type != type_ or fam.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.type}{fam.label_names}; cannot re-register "
                        f"as {type_}{tuple(labels)}")
                if (type_ == "histogram" and buckets is not None
                        and fam.buckets != tuple(sorted(buckets))):
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {fam.buckets}")
                return fam
            cls = _TYPES[type_]
            fam = cls(self, name, help_text, type_, labels,
                      buckets=buckets if buckets is not None
                      else DEFAULT_BUCKETS)
            self._families[name] = fam
            return fam

    def counter(self, name, help_text="", labels=()):
        return self._register("counter", name, help_text, labels)

    def gauge(self, name, help_text="", labels=()):
        return self._register("gauge", name, help_text, labels)

    def histogram(self, name, help_text="", labels=(), buckets=None):
        return self._register("histogram", name, help_text, labels,
                              buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._families.get(name)

    def snapshot(self):
        """{name: {type, help, label_names, samples}} — a consistent cut
        of every family.  Counter/gauge samples are floats keyed by the
        label-value tuple; histogram samples are
        {"buckets": [(le, cum)], "sum", "count"}."""
        with self._lock:
            return {name: fam._snapshot()
                    for name, fam in sorted(self._families.items())}

    def reset(self):
        """Drop every family (tests).  Call sites re-register lazily, so
        a reset mid-run only zeroes, never breaks."""
        with self._lock:
            self._families.clear()
            self._epoch += 1

    @property
    def epoch(self):
        """Bumped on every reset().  A call site that CACHES resolved
        label children (instead of re-registering per call) compares
        this to decide when its children are orphaned and must rebind."""
        return self._epoch


# the process-wide default registry; every layer of the stack reports here
REGISTRY = MetricsRegistry()


def counter(name, help_text="", labels=()):
    return REGISTRY.counter(name, help_text, labels)


def gauge(name, help_text="", labels=()):
    return REGISTRY.gauge(name, help_text, labels)


def histogram(name, help_text="", labels=(), buckets=None):
    return REGISTRY.histogram(name, help_text, labels, buckets=buckets)


def snapshot():
    return REGISTRY.snapshot()


def reset():
    REGISTRY.reset()


def hist_quantile(hist, q):
    """Estimate the q-quantile of a histogram sample (the
    ``hist_data()`` / ``snapshot()`` dict form: cumulative ``buckets``
    [(le, cum)], ``count``) — PromQL ``histogram_quantile`` semantics:
    linear interpolation inside the winning bucket (lower bound 0 for the
    first), and the +Inf bucket reports the largest finite ``le`` (the
    best bound a fixed-bucket histogram can give).  q=1.0 is the max
    estimate; returns None on an empty histogram.

    This is what puts p50/p95/max step-time summaries into BENCH_*.json
    (bench.py metrics digest) instead of sums alone."""
    if not 0.0 <= float(q) <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q!r}")
    buckets = list(hist.get("buckets") or ())
    count = hist.get("count") or 0
    if not count or not buckets:
        return None
    rank = float(q) * count
    prev_le, prev_cum = 0.0, 0
    for le, cum in buckets:
        if cum >= rank:
            if math.isinf(le):
                return prev_le  # observations beyond the last finite bound
            if cum == prev_cum:  # q=0 with an empty leading bucket
                return le
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = le, cum
    return prev_le  # unreachable with well-formed cumulative buckets
