"""Exposition surfaces for the metrics registry: Prometheus text format,
JSON dump, and an opt-in stdlib HTTP endpoint.

Text format follows the Prometheus exposition format (HELP/TYPE comments,
``name{label="value"} value`` samples, histogram ``_bucket``/``_sum``/
``_count`` expansion with cumulative ``le`` buckets, label-value escaping
of ``\\``, ``"`` and newlines).  `parse_text` is the strict line-by-line
inverse used by the golden-format tests — every rendered exposition must
round-trip through it.

The HTTP server is plain ``http.server`` on a daemon thread (no new
dependencies), serving:

    /metricsz   Prometheus text exposition of the default registry
    /statusz    JSON process status: identity (pid/role/rank/trace id),
                restart count, flag surface, jax backend + mesh shape
                (only if jax is ALREADY imported — a scrape must never
                trigger device init), uptime
    /healthz    200 "ok" liveness probe

Enable per process with ``FLAGS_metrics_port`` (env ``FLAGS_metrics_port``
seeds it like every flag); 0 = off.  `ensure_from_flags()` is called from
the executor's construction path, so any process that runs a program —
trainer, pserver, bench child — exposes itself when asked to.
"""

from __future__ import annotations

import json
import math
import sys
import threading
import time
import warnings

from . import metrics as _metrics
from . import tracing

__all__ = ["render_text", "render_json", "parse_text", "MetricsServer",
           "ensure_from_flags", "active_server", "stop_server",
           "register_page", "unregister_page"]

_START_TIME = time.time()


# ---------------------------------------------------------------------------
# text format
# ---------------------------------------------------------------------------


def _escape_label_value(v: str) -> str:
    return (v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"'))


def _escape_help(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _fmt_value(v) -> str:
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(names, values, extra=()):
    pairs = [(n, v) for n, v in zip(names, values)] + list(extra)
    if not pairs:
        return ""
    body = ",".join(f'{n}="{_escape_label_value(str(v))}"'
                    for n, v in pairs)
    return "{" + body + "}"


def render_text(snapshot=None) -> str:
    """Prometheus text exposition of a registry snapshot (default: the
    process registry)."""
    snap = _metrics.snapshot() if snapshot is None else snapshot
    lines = []
    for name, fam in snap.items():
        if fam.get("help"):
            lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {fam['type']}")
        label_names = fam.get("label_names", ())
        for values, sample in sorted(fam["samples"].items()):
            if fam["type"] == "histogram":
                exemplars = sample.get("exemplars") or {}
                for le, cum in sample["buckets"]:
                    line = (
                        f"{name}_bucket"
                        f"{_label_str(label_names, values, [('le', _fmt_value(le))])}"
                        f" {cum}")
                    ex = exemplars.get(le)
                    if ex is not None:
                        # OpenMetrics exemplar: `# {labels} value` after
                        # the bucket sample — how a trace id rides the
                        # exposition (docs/OBSERVABILITY.md "Request
                        # tracing")
                        ex_labels, ex_value = ex
                        body = ",".join(
                            f'{k}="{_escape_label_value(str(v))}"'
                            for k, v in sorted(ex_labels.items()))
                        line += f" # {{{body}}} {_fmt_value(ex_value)}"
                    lines.append(line)
                lines.append(f"{name}_sum{_label_str(label_names, values)}"
                             f" {_fmt_value(sample['sum'])}")
                lines.append(f"{name}_count{_label_str(label_names, values)}"
                             f" {sample['count']}")
            else:
                lines.append(f"{name}{_label_str(label_names, values)}"
                             f" {_fmt_value(sample['value'] if isinstance(sample, dict) else sample)}")
    return "\n".join(lines) + "\n" if lines else ""


def render_json(snapshot=None) -> str:
    snap = _metrics.snapshot() if snapshot is None else snapshot
    out = {}
    for name, fam in snap.items():
        samples = []
        for values, sample in sorted(fam["samples"].items()):
            labels = dict(zip(fam.get("label_names", ()), values))
            if fam["type"] == "histogram":
                samples.append({"labels": labels,
                                "buckets": [[le if not math.isinf(le)
                                             else "+Inf", c]
                                            for le, c in sample["buckets"]],
                                "sum": sample["sum"],
                                "count": sample["count"]})
            else:
                samples.append({"labels": labels, "value": sample})
        out[name] = {"type": fam["type"], "help": fam.get("help", ""),
                     "samples": samples}
    return json.dumps(out, indent=1, sort_keys=True)


# ---------------------------------------------------------------------------
# parser (the golden-format inverse)
# ---------------------------------------------------------------------------


class ExpositionParseError(ValueError):
    pass


def _parse_labels(body: str, line: str):
    """'a="x",b="y"' -> dict, honoring escapes; strict about syntax."""
    labels = {}
    i, n = 0, len(body)
    while i < n:
        j = body.find("=", i)
        if j < 0:
            raise ExpositionParseError(f"label without '=': {line}")
        name = body[i:j]
        if not name or not all(c.isalnum() or c == "_" for c in name):
            raise ExpositionParseError(f"bad label name {name!r}: {line}")
        if j + 1 >= n or body[j + 1] != '"':
            raise ExpositionParseError(f"label value not quoted: {line}")
        i = j + 2
        val = []
        while True:
            if i >= n:
                raise ExpositionParseError(f"unterminated label: {line}")
            c = body[i]
            if c == "\\":
                if i + 1 >= n:
                    raise ExpositionParseError(f"dangling escape: {line}")
                nxt = body[i + 1]
                val.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt))
                if val[-1] is None:
                    raise ExpositionParseError(
                        f"bad escape \\{nxt}: {line}")
                i += 2
            elif c == '"':
                i += 1
                break
            else:
                val.append(c)
                i += 1
        labels[name] = "".join(val)
        if i < n:
            if body[i] != ",":
                raise ExpositionParseError(f"junk after label: {line}")
            i += 1
    return labels


def parse_text(text: str):
    """Strict line-by-line parse of a Prometheus text exposition.

    Returns {metric_name: {"type": ..., "help": ..., "samples":
    [(labels_dict, value)]}} where histogram series appear under their
    ``_bucket``/``_sum``/``_count`` sample names attributed to the base
    family.  Raises ExpositionParseError on any malformed line — the
    golden tests rely on this strictness.
    """
    out = {}

    def family(name):
        return out.setdefault(name, {"type": None, "help": None,
                                     "samples": []})

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            if not name:
                raise ExpositionParseError(f"line {lineno}: empty HELP name")
            family(name)["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, type_ = rest.partition(" ")
            if type_ not in ("counter", "gauge", "histogram", "summary",
                             "untyped"):
                raise ExpositionParseError(
                    f"line {lineno}: bad TYPE {type_!r}")
            family(name)["type"] = type_
            continue
        if line.startswith("#"):
            continue  # comment
        # OpenMetrics exemplar appendix: `... # {labels} value` after a
        # bucket sample.  Split it off first — the label parse below
        # rpartitions on the LAST '}', which would otherwise be the
        # exemplar's closing brace.
        exemplar = None
        ex_at = line.rfind(" # {")
        if ex_at > 0:
            ex_part = line[ex_at + len(" # "):]
            line = line[:ex_at]
            ex_body, _, ex_val = ex_part.rpartition("}")
            if not ex_body.startswith("{") or not ex_val.strip():
                raise ExpositionParseError(
                    f"line {lineno}: malformed exemplar: {raw}")
            try:
                ex_value = float(ex_val.strip().replace("+Inf", "inf")
                                 .replace("-Inf", "-inf"))
            except ValueError:
                raise ExpositionParseError(
                    f"line {lineno}: bad exemplar value "
                    f"{ex_val.strip()!r}") from None
            exemplar = (_parse_labels(ex_body[1:], raw), ex_value)
        # sample line: name[{labels}] value
        if "{" in line:
            name, _, rest = line.partition("{")
            body, _, valpart = rest.rpartition("}")
            if not valpart.startswith(" "):
                raise ExpositionParseError(
                    f"line {lineno}: missing value: {line}")
            labels = _parse_labels(body, line)
            value_str = valpart.strip()
        else:
            name, _, value_str = line.partition(" ")
            labels = {}
            value_str = value_str.strip()
        if not name or not (name[0].isalpha() or name[0] in "_:"):
            raise ExpositionParseError(
                f"line {lineno}: bad metric name {name!r}")
        try:
            value = float(value_str.replace("+Inf", "inf")
                          .replace("-Inf", "-inf"))
        except ValueError:
            raise ExpositionParseError(
                f"line {lineno}: bad value {value_str!r}") from None
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in out:
                base = name[:-len(suffix)]
                labels = dict(labels, __sample__=suffix.lstrip("_"))
                break
        family(base)["samples"].append((labels, value))
        if exemplar is not None:
            # kept beside (not inside) the samples so exemplar-free
            # consumers see the exact legacy shape
            family(base).setdefault("exemplars", []).append(
                (labels, exemplar[0], exemplar[1]))
    return out


# ---------------------------------------------------------------------------
# HTTP endpoint
# ---------------------------------------------------------------------------


def _statusz() -> dict:
    status = dict(tracing.process_identity())
    status["uptime_seconds"] = round(time.time() - _START_TIME, 3)
    status["argv"] = sys.argv
    try:
        from paddle_tpu.fluid import flags as _flags
        status["flags"] = {k: v for k, v in sorted(_flags._VALUES.items())}
    except Exception:
        status["flags"] = {}
    # jax state only when jax is ALREADY imported: a metrics scrape must
    # never be the thing that initializes a TPU runtime
    jx = sys.modules.get("jax")
    if jx is not None:
        try:
            status["jax"] = {"version": jx.__version__,
                             "backend": jx.default_backend(),
                             "device_count": jx.device_count(),
                             "process_index": jx.process_index()}
        except Exception:
            status["jax"] = {"version": getattr(jx, "__version__", "?")}
        try:
            from paddle_tpu.parallel import mesh as _mesh
            m = _mesh.current_mesh()
            if m is not None:
                status["mesh"] = {str(a): int(s)
                                  for a, s in zip(m.axis_names, m.shape.values())} \
                    if hasattr(m.shape, "values") else str(m.shape)
        except Exception:
            pass
    return status


# subsystem status pages served beside the built-ins on EVERY
# MetricsServer in the process (the serving lane's /servez registers
# here): path -> zero-arg callable returning either (body_bytes,
# content_type) or a JSON-serializable object
_extra_pages: dict = {}
# guards the collision check-then-set below: without it two threads
# registering the same path with different renderers can both pass the
# check and the second overwrite wins silently — the exact undetected
# collision the guard exists to prevent (handlers read single keys,
# which is atomic, so only writers lock)
_pages_lock = threading.Lock()


def register_page(path, render):
    """Register an extra GET page (e.g. ``/servez``) on every exposition
    server in this process.  `render()` returns (body, content_type) —
    body bytes or str — or any JSON-serializable object (rendered
    application/json).  A page raising is a 500 on that request, never a
    server crash.  Registering a second renderer for a live path raises
    (a silent overwrite would vanish the first subsystem's page with
    nothing to detect the collision) — `unregister_page` first to
    replace; re-registering the SAME renderer is an idempotent no-op."""
    if not path.startswith("/"):
        raise ValueError(f"page path must start with '/': {path!r}")
    if path in ("/metricsz", "/metrics", "/metricsz.json", "/statusz",
                "/healthz"):
        raise ValueError(f"{path!r} is a built-in page")
    with _pages_lock:
        existing = _extra_pages.get(path)
        if existing is not None and existing is not render:
            raise ValueError(
                f"page {path!r} is already registered; unregister_page() "
                f"it before installing a different renderer")
        _extra_pages[path] = render


def unregister_page(path):
    with _pages_lock:
        _extra_pages.pop(path, None)


class MetricsServer:
    """Daemon-thread HTTP exposition server.  port=0 binds an ephemeral
    port (tests); the flag path passes an explicit port."""

    def __init__(self, port=0, host="127.0.0.1", registry=None):
        import http.server

        reg = registry or _metrics.REGISTRY

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                if path in ("/metricsz", "/metrics"):
                    body = render_text(reg.snapshot()).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/statusz":
                    body = json.dumps(_statusz(), indent=1,
                                      default=str).encode()
                    ctype = "application/json"
                elif path == "/healthz":
                    body, ctype = b"ok\n", "text/plain"
                elif path == "/metricsz.json":
                    body = render_json(reg.snapshot()).encode()
                    ctype = "application/json"
                elif (page := _extra_pages.get(path)) is not None:
                    # single .get(): a concurrent unregister_page between
                    # a membership test and the call would KeyError out
                    # of do_GET instead of 404/500ing the one request
                    try:
                        # serialization stays inside the try: a page
                        # whose RETURN VALUE fails json.dumps (circular
                        # reference, raising __str__) must also 500,
                        # never drop the connection with a traceback
                        out = page()
                        if (isinstance(out, tuple) and len(out) == 2
                                and isinstance(out[1], str)):
                            raw, ctype = out
                        else:
                            raw, ctype = out, "application/json"
                        if isinstance(raw, str):
                            body = raw.encode()
                        elif isinstance(raw, (bytes, bytearray)):
                            body = bytes(raw)
                        else:  # JSON-serializable body, possibly with
                            # an explicit content type alongside it
                            body = json.dumps(raw, indent=1,
                                              default=str).encode()
                    except Exception as e:
                        self.send_error(500, explain=str(e))
                        return
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr spam
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, int(port)),
                                                      Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.5},
            name="paddle-tpu-metricsz", daemon=True)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


_server = None
_server_lock = threading.Lock()
_failed_port = None  # latched: don't re-bind (and re-warn) the same port


def active_server():
    return _server


def ensure_from_flags():
    """Start the exposition server once per process when
    FLAGS_metrics_port is nonzero.  Never fatal: a taken port warns ONCE
    and latches disabled (two roles on one host must each get their own
    port); changing the flag to a different port retries."""
    global _server, _failed_port
    if _server is not None:
        return _server
    try:
        from paddle_tpu.fluid import flags
        port = int(flags.flag("metrics_port"))
    except Exception:
        return None
    # same construction edge also arms the flag-driven SLO evaluator
    # (FLAGS_slo_specs; no-op when the flag is empty) — one hook, every
    # process that runs a program gets both surfaces
    try:
        from . import slo as _slo

        _slo.ensure_from_flags()
    except Exception:
        pass
    if port <= 0 or port == _failed_port:
        return None
    with _server_lock:
        if _server is None and port != _failed_port:
            try:
                _server = MetricsServer(port=port)
            except OSError as e:
                _failed_port = port
                warnings.warn(
                    f"FLAGS_metrics_port={port}: cannot bind ({e}); "
                    f"metrics endpoint disabled for this process")
                return None
    return _server


def stop_server():
    global _server, _failed_port
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None
        _failed_port = None
