"""Cross-version jax API shims.

The framework targets the current jax surface (``jax.shard_map`` with a
``check_vma`` kwarg); the baked container toolchain may carry an older
release where shard_map still lives in ``jax.experimental.shard_map`` and
the kwarg is spelled ``check_rep``.  Installing the canonical name here —
imported before anything else in ``paddle_tpu/__init__`` — keeps every
caller (runners, kernels, tests) on the one modern spelling instead of
scattering try/except imports through the tree.

No-op on jax versions that already expose ``jax.shard_map``.
"""

from __future__ import annotations

import functools


def install():
    import jax

    if hasattr(jax, "shard_map"):
        return

    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, check_rep=None, **kw):
        if check_rep is None:
            # modern kwarg name → legacy one (both default True)
            check_rep = True if check_vma is None else bool(check_vma)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_rep, **kw)

    jax.shard_map = shard_map


install()
