"""Cross-version jax API shims.

The framework targets the current jax surface (``jax.shard_map`` with a
``check_vma`` kwarg); the baked container toolchain may carry an older
release where shard_map still lives in ``jax.experimental.shard_map`` and
the kwarg is spelled ``check_rep``.  Installing the canonical name here —
imported before anything else in ``paddle_tpu/__init__`` — keeps every
caller (runners, kernels, tests) on the one modern spelling instead of
scattering try/except imports through the tree.

No-op on jax versions that already expose ``jax.shard_map``.
"""

from __future__ import annotations

import functools


def install():
    import jax

    if hasattr(jax, "shard_map"):
        return

    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, check_rep=None, **kw):
        if check_rep is None:
            # modern kwarg name → legacy one (both default True)
            check_rep = True if check_vma is None else bool(check_vma)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_rep, **kw)

    jax.shard_map = shard_map


def get_custom_partitioning():
    """`jax.custom_partitioning` across jax versions (modern spelling
    first, then the 0.4.x experimental home) — the GSPMD quant hook's
    TPU-native integration point (parallel/gspmd/quant_hook.py).
    Returns None when the toolchain has neither, so callers can demote
    to the shard_map island instead of crashing at compile time."""
    import jax

    cp = getattr(jax, "custom_partitioning", None)
    if cp is not None:
        return cp
    try:
        from jax.experimental.custom_partitioning import (
            custom_partitioning)

        return custom_partitioning
    except ImportError:
        return None


def distributed_reinit(coordinator_address, num_processes, process_id,
                       **kw):
    """`jax.distributed` re-initialization across jax versions — the
    elastic-rejoin primitive (distributed.elastic.reinit_collective).

    Modern jax exposes ``jax.distributed.shutdown()`` and
    ``is_initialized()``; the baked container toolchain may carry a
    release with neither.  Shut down when possible, then initialize at
    the (possibly new) world size.  When shutdown is unavailable and the
    runtime is already initialized, jax raises its "only be called once"
    RuntimeError — re-raised with the actionable context (restart the
    process to resize) instead of a bare message."""
    import jax

    dist = jax.distributed
    try:
        # attempt shutdown whenever the API exists — some jax lines ship
        # shutdown() without is_initialized(), and skipping the teardown
        # there would turn a legal resize into the "only be called once"
        # failure below
        if getattr(dist, "shutdown", None) and (
                not getattr(dist, "is_initialized", None)
                or dist.is_initialized()):
            dist.shutdown()
    except RuntimeError:
        pass  # resilience: allow — not initialized / already torn down
    try:
        dist.initialize(coordinator_address=coordinator_address,
                        num_processes=num_processes,
                        process_id=process_id, **kw)
    except RuntimeError as e:
        if "only be called once" in str(e).lower():
            raise RuntimeError(
                "jax.distributed is already initialized and this jax "
                "build has no shutdown(); an elastic resize needs a "
                "process restart on this toolchain") from e
        raise


install()
