"""Hybrid parallelism: GSPMD partitioning of a whole Program over a mesh.

Reference analog: the reference composes parallelism out of explicit graph
rewrites — multi_devices_graph_pass clones ops per device and inserts
AllReduceOpHandles (multi_devices_graph_pass.cc:594), the collective
transpiler inserts `c_allreduce_sum` ops (transpiler/collective.py:208), and
tensor parallelism simply does not exist (SURVEY §2.8).

TPU-native redesign: ONE program, compiled ONCE under `jax.jit` with
`in_shardings` over a multi-axis `jax.sharding.Mesh` (dp × mp × sp × ...).
Parameters are annotated with PartitionSpecs by *name pattern* (the Megatron
column/row layout for transformers); feeds are sharded on the batch axis (and
optionally the sequence axis).  XLA GSPMD propagates shardings through the
whole forward+backward+optimizer computation and inserts every collective
(all-reduce, all-gather, reduce-scatter) over ICI by itself — the
fuse_all_reduce / all_reduce_deps / coalesce_grad_tensor passes of the
reference are all subsumed by the XLA all-reduce combiner.

Because `jit` has *global-view* semantics, a loss averaged over the (globally
sharded) batch yields gradients that are already averaged across data-parallel
shards: no ScaleLossGradOpHandle, no explicit grad all-reduce insertion.
"""

from __future__ import annotations

import re
import warnings

import numpy as np

from . import mesh as pmesh

__all__ = [
    "ShardingRule",
    "HybridParallelRunner",
    "megatron_rules",
    "build_hybrid_mesh",
]


class ShardingRule:
    """Maps parameter names to PartitionSpecs by regex.

    rules: list of (pattern, spec) where spec is a tuple of mesh-axis names /
    None per tensor dim, e.g. (None, 'mp') to split columns over the model
    axis.  First match wins; no match → replicated.  Axis names accept the
    paper spellings too ('batch'/'model' → 'dp'/'mp', mesh.canonical_axis).
    """

    def __init__(self, rules):
        self._rules = [(re.compile(p),
                        tuple(pmesh.canonical_axis(a) for a in s))
                       for p, s in rules]

    def spec_for(self, name, shape=None, mesh=None):
        for pat, spec in self._rules:
            if pat.search(name):
                if mesh is not None:
                    # drop axes the mesh doesn't have (e.g. rules mention 'mp'
                    # but the mesh is dp-only) → that dim stays replicated
                    spec = tuple(a if (a is None or a in mesh.axis_names) else None
                                 for a in spec)
                if shape is not None:
                    # keep only axes that evenly divide the dim — protects
                    # scalar optimizer accumulators (beta_pow: shape [1]) that
                    # share the parameter's name prefix
                    spec = spec[:len(shape)]
                    spec = tuple(
                        a if (a is None or (mesh is None or shape[d] % mesh.shape[a] == 0))
                        else None
                        for d, a in enumerate(spec))
                    spec = spec + (None,) * (len(shape) - len(spec))
                return spec
        return ()


def megatron_rules(extra=()):
    """Megatron column/row-parallel layout for the transformer param naming
    used by paddle_tpu.models.bert (and any model following it):

      - QKV and FFN-in weights: columns (output features) split over 'mp'
      - attention-output and FFN-out weights: rows (input features) split
      - word embedding: vocab dim split (logits become mp-sharded; GSPMD
        all-gathers only where needed)

    One all-reduce per transformer block in fwd and bwd — the classic layout,
    expressed as annotations instead of c_identity/c_allreduce op rewrites.
    """
    # patterns deliberately match optimizer accumulators too, which are named
    # `<param>_<acc>_<n>` (optimizer.py _add_accumulator) and must be sharded
    # exactly like their parameter
    rules = list(extra) + [
        # MoE expert weights: expert dim over 'ep' (beyond-parity; no
        # reference analog — SURVEY §2.8 lists expert parallel as absent)
        (r"_moe_(w1|w2)\.w_0($|_)", ("ep", None, None)),
        (r"_moe_(w1|w2)\.b_0($|_)", ("ep", None)),
        (r"(_query_fc|_key_fc|_value_fc|_qkv_fc|_ffn_fc_0)\.w_0($|_)", (None, "mp")),
        (r"(_query_fc|_key_fc|_value_fc|_qkv_fc|_ffn_fc_0)\.b_0($|_)", ("mp",)),
        (r"(_output_fc|_ffn_fc_1)\.w_0($|_)", ("mp", None)),
        (r"^(word_embedding|src_word_emb_table|trg_word_emb_table)($|_)", ("mp", None)),
    ]
    return ShardingRule(rules)


def build_hybrid_mesh(n_devices=None, dp=None, mp=1, sp=1, pp=1, ep=1,
                      devices=None):
    """Build a Mesh with the standard axis order (pp, dp, ep, sp, mp).

    mp innermost: tensor-parallel collectives are the most latency-sensitive,
    so they ride the fastest/nearest ICI links; pp outermost (stage-to-stage
    transfers are point-to-point and infrequent).
    """
    import jax

    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices % (mp * sp * pp * ep) != 0:
        raise ValueError(
            f"n_devices={n_devices} not divisible by mp*sp*pp*ep="
            f"{mp * sp * pp * ep}")
    if dp is None:
        dp = n_devices // (mp * sp * pp * ep)
    shape = {}
    if pp > 1:
        shape[pmesh.PIPE_AXIS] = pp
    shape[pmesh.DATA_AXIS] = dp
    if ep > 1:
        shape[pmesh.EXPERT_AXIS] = ep
    if sp > 1:
        shape[pmesh.SEQ_AXIS] = sp
    shape[pmesh.MODEL_AXIS] = mp
    return pmesh.build_mesh(shape, devices=devices[:n_devices])


class HybridParallelRunner:
    """Compile and run a Program SPMD-partitioned over a hybrid mesh.

    feed_specs: dict feed-name → PartitionSpec tuple.  Default: dim 0 on
    'dp' (batch sharding); pass e.g. ('dp', 'sp') for [B, S] token ids to add
    sequence parallelism.
    """

    def __init__(self, program, mesh, rules: ShardingRule | None = None,
                 feed_specs=None, scope=None, zero_stage=0,
                 zero_gather_quant=None, fused_update=None, gspmd=None,
                 policy_pin=None):
        """zero_stage=1: shard optimizer-state vars (moment accumulators,
        tagged is_optimizer_state) over the 'dp' axis on dim 0 — the
        cross-replica weight-update sharding of arXiv:2004.13336 (ZeRO-1).
        XLA GSPMD then keeps each replica's accumulator shard resident and
        all-gathers the updated parameters, cutting optimizer-state memory
        by the dp degree at the cost of one all-gather per step.

        zero_gather_quant (None = FLAGS_zero_gather_quant): with
        zero_stage>=1, the weight-update all-gather of every ZeRO-eligible
        parameter (replicated by the rules, dim 0 divisible by dp) moves a
        block-scaled int8 wire format instead of fp32
        (kernels.ring_collectives.quantized_all_gather): each dp shard
        quantizes its slice of the updated parameter, int8 payload +
        per-block fp32 scales ride the gather, and the full tensor
        dequantizes on arrival — halving (dual-int8) the gather bytes the
        ZeRO-1 trade costs.  Optimizer-state shards never gather at all,
        so optimizer state stays fp32-exact regardless of this knob.

        fused_update (None = FLAGS_fused_update): with zero_gather_quant
        on, the sgd/adam ops of ZeRO-gather-eligible parameters are
        rewritten to their fused update→requant variants
        (`fused_sgd_quant_gather` / `fused_adam_quant_gather`,
        kernels/fused_update.py): the op itself emits the block-scaled
        int8 image of the updated parameter, the gather rides THAT
        payload (gather_quantized_shards), and the fp32 updated parameter
        between update and requant never round-trips HBM — saved bytes
        book on ``pt_fused_update_bytes_saved_total``.  ``ParamOut``
        stays the exact fp32 update, so the same program run outside this
        runner is bit-identical to the unfused ops.

        gspmd (None = FLAGS_gspmd_executor): route compilation through
        the shared `parallel.gspmd.GSPMDExecutor` with a
        `TensorParallelPolicy` wrapping these rules (+ ZeRO-1 state
        sharding when zero_stage >= 1) — this runner becomes a thin
        policy selection over the one partitioned executor, sharing its
        compile cache/metrics/HLO-inspection plumbing with the DP lane.
        The fused-update / zero_gather_quant op rewrites stay on the
        classic path (their gather already rides the quantized wire
        format); the gspmd lane's quantized gradient hook engages via
        FLAGS_quant_allreduce instead."""
        self.program = program
        self.mesh = mesh
        self.rules = rules or ShardingRule([])
        # autotune pin (docs/AUTOTUNE.md "Pinning"): explicit pin or the
        # standing FLAGS_autotune_report path.  Unlike the DP runner the
        # mesh here is caller-supplied, so the pin must AGREE with it —
        # a silent re-mesh would invalidate the caller's feed_specs.
        if policy_pin is None:
            from paddle_tpu.fluid import flags as _flags

            policy_pin = _flags.flag("autotune_report") or None
        self.policy_pin = None
        if policy_pin is not None:
            from . import autotune as _autotune

            pin = _autotune.resolve_pin(policy_pin)
            shape = dict(getattr(mesh, "shape", {}) or {})
            got = {ax: int(shape.get(ax, 1))
                   for ax in (pmesh.PIPE_AXIS, pmesh.DATA_AXIS,
                              pmesh.MODEL_AXIS)}
            if got != pin.mesh_dims:
                raise ValueError(
                    f"autotune pin {pin.label()} names mesh dims "
                    f"{pin.mesh_dims} but this runner's mesh is {got}")
            self.policy_pin = pin
            gspmd = True          # a pin is always a GSPMD assignment
            zero_stage = pin.zero_stage
        self.feed_specs = dict(feed_specs or {})
        self._default_scope = scope
        self._cache = {}
        self._ran_keys = set()  # signatures that executed at least once
        self._step = 0
        self.zero_stage = int(zero_stage)
        if zero_gather_quant is None:
            from paddle_tpu.fluid import flags as _flags

            zero_gather_quant = _flags.flag("zero_gather_quant")
        self.zero_gather_quant = bool(zero_gather_quant)
        if fused_update is None:
            from paddle_tpu.fluid import flags as _flags

            fused_update = _flags.flag("fused_update")
        self.fused_update = bool(fused_update)
        if gspmd is None:
            from paddle_tpu.fluid import flags as _flags

            gspmd = _flags.flag("gspmd_executor")
        self.gspmd = bool(gspmd)
        # graph-optimization passes (FLAGS_graph_passes) BEFORE the
        # fused-gather rewrite and the health transpile — the declared
        # PASS_ORDER; the gspmd branch applies them inside GSPMDExecutor.
        if not self.gspmd:
            from paddle_tpu import passes as _graph_passes

            _graph_passes.apply_graph_passes(program, lane="hybrid")
        self._gspmd_exec = None
        if self.gspmd:
            # thin policy selection over the shared partitioned executor
            # (policy_for — the one rule the DP lane shares); the
            # program stays unrewritten (no fused-gather op rewrite — the
            # hook owns the wire format on this lane)
            from .gspmd import GSPMDExecutor, policy_for

            if self.policy_pin is not None:
                policy = self.policy_pin.build_policy(rules=self.rules)
                quant_hook = self.policy_pin.quant
            else:
                policy = policy_for(mesh, rules=rules,
                                    zero_stage=self.zero_stage)
                quant_hook = None
            self._gspmd_exec = GSPMDExecutor(
                program, mesh, policy, scope=scope,
                feed_specs=self.feed_specs, quant_hook=quant_hook)
            self._sentinel = None  # the shared executor owns it there
            self._fused_gather = {}
            # capture_hlo/last_hlo stay live on this lane through the
            # properties below (delegated to the executor), so the
            # classic dryrun/driver contract keeps working
            return
        # {param: {"shape", "padded", "qhi", "qlo", "qsc"}} for optimizer
        # ops rewritten to the fused update→requant→gather form
        self._fused_gather = (self._rewrite_fused_updates()
                              if (self.fused_update and self.zero_stage >= 1
                                  and self.zero_gather_quant) else {})
        # health sentinel (FLAGS_health_sentinel, docs/DISTRIBUTED.md
        # §6): inserted AFTER the fused-gather rewrite so the check
        # covers the final optimizer op forms; ZeRO-1 NOTE — snapshots
        # copy the scope's sharded arrays, so each process holds only
        # its resident moment shards
        from paddle_tpu import health

        self._sentinel = health.attach(program, lane="hybrid")
        # capture_hlo=True records the OPTIMIZED (post-GSPMD-partitioner)
        # HLO of the first compiled step in .last_hlo so callers can assert
        # which collectives XLA inserted (the dryrun/driver check does).
        # Costs one extra AOT compile of the same tiny computation.
        self.capture_hlo = False
        self.last_hlo = None

    # capture_hlo/last_hlo: plain attributes on the classic lane, live
    # delegation to the shared executor on the gspmd lane — the
    # documented dryrun/driver contract (set capture_hlo, run once, read
    # last_hlo) works identically on both
    @property
    def capture_hlo(self):
        if getattr(self, "_gspmd_exec", None) is not None:
            return self._gspmd_exec.capture_hlo
        return getattr(self, "_capture_hlo_flag", False)

    @capture_hlo.setter
    def capture_hlo(self, value):
        if getattr(self, "_gspmd_exec", None) is not None:
            self._gspmd_exec.capture_hlo = bool(value)
        else:
            self._capture_hlo_flag = bool(value)

    @property
    def last_hlo(self):
        if getattr(self, "_gspmd_exec", None) is not None:
            return self._gspmd_exec.last_hlo
        return getattr(self, "_last_hlo", None)

    @last_hlo.setter
    def last_hlo(self, value):
        self._last_hlo = value

    def rebuild(self, mesh):
        """Re-specialize the runner onto a new mesh — the elastic-rejoin
        hook (docs/DISTRIBUTED.md §6 "Elastic membership"): after a
        preemption resized the collective job and
        `distributed.elastic.reinit_collective` re-formed
        `jax.distributed`, every compiled executable is specialized to
        the OLD device set and sharding layout.  Dropping the caches and
        swapping the mesh re-lowers on next run; scope-resident device
        arrays re-shard on the fly through jax.device_put.  Returns self
        for chaining (`runner.rebuild(elastic.rebuild_mesh(mp=2))`)."""
        self.mesh = mesh
        self._cache.clear()
        self._ran_keys.clear()
        self.last_hlo = None
        if self._gspmd_exec is not None:
            # re-specialize the shared executor onto the new mesh: the
            # policy is mesh-independent, the compiled blocks are not
            from .gspmd import GSPMDExecutor

            old = self._gspmd_exec
            self._gspmd_exec = GSPMDExecutor(
                self.program, mesh, old.policy,
                scope=self._default_scope, feed_specs=self.feed_specs,
                quant_hook=old.quant_hook, quant_algo=old.quant_algo,
                capture_hlo=old.capture_hlo)
        if self._fused_gather:
            self._restamp_fused_updates()
        from paddle_tpu.observability import events

        events.emit("hybrid_rebuild",
                    mesh_shape={k: int(v) for k, v in mesh.shape.items()},
                    n_devices=int(len(mesh.devices.reshape(-1))))
        return self

    def _spec(self, *axes):
        from jax.sharding import NamedSharding, PartitionSpec as P

        axes = tuple(a for a in axes)
        return NamedSharding(self.mesh, P(*axes))

    def _param_sharding(self, name, shape):
        spec = self.rules.spec_for(name, shape=shape, mesh=self.mesh)
        if self.zero_stage >= 1 and not any(spec):
            spec = self._zero1_spec(name, shape) or spec
        return self._spec(*spec)

    def _zero1_spec(self, name, shape):
        """dp-shard dim 0 of optimizer-state vars (ZeRO-1) when possible."""
        if pmesh.DATA_AXIS not in self.mesh.axis_names:
            return None
        dp = self.mesh.shape[pmesh.DATA_AXIS]
        if dp <= 1 or not shape or shape[0] % dp != 0:
            return None
        v = self.program.global_block()._find_var_recursive(name)
        if v is None or not getattr(v, "is_optimizer_state", False):
            return None
        return (pmesh.DATA_AXIS,) + (None,) * (len(shape) - 1)

    def _zero_gather_params(self, scope, donated_names):
        """Parameters whose weight-update gather takes the quantized wire
        format (zero_gather_quant): trainable Parameters left replicated
        by the rules with dim 0 divisible by dp — the same eligibility
        gate `_zero1_spec` applies to their optimizer state.  Optimizer
        state itself is never in this set: its shards stay resident and
        fp32-exact.  Parameters whose per-device shard is smaller than
        one quantization block also stay fp32: block padding + scales
        would move MORE bytes than the fp32 gather they replace (the same
        size-adaptivity the all-reduce crossover applies)."""
        from paddle_tpu.fluid import flags as _flags
        from paddle_tpu.fluid.framework import Parameter

        if (not self.zero_gather_quant or self.zero_stage < 1
                or pmesh.DATA_AXIS not in self.mesh.axis_names):
            return {}
        dp = self.mesh.shape[pmesh.DATA_AXIS]
        if dp <= 1:
            return {}
        block = int(_flags.flag("quant_allreduce_block_size"))
        out = {}
        for name in donated_names:
            v = self.program.global_block()._find_var_recursive(name)
            if not isinstance(v, Parameter):
                continue
            val = scope.get(name)
            shape = tuple(np.shape(val)) if val is not None else None
            if not shape or shape[0] % dp != 0:
                continue
            if int(np.prod(shape)) // dp < block:
                continue  # sub-block shard: fp32 gather is cheaper
            if any(self.rules.spec_for(name, shape=shape, mesh=self.mesh)):
                continue  # mp/ep-sharded params: GSPMD owns their layout
            out[name] = shape
        return out

    _FUSED_GATHER_OPS = {"sgd": "fused_sgd_quant_gather",
                         "adam": "fused_adam_quant_gather",
                         "adamw": "fused_adamw_quant_gather",
                         "lamb": "fused_lamb_quant_gather",
                         "momentum": "fused_momentum_quant_gather"}

    def _fused_gather_eligible(self, name):
        """ZeRO-gather eligibility from program metadata (the same gates
        `_zero_gather_params` applies from the scope, minus the live
        values — the op rewrite happens at construction, before any
        scope is bound): trainable Parameter, static shape, dim 0
        divisible by dp, at least one quantization block per shard, not
        mp/ep-sharded by the rules."""
        from paddle_tpu.fluid import flags as _flags
        from paddle_tpu.fluid.framework import Parameter

        if pmesh.DATA_AXIS not in self.mesh.axis_names:
            return None
        dp = self.mesh.shape[pmesh.DATA_AXIS]
        if dp <= 1:
            return None
        v = self.program.global_block()._find_var_recursive(name)
        if not isinstance(v, Parameter) or not v.shape:
            return None
        shape = tuple(v.shape)
        if any(d is None or d < 0 for d in shape) or shape[0] % dp != 0:
            return None
        block = int(_flags.flag("quant_allreduce_block_size"))
        if int(np.prod(shape)) // dp < block:
            return None
        if any(self.rules.spec_for(name, shape=shape, mesh=self.mesh)):
            return None
        return shape

    def _rewrite_fused_updates(self):
        """Rewrite eligible sgd/adam ops to their fused
        update→requant→gather variants (in place, the DP transpiler's
        precedent): same slots plus QHi/QLo/QScale outputs carrying the
        block-scaled int8 image of the updated parameter, padded to
        dp*block so per-shard blocks never straddle the gather's shard
        boundary.  Returns {param: q-var info} for `_wrap_fused_gather`."""
        from paddle_tpu.fluid import flags as _flags
        from paddle_tpu.fluid.framework import Operator

        block = int(_flags.flag("quant_allreduce_block_size"))
        dp = self.mesh.shape.get(pmesh.DATA_AXIS, 1)
        blk = self.program.global_block()
        fused = {}
        for i, op in enumerate(blk.ops):
            if op.type not in self._FUSED_GATHER_OPS:
                continue
            pname = (op.inputs.get("Param") or [None])[0]
            if pname is None or pname in fused:
                continue
            shape = self._fused_gather_eligible(pname)
            if shape is None:
                continue
            numel = int(np.prod(shape))
            padded = numel + (-numel) % (dp * block)
            qhi = blk.create_var(name=pname + "@ZGQ_HI", dtype="int8",
                                 shape=[padded])
            qlo = blk.create_var(name=pname + "@ZGQ_LO", dtype="int8",
                                 shape=[padded])
            qsc = blk.create_var(name=pname + "@ZGQ_SCALE",
                                 dtype="float32", shape=[padded // block])
            outputs = {s: list(n) for s, n in op.outputs.items()}
            outputs.update(QHi=[qhi.name], QLo=[qlo.name],
                           QScale=[qsc.name])
            attrs = dict(op.attrs)
            attrs.update(block_size=block, pad_multiple=dp * block)
            blk.ops[i] = Operator(
                blk, self._FUSED_GATHER_OPS[op.type],
                inputs={s: list(n) for s, n in op.inputs.items()},
                outputs=outputs, attrs=attrs)
            fused[pname] = {"shape": shape, "padded": padded,
                            "qhi": qhi.name, "qlo": qlo.name,
                            "qsc": qsc.name}
        if fused:
            self.program._bump_version()
        return fused

    def _restamp_fused_updates(self):
        """Re-specialize the fused update→requant ops onto the current
        mesh (rebuild() path): the gather payload pads to dp*block, so
        the op attrs and the q-var shapes are dp-dependent — and
        eligibility itself is mesh-dependent, so a parameter the NEW mesh
        disqualifies (dp resized to 1, dim-0 divisibility lost, the dp
        axis gone entirely) REVERTS to its base optimizer op: leaving it
        fused would quantize-round-trip every step on a configuration
        that is exact by contract (dp=1) or crash the gather wrapper."""
        from paddle_tpu.fluid import flags as _flags
        from paddle_tpu.fluid.framework import Operator

        block = int(_flags.flag("quant_allreduce_block_size"))
        dp = self.mesh.shape.get(pmesh.DATA_AXIS, 1)
        base_of = {v: k for k, v in self._FUSED_GATHER_OPS.items()}
        blk = self.program.global_block()
        for i, op in enumerate(blk.ops):
            if op.type not in base_of:
                continue
            pname = (op.inputs.get("Param") or [None])[0]
            info = self._fused_gather.get(pname)
            if info is None:
                continue
            if self._fused_gather_eligible(pname) is None:
                # demote back to the exact base op on the new mesh
                attrs = {k: v for k, v in op.attrs.items()
                         if k not in ("block_size", "pad_multiple")}
                outputs = {s: list(n) for s, n in op.outputs.items()
                           if s not in ("QHi", "QLo", "QScale")}
                blk.ops[i] = Operator(
                    blk, base_of[op.type],
                    inputs={s: list(n) for s, n in op.inputs.items()},
                    outputs=outputs, attrs=attrs)
                del self._fused_gather[pname]
                continue
            numel = int(np.prod(info["shape"]))
            padded = numel + (-numel) % (dp * block)
            op.attrs.update(block_size=block, pad_multiple=dp * block)
            info["padded"] = padded
            blk.vars[info["qhi"]].shape = (padded,)
            blk.vars[info["qlo"]].shape = (padded,)
            blk.vars[info["qsc"]].shape = (padded // block,)
        self.program._bump_version()

    def _make_inner_body(self, plan):
        """The traced step body.  With fused update→requant ops in the
        program, returns a 3-tuple body that also exposes the quantized
        updated-parameter images (non-persistable op outputs, invisible
        to out_writes) so `_wrap_fused_gather` can ride them through the
        ZeRO gather; otherwise the plain BlockPlan body."""
        if not self._fused_gather:
            return plan.make_body(), False
        fetch_names, write_names = plan.jit_fetch_names, plan.write_names
        qnames = {p: (i["qhi"], i["qlo"], i["qsc"])
                  for p, i in self._fused_gather.items()}

        def fn(donated, readonly, feeds, step):
            env = plan.trace_env(donated, readonly, feeds, step)
            fetches = [env[n] for n in fetch_names]
            out_writes = {n: env[n] for n in write_names if n in env}
            extras = {p: (env[h], env[l], env[s])
                      for p, (h, l, s) in qnames.items() if h in env}
            return fetches, out_writes, extras

        return fn, True

    def _wrap_fused_gather(self, inner3, live_writes):
        """Close the fused chain: each rewritten parameter's quantized
        image (already padded to dp*block by the op) rides the ZeRO-1
        weight-update gather as int8 + scales
        (gather_quantized_shards), dequantizing only on arrival — the
        parameter write the next step reads is the gathered value, and
        the op's exact fp32 ParamOut is dead code XLA removes.  Returns
        (2-tuple body, modeled wire bytes/step, modeled HBM bytes
        saved/step)."""
        import jax
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.fluid import flags as _flags
        from paddle_tpu.kernels import fused_update as fu
        from paddle_tpu.kernels import quantized_collectives as qc
        from paddle_tpu.kernels import ring_collectives as rcol

        axis = pmesh.DATA_AXIS
        dp = self.mesh.shape[axis]
        block = int(_flags.flag("quant_allreduce_block_size"))
        # one shard_map serves every parameter: the payloads are all flat
        # 1-D images with identical specs/axis/block (unlike the plain
        # zero-gather wrapper, whose in_specs depend on each shape)
        gather_fn = jax.shard_map(
            lambda h, l, s: rcol.gather_quantized_shards(
                h, l, s, axis, block),
            mesh=self.mesh, in_specs=(P(axis), P(axis), P(axis)),
            out_specs=P(), check_vma=False)
        gathered, wire, saved = set(), 0, 0
        for name, info in self._fused_gather.items():
            if name not in live_writes:
                continue
            gathered.add(name)
            wire += qc.gather_wire_bytes(info["padded"] // dp,
                                         block_size=block, n_devices=dp)
            saved += fu.bytes_saved(int(np.prod(info["shape"])))

        def body(donated, readonly, feeds, step):
            fetches, out_writes, extras = inner3(donated, readonly, feeds,
                                                 step)
            out_writes = dict(out_writes)
            for name, (qh, ql, qsc) in extras.items():
                if name not in gathered:
                    continue
                info = self._fused_gather[name]
                flat = gather_fn(qh, ql, qsc)
                numel = int(np.prod(info["shape"]))
                val = flat[:numel].reshape(info["shape"])
                prev = out_writes.get(name)
                out_writes[name] = (val.astype(prev.dtype)
                                    if prev is not None else val)
            return fetches, out_writes

        return body, wire, saved

    def _wrap_zero_gather(self, inner, zgq_params):
        """Wrap a compiled step body so every ZeRO-gather-eligible
        parameter write re-replicates through the block-scaled int8
        all-gather: the nested shard_map's in_spec pins the updated
        parameter dp-sharded on dim 0 (which is how the ZeRO-sharded
        optimizer state computes it anyway), the int8 payload + scales
        ride the gather, and the out_spec hands the replicated fp32
        tensor back to GSPMD.  Returns (wrapped_body, modeled per-step
        wire bytes)."""
        import jax
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.fluid import flags as _flags
        from paddle_tpu.kernels import quantized_collectives as qc
        from paddle_tpu.kernels import ring_collectives as rcol

        axis = pmesh.DATA_AXIS
        dp = self.mesh.shape[axis]
        block = int(_flags.flag("quant_allreduce_block_size"))
        gathers, total = {}, 0
        for name, shape in zgq_params.items():
            in_spec = P(*((axis,) + (None,) * (len(shape) - 1)))
            gathers[name] = jax.shard_map(
                lambda s: rcol.quantized_all_gather(s, axis, block),
                mesh=self.mesh, in_specs=in_spec,
                out_specs=P(*((None,) * len(shape))), check_vma=False)
            total += qc.gather_wire_bytes(
                int(np.prod(shape)) // dp, block_size=block, n_devices=dp)

        def body(donated, readonly, feeds, step):
            fetches, out_writes = inner(donated, readonly, feeds, step)
            out_writes = dict(out_writes)
            for name, fn in gathers.items():
                if name in out_writes:
                    out_writes[name] = fn(out_writes[name])
            return fetches, out_writes

        return body, total

    def _resolve_scope(self, scope):
        if scope is not None:
            return scope
        if self._default_scope is not None:
            return self._default_scope
        from paddle_tpu.fluid.executor import global_scope

        return global_scope()

    @staticmethod
    def _prep(feed, fetch_list):
        """The shared dispatch-key helper (gspmd.executor.prep_feed) —
        one implementation so the two partitioned lanes' cache-key
        semantics cannot drift."""
        from .gspmd.executor import prep_feed

        return prep_feed(feed, fetch_list)

    def _dispatch(self, key, scope, feed, fetch_names, n_steps,
                  stacked_feed, return_numpy):
        import time as _time

        from paddle_tpu.fluid.executor import (_feed_batch, _m_cache,
                                               _m_compile_seconds,
                                               _record_step,
                                               _report_examples)

        sent = self._sentinel
        cb = self._cache.get(key)
        if cb is None:
            _m_cache().labels(path="hybrid", result="miss").inc()
            if sent is not None:
                sent.ensure_state(scope)  # before BlockPlan scope checks
            t0 = _time.perf_counter()  # observability: allow
            cb = self._compile(scope, list(feed.keys()), fetch_names,
                               n_steps=n_steps, stacked_feed=stacked_feed)
            self._cache[key] = cb
            _m_compile_seconds().labels(
                path="hybrid", phase="trace").inc(_time.perf_counter() - t0)  # observability: allow
        else:
            _m_cache().labels(path="hybrid", result="hit").inc()
        # health sentinel at dispatch granularity (one run() step, or one
        # whole run_steps chain — a rollback restores the pre-chain state
        # and replays the chain)
        def attempt():
            first_run = key not in self._ran_keys
            t0 = _time.perf_counter()  # observability: allow
            fetches = cb(scope, feed, self._step)
            step_s = _time.perf_counter() - t0  # observability: allow
            _record_step("hybrid", step_s, first_run)
            zgq_bytes = getattr(cb, "_zgq_bytes_per_step", 0)
            if zgq_bytes:
                from .data_parallel import collective_payload_counter

                collective_payload_counter().labels(
                    collective="zero_gather_quant").inc(
                    zgq_bytes * n_steps)
            fused_saved = getattr(cb, "_fused_saved_per_step", 0)
            if fused_saved:
                from .data_parallel import fused_update_bytes_counter

                fused_update_bytes_counter().inc(fused_saved * n_steps)
            self._ran_keys.add(key)
            # stacked_feed: leading feed axis is the step index, not batch
            batch = 0 if stacked_feed else _feed_batch(feed) * n_steps
            _report_examples("hybrid", batch, step_s)
            self._step += n_steps
            return fetches

        from paddle_tpu.health import run_guarded

        fetches = run_guarded(sent, scope, fetch_names, attempt,
                              chain=n_steps > 1)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches

    def run(self, scope=None, feed=None, fetch_list=None, return_numpy=True):
        if self._gspmd_exec is not None:
            return self._gspmd_exec.run(scope=scope, feed=feed,
                                        fetch_list=fetch_list,
                                        return_numpy=return_numpy)
        scope = self._resolve_scope(scope)
        feed, fetch_names, feed_sig = self._prep(feed, fetch_list)
        key = (self.program._version, feed_sig, tuple(fetch_names))
        return self._dispatch(key, scope, feed, fetch_names, 1, False,
                              return_numpy)

    def run_steps(self, feed, n_steps, fetch_list=None, scope=None,
                  return_numpy=True, stacked_feed=False):
        """`n_steps` GSPMD-partitioned steps in ONE jitted call — the
        fori_loop carries the sharded params/opt-state on-device (the
        big-training scan-over-steps pattern), with the step counter
        advancing per iteration exactly like n run() calls.
        stacked_feed=True: feed arrays carry a leading [n_steps] axis
        (replicated across the mesh), one slice per iteration.  Only the
        final step's fetches return."""
        if self._gspmd_exec is not None:
            # the shared executor chains the loop on-device now (one
            # jitted fori_loop call, stacked_feed included) — dispatch
            # amortization on the gspmd lane instead of n Python run()s
            return self._gspmd_exec.run_steps(
                feed, n_steps, fetch_list=fetch_list, scope=scope,
                return_numpy=return_numpy, stacked_feed=stacked_feed)
        scope = self._resolve_scope(scope)
        n = int(n_steps)
        if n < 1:
            raise ValueError(f"n_steps must be >= 1, got {n_steps!r}")
        feed, fetch_names, feed_sig = self._prep(feed, fetch_list)
        if stacked_feed:
            bad = {k: np.shape(v) for k, v in feed.items()
                   if not np.shape(v) or np.shape(v)[0] != n}
            if bad:
                raise ValueError(
                    f"stacked_feed arrays need a leading [{n}] axis; "
                    f"got {bad}")
        key = (self.program._version, feed_sig, tuple(fetch_names),
               "chain", n, bool(stacked_feed))
        return self._dispatch(key, scope, feed, fetch_names, n,
                              bool(stacked_feed), return_numpy)

    def _compile(self, scope, feed_names, fetch_names, n_steps=1,
                 stacked_feed=False):
        import jax
        from paddle_tpu.fluid.executor import BlockPlan, HostOpsUnsupported

        program, mesh = self.program, self.mesh
        plan = BlockPlan(program, program.global_block(), feed_names,
                         fetch_names, scope)
        if plan.host_pre_ops:
            raise NotImplementedError(
                "pre-stage host ops (distributed lookup) are only "
                "supported by the single-device Executor")
        chain_mode = n_steps > 1 or stacked_feed
        if chain_mode and (plan.host_ops or plan.host_fetch_names):
            raise HostOpsUnsupported(
                "run_steps chains the whole loop on-device; host ops "
                f"({[op.type for op in plan.host_ops]}) need the host "
                "between steps — use run() per step")
        inner_body, has_extras = self._make_inner_body(plan)
        zgq_bytes = fused_saved = 0
        if has_extras:
            # fused update→requant ops: their quantized images ride the
            # gather; wrapped BEFORE the chain wrap so every chained
            # iteration's parameter writes re-replicate through it.
            # Only params this plan actually WRITES count (a forward-only
            # fetch prunes the optimizer ops — no gather, no booking).
            live = set(plan.write_names)
            inner_body, fused_wire, fused_saved = \
                self._wrap_fused_gather(inner_body, live)
            zgq_bytes += fused_wire
        zgq = self._zero_gather_params(scope, plan.donated_names)
        # params on the fused path already gather quantized — the plain
        # quantize-then-gather wrapper covers only the rest (momentum /
        # other optimizers the fused rewrite doesn't absorb)
        zgq = {k: v for k, v in zgq.items() if k not in self._fused_gather}
        if zgq:
            inner_body, plain_bytes = self._wrap_zero_gather(inner_body,
                                                             zgq)
            zgq_bytes += plain_bytes
        # the health gate wraps OUTERMOST (after the gather wrappers, so
        # a parameter write replaced by a gathered quantized image is
        # gated too) but INSIDE the chain loop (per-iteration masking)
        from paddle_tpu.health import wrap_body as _health_gate

        inner_body = _health_gate(program, inner_body)

        if chain_mode:
            # the ONE chain combinator every lane shares
            # (fluid.executor.chain_step_body)
            from paddle_tpu.fluid.executor import chain_step_body

            inner_body = chain_step_body(inner_body, n_steps,
                                         stacked_feed)

        def body(*args):
            # ops that adapt their lowering to the mesh (ring attention on
            # the sp axis) read current_mesh() at trace time
            with pmesh.mesh_guard(mesh):
                return inner_body(*args)
        donated, readonly = plan.donated_names, plan.readonly_names
        writes = plan.write_names

        def shard_of(n, v):
            return self._param_sharding(n, tuple(np.shape(v)))

        don_sh = {n: shard_of(n, scope.get(n)) for n in donated}
        ro_sh = {n: shard_of(n, scope.get(n)) for n in readonly}

        def feed_shard(name):
            if name in self.feed_specs:
                axes = tuple(self.feed_specs[name])
            else:
                ax = (pmesh.DATA_AXIS
                      if pmesh.DATA_AXIS in mesh.axis_names else None)
                axes = (ax,) if ax else ()
            if stacked_feed:
                # leading [n_steps] axis is the loop index — replicated;
                # the batch dim (now dim 1) keeps its dp sharding
                axes = (None,) + axes
            return self._spec(*axes)

        feeds_sh = {n: feed_shard(n) for n in feed_names}
        out_sh = ([self._spec() for _ in fetch_names],
                  {n: don_sh.get(n, self._spec()) for n in writes})
        jitted = jax.jit(
            body,
            in_shardings=(don_sh, ro_sh, feeds_sh, self._spec()),
            out_shardings=out_sh,
            donate_argnums=(0,))
        prof_state = {"ran": False}

        def stage_global(value, sharding):
            """Multi-process SPMD staging: jit refuses numpy (or
            process-local jax) inputs with non-trivial shardings when the
            mesh spans processes.  Host values are the GLOBAL content,
            identical on every process (functional RNG makes startup
            deterministic; feeds are built from shared seeds), so each
            process materializes its addressable shards in place.
            Single-process: identity — no copy, no behavior change."""
            if jax.process_count() == 1:
                return value
            if (isinstance(value, jax.Array)
                    and value.sharding.device_set == sharding.device_set):
                return value  # already a global array on this mesh
            arr = np.asarray(value)
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx])

        def compiled(scope_, feeds, step):
            from paddle_tpu.fluid import profiler as _prof
            from paddle_tpu.observability import profiling as _profiling

            # step_phases outermost; timed_run keeps its historic region
            # (the jitted call + scope writes only — staging/HLO capture
            # before it, host ops after) so the "run" span semantics are
            # unchanged; fetch_sync brackets accumulate across both
            with _profiling.step_phases(
                    "hybrid", f"hybrid_block@{id(jitted):x}") as ph:
                with ph.phase("feed_prep"):
                    don_vals = {n: stage_global(scope_.get(n), don_sh[n])
                                for n in donated}
                    ro_vals = {n: stage_global(scope_.get(n), ro_sh[n])
                               for n in readonly}
                    feeds = {n: stage_global(v, feeds_sh[n])
                             for n, v in feeds.items()}
                    if self.capture_hlo and self.last_hlo is None:
                        self.last_hlo = (
                            jitted.lower(don_vals, ro_vals, dict(feeds),
                                         np.uint32(step))
                            .compile().as_text())
                with _prof.timed_run(f"hybrid_block@{id(jitted):x}",
                                     prof_state) as timer:
                    with ph.phase("dispatch"):
                        with warnings.catch_warnings():
                            warnings.simplefilter("ignore")  # donation unsupported on CPU
                            fetches, out_writes = jitted(
                                don_vals, ro_vals, dict(feeds),
                                np.uint32(step))
                    with ph.phase("device_wait"):
                        ph.wait((fetches, out_writes))
                    with ph.phase("fetch_sync"):
                        for n, v in out_writes.items():
                            scope_.set(n, v)
                        timer.done(fetches, out_writes)
                with ph.phase("fetch_sync"):
                    plan.run_host_ops(scope_)
                    out = plan.assemble_fetches(fetches, scope_)
            return out

        # modeled ZeRO-gather wire bytes (and fused-update HBM savings)
        # ride on the compiled closure so _dispatch can book them per
        # executed step
        compiled._zgq_bytes_per_step = zgq_bytes
        compiled._fused_saved_per_step = fused_saved
        return compiled
