"""Pipeline parallelism: GPipe-style microbatched stage execution.

Reference analog: PipelineOptimizer (optimizer.py:2664) cuts the program at
`cut_list` variables into sections, places each section on a device, and
runs them with scope queues between sections (PipelineTrainer +
SectionWorker, trainer_desc.py:145 / device_worker.py:184).

TPU-native redesign:
  - The program is cut by dataflow at the cut variables; every op (forward,
    backward, optimize) is assigned a stage — backward ops exactly, via the
    `fwd_op_idx` attr append_backward stamps on them.
  - Each stage compiles to TWO whole-stage XLA computations: a forward
    program (activations in → activations out) and a backward program that
    RECOMPUTES the stage forward and then runs its backward ops
    (rematerialization — the jax.checkpoint idiom at stage granularity, so
    no intermediate activations are ever shipped between stages; only the
    O(boundary) activation/grad tensors cross stages, like the reference's
    scope queues but without pickling whole scopes).
  - Gradients are accumulated over microbatches (mean) and each stage's
    optimizer ops run once per step in a third per-stage program — the
    multi_batch_merge_pass grad-accumulation semantics.
  - The schedule is GPipe fill-drain over M microbatches.  Math is exactly
    the full-batch step (mean-of-microbatch grads == full-batch grad for
    mean losses), which the tests assert.
"""

from __future__ import annotations

import collections
import dataclasses

import numpy as np

from paddle_tpu.fluid.framework import Program, grad_var_name

__all__ = ["assign_stages", "stage_partition", "boundary_sets",
           "StageInfo", "PipelineRunner"]

GRAD_SUFFIX = "@GRAD"


def _base_var(name):
    return name.split(GRAD_SUFFIX)[0] if GRAD_SUFFIX in name else None


def assign_stages(program, cut_vars):
    """Return (stage_of_op: list[int], n_stages).

    Forward ops: stage = max over effective input stages, where reading a
    cut variable of stage i (other than producing it) promotes to i+1.
    Backward ops: the stage of the forward op they differentiate
    (`fwd_op_idx`); grad-accumulation sums / the loss seed follow their
    grad's base variable.  Optimize ops: the stage that consumed their Param.
    """
    block = program.global_block()
    cut_set = set(cut_vars)
    n_stages = len(cut_vars) + 1
    var_stage: dict[str, int] = {}
    param_stage: dict[str, int] = {}
    fwd_stage: dict[int, int] = {}
    stage_of: list[int] = []

    def eff(name, producer=False):
        s = var_stage.get(name, 0)
        if name in cut_set and not producer:
            return s + 1
        return s

    for idx, op in enumerate(block.ops):
        role = op.attrs.get("op_role")
        if role == "backward":
            if "fwd_op_idx" in op.attrs:
                s = fwd_stage.get(int(op.attrs["fwd_op_idx"]), 0)
            else:
                # grad-accumulation sums follow the stage that PRODUCED
                # their partial-gradient inputs (a multi-consumer cut
                # activation accumulates in the consuming stage, and the
                # summed gradient crosses the boundary like any other
                # cotangent); the loss seed and input-less ops keep the
                # base-variable rule
                ins = [var_stage[n] for n in op.input_arg_names
                       if n in var_stage]
                if ins:
                    s = max(ins)
                else:
                    bases = [b for n in (list(op.input_arg_names)
                                         + list(op.output_arg_names))
                             if (b := _base_var(n)) is not None]
                    s = max((eff(b) for b in bases),
                            default=n_stages - 1)
        elif role == "optimize":
            if op.input("Param"):
                s = param_stage.get(op.input("Param")[0], 0)
            else:
                owners = [ps for p, ps in param_stage.items()
                          if any(n.startswith(p) for n in op.input_arg_names)]
                s = max(owners, default=0)
        else:
            s = max((eff(n) for n in op.input_arg_names), default=0)
            fwd_stage[idx] = s
            for n in op.input_arg_names:
                v = block._find_var_recursive(n)
                if v is not None and getattr(v, "trainable", None) is not None:
                    param_stage[n] = max(param_stage.get(n, 0), s)
        stage_of.append(s)
        for n in op.output_arg_names:
            var_stage[n] = s
    return stage_of, n_stages


@dataclasses.dataclass
class StageInfo:
    """One pipeline stage's op lists and boundary classification — the
    shared analysis behind BOTH execution lanes (PipelineRunner's
    per-stage host-scheduled programs and the gspmd PipelinePolicy's
    in-graph stage island, parallel/gspmd/pipeline_policy.py).  One
    implementation so the two lanes' stage semantics cannot drift."""

    index: int
    fwd_ops: list          # forward ops of this stage, program order
    bwd_ops: list          # backward ops (fwd_op_idx-matched)
    opt_ops: list          # optimizer ops for this stage's params
    acts_in: list          # cross-stage activations the forward consumes
    acts_out: list         # activations later stages consume
    grads_in: list         # incoming d(acts_out) names the backward feeds
    data_feeds: list       # data-feed names this stage reads
    param_grads: list      # [(param, grad)] owned by this stage
    loss_name: str | None  # set on the last stage


def stage_partition(program, ops, cut_vars, loss_name=None):
    """Partition ``ops`` (block-0 ops of ``program``, program order — a
    pruned subset is fine) into pipeline stages at ``cut_vars``.

    Returns ``(stages, stage_of)`` where ``stages`` is a
    list[StageInfo] and ``stage_of`` maps ``id(op) -> stage`` for every
    op in ``ops``.  Stage assignment always runs over the FULL block
    (assign_stages) so a pruned op list cannot shift the dataflow-based
    stage boundaries; the per-stage op lists then keep only the ops the
    caller passed."""
    block = program.global_block()
    stage_of_all, S = assign_stages(program, cut_vars)
    by_id = {id(op): s for op, s in zip(block.ops, stage_of_all)}
    stage_of = {id(op): by_id[id(op)] for op in ops}

    ops_by_stage = [[] for _ in range(S)]
    role_by_stage = [[] for _ in range(S)]
    for op in ops:
        s = stage_of[id(op)]
        ops_by_stage[s].append(op)
        role_by_stage[s].append(op.attrs.get("op_role"))

    pg = dict(getattr(program, "_params_grads", []))
    if loss_name is None:
        loss_name = getattr(program, "_pipeline", {}).get("loss_name")

    produced_in = {}
    for op in ops:
        for n in op.output_arg_names:
            produced_in.setdefault(n, stage_of[id(op)])

    def is_data(n):
        v = block._find_var_recursive(n)
        return v is not None and getattr(v, "is_data", False)

    def is_persistable(n):
        v = block._find_var_recursive(n)
        return v is not None and v.persistable

    stages = []
    for s in range(S):
        fwd_ops = [op for op, r in zip(ops_by_stage[s], role_by_stage[s])
                   if r not in ("backward", "optimize")]
        bwd_ops = [op for op, r in zip(ops_by_stage[s], role_by_stage[s])
                   if r == "backward"]
        opt_ops = [op for op, r in zip(ops_by_stage[s], role_by_stage[s])
                   if r == "optimize"]

        def boundary_inputs(stage_ops):
            acts, data = [], []
            produced_here = set()
            for op in stage_ops:
                for n in op.input_arg_names:
                    if n in produced_here or n in acts or n in data:
                        continue
                    if is_data(n):
                        data.append(n)
                    elif (n in produced_in and produced_in[n] != s
                          and not is_persistable(n)):
                        acts.append(n)
                produced_here.update(op.output_arg_names)
            return acts, data

        acts_in, data_fwd = boundary_inputs(fwd_ops)
        # backward program recomputes forward, then needs incoming grads
        bwd_all = fwd_ops + bwd_ops
        bwd_bound, data_bwd = boundary_inputs(bwd_all)
        grads_in = [n for n in bwd_bound if n not in acts_in]

        # activations this stage must export: produced here, consumed in
        # a later stage's forward/backward
        consumed_later = set()
        for op in ops:
            if stage_of[id(op)] > s \
                    and op.attrs.get("op_role") != "optimize":
                consumed_later.update(op.input_arg_names)
        acts_out = []
        for op in fwd_ops:
            for n in op.output_arg_names:
                if n in consumed_later and not is_persistable(n) \
                        and n not in acts_out:
                    acts_out.append(n)

        stage_pg = [(p, g) for p, g in pg.items()
                    if any(g in op.output_arg_names or
                           g in op.input_arg_names for op in bwd_ops)]
        stages.append(StageInfo(
            s, fwd_ops, bwd_ops, opt_ops, acts_in, acts_out, grads_in,
            sorted(set(data_fwd) | set(data_bwd)), stage_pg,
            loss_name if s == S - 1 else None))
    return stages, stage_of


def boundary_sets(stages):
    """The pipeline WIRE contents: ``boundary[b]`` is the ordered list of
    activation names crossing the stage-b → stage-b+1 link — everything
    a stage at index > b consumes (forward or backward-recompute) that a
    stage at index <= b produced.  A skip connection (produced at stage
    0, consumed at stage 2) appears in EVERY boundary it crosses, so the
    in-graph island can forward it hop by hop (the host scheduler ships
    it point-to-point instead)."""
    S = len(stages)
    produced_at = {}
    for st in stages:
        for op in st.fwd_ops:
            for n in op.output_arg_names:
                produced_at.setdefault(n, st.index)
    out = []
    for b in range(S - 1):
        names = []
        for st in stages[b + 1:]:
            for n in st.acts_in:
                if produced_at.get(n, S) <= b and n not in names:
                    names.append(n)
        out.append(names)
    return out


class _StagePrograms:
    """The three compiled faces of one pipeline stage."""

    def __init__(self, fwd, bwd, opt, acts_in, acts_out, grads_in_of_next,
                 data_feeds, param_grads, loss_name):
        self.fwd = fwd                # Program: acts_in+data → acts_out
        self.bwd = bwd                # Program: acts_in+data+d(acts_out) → d(acts_in)+param grads
        self.opt = opt                # Program or None: mean grads → param updates
        self.acts_in = acts_in        # boundary activation names (from prev)
        self.acts_out = acts_out      # boundary activation names (to next)
        self.grads_in_of_next = grads_in_of_next  # d(acts_out) names fed to bwd
        self.data_feeds = data_feeds  # data feed names this stage consumes
        self.param_grads = param_grads  # [(param, grad)] of this stage
        self.loss_name = loss_name    # set on the last stage


class PipelineRunner:
    """Compile a pipelined program and run GPipe steps.

    Usage:
        opt = fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.SGD(0.01), cut_list=[h1], num_microbatches=4)
        opt.minimize(loss)
        runner = PipelineRunner(main_program, scope=scope)
        (loss_val,) = runner.run(feed=batch, fetch_list=[loss.name])
    """

    def __init__(self, program, scope=None, place=None, mesh=None,
                 rules=None, feed_specs=None):
        """mesh: optional jax Mesh carrying a 'pp' axis of size n_stages —
        the dp×pp×mp hybrid.  The mesh is SLICED along pp: each stage owns
        a disjoint dp×mp submesh and its three programs run GSPMD-
        partitioned on it (rules/feed_specs as in HybridParallelRunner);
        only the O(boundary) activation/grad tensors cross stages through
        the host scheduler — the TPU shape of the reference's section
        placement (device_worker.py:184, each section on its own device).
        A mesh without a 'pp' axis runs every stage on the full mesh."""
        from paddle_tpu.fluid import executor as ex
        from paddle_tpu.fluid.framework import CPUPlace

        meta = getattr(program, "_pipeline", None)
        if meta is None:
            raise ValueError("program has no pipeline metadata; minimize() "
                             "with PipelineOptimizer first")
        self.program = program
        self.cut_vars = list(meta["cut_vars"])
        self.num_microbatches = int(meta["num_microbatches"])
        self.scope = scope or ex.global_scope()
        self.place = place or CPUPlace()
        self._exe = ex.Executor(self.place)
        self._step = 0
        self._build()
        self.mesh = mesh
        self.rules = rules
        self.feed_specs = dict(feed_specs or {})
        self._runners = {}
        self._stage_meshes = None
        if mesh is not None:
            self._stage_meshes = self._slice_mesh(mesh)

    def _slice_mesh(self, mesh):
        """One submesh per stage: slice the 'pp' axis (disjoint device
        groups, the real pipeline placement).  pp size must equal
        n_stages."""
        from jax.sharding import Mesh

        from . import mesh as pmesh

        if pmesh.PIPE_AXIS not in mesh.axis_names:
            return [mesh] * self.n_stages
        pp = mesh.shape[pmesh.PIPE_AXIS]
        if pp != self.n_stages:
            raise ValueError(
                f"mesh pp axis {pp} != pipeline stages {self.n_stages}")
        idx = list(mesh.axis_names).index(pmesh.PIPE_AXIS)
        devs = np.moveaxis(mesh.devices, idx, 0)
        rest = tuple(a for a in mesh.axis_names if a != pmesh.PIPE_AXIS)
        return [Mesh(devs[s], rest) for s in range(pp)]

    def _stage_runner(self, s, prog, kind):
        """HybridParallelRunner for stage s's fwd/bwd/opt program on its
        submesh (cached).  Optimizer feeds are full mean-gradients —
        replicate them instead of the dim-0-on-dp default, which would be
        wrong for weight-shaped tensors."""
        key = (s, kind)
        r = self._runners.get(key)
        if r is None:
            from .hybrid import HybridParallelRunner

            feed_specs = dict(self.feed_specs)
            if kind == "opt":
                feed_specs.update(
                    {g: () for _, g in self.stages[s].param_grads})
            r = HybridParallelRunner(prog, self._stage_meshes[s],
                                     rules=self.rules,
                                     feed_specs=feed_specs,
                                     scope=self.scope)
            self._runners[key] = r
        return r

    def _run_stage(self, s, prog, kind, step, feed, fetch_list):
        """Run one stage program: plain Executor, or GSPMD on the stage's
        submesh when a mesh is configured."""
        if self._stage_meshes is None:
            self._exe._step = step
            return self._exe.run(prog, feed=feed, fetch_list=fetch_list)
        r = self._stage_runner(s, prog, kind)
        r._step = step
        return r.run(self.scope, feed=feed, fetch_list=fetch_list)

    # -- program construction -------------------------------------------
    def _build(self):
        block = self.program.global_block()
        infos, _stage_of = stage_partition(self.program, block.ops,
                                           self.cut_vars)
        self.n_stages = len(infos)
        self.stages = []
        for si in infos:
            bwd_all = si.fwd_ops + si.bwd_ops
            fwd_prog = self._subprogram(
                si.fwd_ops, feed_vars=si.acts_in + si.data_feeds)
            bwd_prog = self._subprogram(
                bwd_all, feed_vars=si.acts_in + si.data_feeds + si.grads_in)
            opt_prog = (self._subprogram(
                si.opt_ops, feed_vars=[g for _, g in si.param_grads])
                if si.opt_ops else None)
            st = _StagePrograms(
                fwd_prog, bwd_prog, opt_prog, si.acts_in, si.acts_out,
                si.grads_in, si.data_feeds, si.param_grads, si.loss_name)
            self.stages.append(st)

    def _subprogram(self, ops, feed_vars):
        src = self.program.global_block()
        prog = Program()
        blk = prog.global_block()
        feed_set = set(feed_vars)
        names = []
        for op in ops:
            names.extend(op.input_arg_names)
            names.extend(op.output_arg_names)
        for n in dict.fromkeys(names):
            v = src._find_var_recursive(n)
            blk.create_var(
                name=n, shape=None if v is None else v.shape,
                dtype="float32" if v is None else v.dtype,
                persistable=bool(v is not None and v.persistable),
                is_data=n in feed_set,
                stop_gradient=True)
        for op in ops:
            blk.append_op(op.type,
                          inputs={k: [blk.var(n) for n in ns]
                                  for k, ns in op.inputs.items()},
                          outputs={k: [blk.var(n) for n in ns]
                                   for k, ns in op.outputs.items()},
                          attrs=dict(op.attrs))
        return prog

    # -- execution -------------------------------------------------------
    def run(self, feed=None, fetch_list=None, return_numpy=True):
        """One pipelined training step: split `feed` into M microbatches on
        dim 0, GPipe forward/backward, accumulate grads, apply optimizers.
        Fetches (from the last stage's forward) are averaged over
        microbatches."""
        M = self.num_microbatches
        feed = {k: np.asarray(v) for k, v in (feed or {}).items()}
        # each microbatch additionally dp-shards over the stage submesh —
        # validate here with a named error rather than letting stage 0's
        # jit raise an opaque not-divisible-by-shards error mid-schedule
        dp = 1
        if self._stage_meshes is not None:
            from . import mesh as pmesh

            dp = self._stage_meshes[0].shape.get(pmesh.DATA_AXIS, 1)
        for k, v in feed.items():
            if v.shape[0] % (M * dp):
                raise ValueError(
                    f"feed {k!r} batch {v.shape[0]} not divisible by "
                    f"num_microbatches={M}"
                    + (f" x submesh dp={dp}" if dp > 1 else ""))
        micro = [{k: v[m * (v.shape[0] // M):(m + 1) * (v.shape[0] // M)]
                  for k, v in feed.items()} for m in range(M)]
        fetch_names = [f if isinstance(f, str) else f.name
                       for f in (fetch_list or [])]
        last = self.stages[-1]

        acts = [dict() for _ in range(M)]   # microbatch → boundary name → val
        fetch_acc = [[] for _ in fetch_names]
        base_step = self._step

        # ---- forward fill ----
        for m in range(M):
            env = dict(micro[m])
            for s, st in enumerate(self.stages):
                feeds = {n: env[n] for n in st.acts_in}
                feeds.update({n: micro[m][n] for n in st.data_feeds
                              if n in micro[m]})
                wants = list(st.acts_out)
                if st.loss_name is not None:
                    wants = wants + [n for n in fetch_names if n not in wants]
                outs = self._run_stage(s, st.fwd, "fwd", base_step + m,
                                       feeds, wants) if wants else []
                got = dict(zip(wants, outs))
                env.update(got)
                acts[m].update({n: got[n] for n in st.acts_out})
                if st.loss_name is not None:
                    for i, n in enumerate(fetch_names):
                        fetch_acc[i].append(np.asarray(got[n]))

        # ---- backward drain (reverse microbatch order, GPipe) ----
        grad_sums = collections.defaultdict(lambda: 0.0)
        for m in reversed(range(M)):
            dacts = {}
            for s in reversed(range(self.n_stages)):
                st = self.stages[s]
                feeds = {n: acts[m].get(n, micro[m].get(n)) for n in st.acts_in}
                feeds.update({n: micro[m][n] for n in st.data_feeds
                              if n in micro[m]})
                feeds.update({n: dacts[n] for n in st.grads_in_of_next})
                wants = [grad_var_name(n) for n in st.acts_in] \
                    + [g for _, g in st.param_grads]
                outs = self._run_stage(s, st.bwd, "bwd", base_step + m,
                                       feeds, wants)
                got = dict(zip(wants, outs))
                for n in st.acts_in:
                    dacts[grad_var_name(n)] = got[grad_var_name(n)]
                for _, g in st.param_grads:
                    grad_sums[g] = grad_sums[g] + np.asarray(got[g])

        # ---- optimizer: mean grads, one update per stage ----
        for s, st in enumerate(self.stages):
            if st.opt is None or not st.param_grads:
                continue
            gfeed = {g: (grad_sums[g] / M).astype(np.float32)
                     for _, g in st.param_grads}
            self._run_stage(s, st.opt, "opt", base_step, gfeed, [])

        self._step += M
        result = [np.mean(np.stack(v), axis=0) if v else None
                  for v in fetch_acc]
        return result if return_numpy else result
