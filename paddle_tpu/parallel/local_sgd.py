"""LocalSGD: k local optimizer steps per device, then one parameter average.

Reference analog: transpiler/collective.py LocalSGD (:269) — every worker
runs SGD locally and a `c_allreduce_sum`+scale pair periodically averages the
parameters, cutting collective traffic by k×.

TPU-native redesign: the reference expresses "local divergence" through
per-GPU scopes; under jit's global-view semantics parameters are one logical
array, so divergence must live INSIDE the compiled step.  This runner scans
k micro-steps inside shard_map over the dp axis — within the scan each
device's parameter copy evolves independently (no collectives at all), and a
single lax.pmean at the end of the scan re-synchronizes before write-back.
One compiled program, one collective per k steps, and the scan keeps the
whole k-step loop on device (no host round-trips between local steps).

All floating parameter-state carries (params + optimizer accumulators) are
averaged at the sync point; integer state is taken as-is (replicated).
"""

from __future__ import annotations

import numpy as np

from . import mesh as pmesh

__all__ = ["LocalSGDRunner"]


class LocalSGDRunner:
    def __init__(self, program, k_steps, places=None, scope=None):
        import jax

        self.program = program
        self.k = int(k_steps)
        n = len(places) if places else jax.device_count()
        self.num_devices = n
        self.mesh = pmesh.build_mesh({pmesh.DATA_AXIS: n})
        self._default_scope = scope
        self._cache = {}
        self._step = 0

    def run(self, scope=None, feed_list=None, fetch_list=None,
            return_numpy=True):
        """feed_list: k feed dicts (one per local step); each feed's batch
        dim is additionally sharded over the dp axis.  Returns the fetches of
        every local step, stacked on a leading [k] axis (then the dp axis,
        FetchOpHandle concat semantics)."""
        from paddle_tpu.fluid import executor as ex

        scope = scope or self._default_scope or ex.global_scope()
        if len(feed_list) != self.k:
            raise ValueError(f"need {self.k} feeds, got {len(feed_list)}")
        names = sorted(feed_list[0].keys())
        stacked = {n: np.stack([np.asarray(f[n]) for f in feed_list])
                   for n in names}
        fetch_names = [f if isinstance(f, str) else f.name
                       for f in (fetch_list or [])]
        sig = tuple((n, tuple(v.shape), str(v.dtype))
                    for n, v in sorted(stacked.items()))
        key = (self.program._version, sig, tuple(fetch_names))
        cb = self._cache.get(key)
        if cb is None:
            cb = self._compile(scope, names, fetch_names)
            self._cache[key] = cb
        out = cb(scope, stacked, self._step)
        self._step += self.k
        if return_numpy:
            return [np.asarray(f) for f in out]
        return out

    def _compile(self, scope, feed_names, fetch_names):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.fluid.executor import BlockPlan

        plan = BlockPlan(self.program, self.program.global_block(),
                         feed_names, fetch_names, scope)
        if plan.host_pre_ops:
            raise NotImplementedError(
                "pre-stage host ops (distributed lookup) are only "
                "supported by the single-device Executor")
        axis = pmesh.DATA_AXIS
        inner = plan.make_body(mesh_axes=(axis,))
        donated, readonly = plan.donated_names, plan.readonly_names
        write_names = plan.write_names
        k = self.k

        def body(don, ro, feeds, step0):
            def one(carry, xs):
                step_i, feed = xs
                fetches, out_writes = inner(carry, ro, feed, step_i)
                new_carry = {n: out_writes.get(n, v) for n, v in carry.items()}
                extra = {n: v for n, v in out_writes.items()
                         if n not in new_carry}
                fetches = [jnp.reshape(v, (1,) + tuple(jnp.shape(v)))
                           if jnp.ndim(v) == 0 else v for v in fetches]
                return new_carry, (fetches, extra)

            steps = step0 + jnp.arange(k, dtype=jnp.uint32)
            carry, (fetches, extras) = jax.lax.scan(one, dict(don),
                                                    (steps, feeds))
            # sync point: average the float state that diverged locally
            synced = {
                n: jax.lax.pmean(v, axis)
                if jnp.issubdtype(v.dtype, jnp.floating) else v
                for n, v in carry.items()
            }
            # non-carry writes (e.g. BN stats not re-read): last step's value
            last_extra = {n: v[-1] for n, v in extras.items()}
            out_writes = dict(last_extra)
            out_writes.update(synced)
            return fetches, out_writes

        if plan.host_ops:
            raise NotImplementedError(
                "LocalSGD cannot scan host (RPC/IO) ops inside the compiled "
                "k-step loop")
        in_specs = ({n: P() for n in donated}, {n: P() for n in readonly},
                    {n: P(None, axis) for n in feed_names}, P())
        out_specs = ([P(None, axis) for _ in plan.jit_fetch_names],
                     {n: P() for n in write_names})
        sharded = jax.shard_map(body, mesh=self.mesh, in_specs=in_specs,
                                out_specs=out_specs, check_vma=False)
        jitted = jax.jit(sharded, donate_argnums=(0,))

        def compiled(scope_, feeds, step):
            import warnings

            don_vals = {n: scope_.get(n) for n in donated}
            ro_vals = {n: scope_.get(n) for n in readonly}
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                fetches, out_writes = jitted(don_vals, ro_vals, feeds,
                                             np.uint32(step))
            for n, v in out_writes.items():
                scope_.set(n, v)
            return fetches

        return compiled
