"""Data-parallel execution: shard_map over a device mesh.

Reference analog: paddle/fluid/framework/parallel_executor.cc + the
multi_devices_graph_pass (multi_devices_graph_pass.cc:169) which clones every
op onto every GPU, inserts ScaleLossGradOpHandle (1/ndev seed, :267) and one
AllReduceOpHandle per gradient (:594), then schedules the SSA graph with a
thread pool per device and NCCL rings.

TPU-native redesign: ONE program, compiled ONCE under jax.shard_map over a
Mesh({'dp': n}).  The transpiler below performs the same graph rewrite the
reference's pass does — scale the loss-grad seed by 1/ndev, insert a
`c_allreduce_sum` op on every parameter gradient before its optimizer op —
but the collectives lower to lax.psum over ICI and XLA overlaps them with the
backward computation (the fuse_all_reduce/all_reduce_deps passes are subsumed
by XLA's all-reduce combiner).  Feeds are batch-sharded on dim 0; parameters
stay replicated; fetches are concatenated across devices like the reference's
FetchOpHandle (scalar fetches become per-device [n] vectors).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.fluid.framework import grad_var_name
from . import mesh as pmesh

__all__ = ["DataParallelRunner", "transpile_data_parallel"]


def transpile_data_parallel(program, loss_name, num_devices,
                            gradient_scale="coeff_num_device",
                            sync_batch_norm_stats=True):
    """Rewrite `program` in place for data-parallel execution.

    Mirrors multi_devices_graph_pass: (1) the loss-gradient seed becomes
    1/ndev, (2) every optimizer-consumed gradient gets a c_allreduce_sum
    (ring 0 = the dp axis), (3) batch-norm running stats are averaged across
    devices so the single written copy is well-defined.
    """
    block = program.global_block()
    if loss_name is not None and gradient_scale == "coeff_num_device":
        seed_name = grad_var_name(loss_name)
        for op in block.ops:
            if op.type == "fill_constant" and seed_name in op.output_arg_names:
                op.attrs["value"] = float(op.attrs.get("value", 1.0)) / num_devices

    # Allreduce each RAW parameter gradient right after it is produced —
    # the reference inserts AllReduceOpHandle at the same point
    # (multi_devices_graph_pass.cc:594), so weight decay / gradient clipping
    # downstream operate on the full (averaged) gradient, not per-device
    # partials.  Raw grad names are recorded by Optimizer.apply_gradients.
    from paddle_tpu.fluid.framework import Operator

    raw_grads = {g for _, g in getattr(program, "_params_grads", [])}
    if not raw_grads:  # fallback: grads feeding optimizer ops directly
        raw_grads = {op.inputs["Grad"][0] for op in block.ops
                     if op.attrs.get("op_role") == "optimize" and "Grad" in op.inputs}
    # DGC moves the allreduce onto the compressed gradient (the reference's
    # SparseAllReduceOpHandle placement): watch the encoded var instead
    dgc_map = getattr(program, "_dgc_encoded", {})
    raw_grads = {dgc_map.get(g, g) for g in raw_grads}

    new_ops = []
    pending = set(raw_grads)
    for op in block.ops:
        new_ops.append(op)
        produced = pending.intersection(op.output_arg_names)
        for g in produced:
            pending.discard(g)
            new_ops.append(Operator(
                block, "c_allreduce_sum",
                inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"ring_id": 0, "use_calc_stream": True,
                       "op_role": "backward"}))
        if sync_batch_norm_stats and op.type == "batch_norm" and not op.attrs.get("is_test"):
            from paddle_tpu.fluid.framework import Operator

            for slot in ("MeanOut", "VarianceOut"):
                names = op.outputs.get(slot, [])
                if names:
                    new_ops.append(Operator(
                        block, "c_allreduce_avg",
                        inputs={"X": [names[0]]}, outputs={"Out": [names[0]]},
                        attrs={"ring_id": 0, "op_role": "forward"}))
    block.ops = new_ops
    program._bump_version()
    return program


class DataParallelRunner:
    """Compiles + runs a data-parallel program over all local devices."""

    def __init__(self, program, loss_name, build_strategy=None, places=None):
        import jax

        n = len(places) if places else jax.device_count()
        self.num_devices = n
        self.mesh = pmesh.build_mesh({pmesh.DATA_AXIS: n})
        # rewrite in place, like the reference's multi-device pass
        self.program = transpile_data_parallel(
            program, loss_name, n,
            sync_batch_norm_stats=(build_strategy is None
                                   or getattr(build_strategy, "sync_batch_norm", True) is not False))
        self._cache = {}

    def run(self, executor, feed, fetch_list, scope, return_numpy=True):
        import jax

        from paddle_tpu.fluid import executor as ex

        scope = scope or ex.global_scope()
        feed = executor._coerce_feed(self.program, feed or {})
        fetch_names = [f.name if not isinstance(f, str) else f for f in (fetch_list or [])]
        for k, v in feed.items():
            if np.shape(v) and np.shape(v)[0] % self.num_devices != 0:
                raise ValueError(
                    f"feed {k!r} batch {np.shape(v)[0]} not divisible by "
                    f"{self.num_devices} devices")
        feed_sig = tuple((k, tuple(np.shape(v)), str(np.asarray(v).dtype))
                         for k, v in sorted(feed.items()))
        key = (id(self.program), self.program._version, feed_sig, tuple(fetch_names))
        cb = self._cache.get(key)
        if cb is None:
            cb = _ShardedBlock(self.program, feed.keys(), fetch_names, self.mesh, scope)
            self._cache[key] = cb
        fetches = cb.run(scope, feed, executor._step)
        executor._step += 1
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches


class _ShardedBlock:
    def __init__(self, program, feed_names, fetch_names, mesh, scope):
        import jax
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.fluid.executor import BlockPlan

        plan = BlockPlan(program, program.global_block(), feed_names,
                         fetch_names, scope)
        if plan.host_pre_ops:
            raise NotImplementedError(
                "pre-stage host ops (distributed lookup) are only "
                "supported by the single-device Executor")
        self.plan = plan
        self.feed_names = plan.feed_names
        self.fetch_names = plan.fetch_names
        self.ops = plan.ops
        self.donated_names = plan.donated_names
        self.readonly_names = plan.readonly_names
        self.write_names = plan.write_names
        axis = pmesh.DATA_AXIS
        inner = plan.make_body(mesh_axes=(axis,))

        def body(donated, readonly, feeds, step):
            import jax.numpy as jnp

            raw_fetches, out_writes = inner(donated, readonly, feeds, step)
            # scalar fetches become per-device [1] vectors so the dp-axis
            # concat (FetchOpHandle semantics) has a dim to stack on
            fetches = [jnp.reshape(v, (1,) + tuple(jnp.shape(v)))
                       if jnp.ndim(v) == 0 else v for v in raw_fetches]
            return fetches, out_writes

        in_specs = (
            {n: P() for n in self.donated_names},
            {n: P() for n in self.readonly_names},
            {n: P(axis) for n in self.feed_names},
            P(),
        )
        out_specs = ([P(axis) for _ in plan.jit_fetch_names],
                     {n: P() for n in self.write_names})
        sharded = jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_vma=False)
        self._jitted = jax.jit(sharded, donate_argnums=(0,))
        self.mesh = mesh

    def run(self, scope, feeds, step):
        import warnings

        from paddle_tpu.fluid import profiler as _prof

        if not hasattr(self, "_prof_state"):
            self._prof_state = {"ran": False}
        with _prof.timed_run(f"dp_block@{id(self):x}", self._prof_state) as timer:
            donated = {n: scope.get(n) for n in self.donated_names}
            readonly = {n: scope.get(n) for n in self.readonly_names}
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                fetches, out_writes = self._jitted(donated, readonly, dict(feeds),
                                                   np.uint32(step))
            for n, v in out_writes.items():
                scope.set(n, v)
            timer.done(fetches, out_writes)
        # PS-mode programs carry host RPC ops — run them, don't drop them
        self.plan.run_host_ops(scope)
        return self.plan.assemble_fetches(fetches, scope)
