"""Data-parallel execution: shard_map over a device mesh.

Reference analog: paddle/fluid/framework/parallel_executor.cc + the
multi_devices_graph_pass (multi_devices_graph_pass.cc:169) which clones every
op onto every GPU, inserts ScaleLossGradOpHandle (1/ndev seed, :267) and one
AllReduceOpHandle per gradient (:594), then schedules the SSA graph with a
thread pool per device and NCCL rings.

TPU-native redesign: ONE program, compiled ONCE under jax.shard_map over a
Mesh({'dp': n}).  The transpiler below performs the same graph rewrite the
reference's pass does — scale the loss-grad seed by 1/ndev, insert a
`c_allreduce_sum` op on every parameter gradient before its optimizer op —
but the collectives lower to lax.psum over ICI and XLA overlaps them with the
backward computation (the fuse_all_reduce/all_reduce_deps passes are subsumed
by XLA's all-reduce combiner).  Feeds are batch-sharded on dim 0; parameters
stay replicated; fetches are concatenated across devices like the reference's
FetchOpHandle (scalar fetches become per-device [n] vectors).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.fluid.executor import _JitExecutable
from paddle_tpu.fluid.framework import grad_var_name
from . import mesh as pmesh

__all__ = ["DataParallelRunner", "transpile_data_parallel"]


def collective_payload_counter():
    """The one schema for ``pt_collective_payload_bytes_total`` —
    shared by the DP runner's per-step estimate and the hybrid runner's
    ZeRO-gather booking, so the two call sites cannot drift into the
    registry's re-registration conflict."""
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_collective_payload_bytes_total",
        "Estimated per-device ICI payload moved by gradient/BN "
        "collectives (both phases counted; static shapes only)",
        labels=("collective",))


def overlap_buckets_counter():
    """Gradient buckets whose collective dispatched in READY ORDER
    (immediately after the last member gradient was produced, so the ring
    hops overlap the remaining backward compute) — emitted per executed
    step from the transpile-time schedule (docs/OBSERVABILITY.md)."""
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_overlap_buckets_ready_total",
        "Gradient buckets dispatched in ready order (overlap with "
        "backward compute) per step")


def fused_update_bytes_counter():
    """Modeled HBM bytes the fused dequant->update->requant step kernels
    avoid per step (the fp32 intermediate's write+read,
    kernels.fused_update.bytes_saved) — shared by the DP and hybrid
    runners' bookings."""
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_fused_update_bytes_saved_total",
        "Modeled fp32 HBM round-trip bytes avoided by fused "
        "dequant->optimizer-update->requant step kernels")


# optimizer ops the fused-update rewrite can absorb: their Grad input is
# replaced by the bucket's wire-format image (int8 + scales), the update
# dequantizes the member's block-aligned slice inline
_FUSED_UPDATE_OPS = {"sgd": "fused_sgd_quant_grad",
                     "adam": "fused_adam_quant_grad",
                     "adamw": "fused_adamw_quant_grad",
                     "lamb": "fused_lamb_quant_grad",
                     "momentum": "fused_momentum_quant_grad"}


def _plan_quant_buckets(block, grads, prod_index, block_size, bucket_mb):
    """fuse_all_reduce_op_pass analog: group same-dtype grads into fused
    buckets (capped at ``bucket_mb`` MB) so one quantized collective per
    bucket replaces one fp32 collective per grad — per-block scale
    overhead and collective-launch count amortize over the bucket.

    Returns (buckets, leftovers): each bucket is a dict with the member
    grad names (production order), their shapes, dtype, and the op index
    after which the fused ops insert (= last member's producer).
    Leftovers are grads that cannot be bucketed (dynamic shape / no var /
    non-float dtype) and keep the per-grad fp32 allreduce.
    """
    cap_bytes = max(1, int(float(bucket_mb) * (1 << 20)))
    eligible, leftovers = [], []
    for g in sorted(grads, key=lambda g: prod_index[g]):
        v = block._find_var_recursive(g)
        shape = tuple(v.shape) if (v is not None and v.shape) else None
        dtype = v.dtype if v is not None else None
        if (shape is None or any(d is None or d < 0 for d in shape)
                or dtype not in ("float32", "float16", "bfloat16")):
            leftovers.append(g)
            continue
        eligible.append((g, shape, dtype))

    itemsize = {"float32": 4, "float16": 2, "bfloat16": 2}
    buckets = []
    open_by_dtype = {}
    for g, shape, dtype in eligible:
        nbytes = int(np.prod(shape)) * itemsize[dtype]
        b = open_by_dtype.get(dtype)
        if b is None or b["bytes"] + nbytes > cap_bytes:
            b = {"grads": [], "shapes": [], "dtype": dtype, "bytes": 0,
                 "insert_at": -1}
            buckets.append(b)
            open_by_dtype[dtype] = b
        b["grads"].append(g)
        b["shapes"].append(list(shape))
        b["bytes"] += nbytes
        b["insert_at"] = max(b["insert_at"], prod_index[g])
    return buckets, leftovers


def _plan_fused_updates(block, buckets, block_size):
    """Fused-update eligibility (FLAGS_fused_update): a bucket qualifies
    when EVERY member gradient has exactly one consumer in the original
    program and that consumer is an sgd/adam op taking it as `Grad` —
    then the bucket's collective can keep the wire format
    (`c_allreduce_quant_keep`), the uncoalesce disappears, and each
    optimizer op is rewritten to its fused dequant→update variant.  Any
    other consumer (gradient clip, weight decay reading the raw grad, a
    fetch-feeding op) keeps the whole bucket on the unfused path: with
    the uncoalesce gone, nothing would rewrite the member var to its
    reduced value.  Returns {id(optimizer op): (bucket, grad)} and
    annotates qualifying buckets with block-aligned member offsets."""
    member = {g: b for b in buckets for g in b["grads"]}
    consumers = {}
    for op in block.ops:
        for g in set(op.input_arg_names):
            if g in member:
                consumers.setdefault(g, []).append(op)
    rewrites = {}
    bs_q = int(block_size)
    for b in buckets:
        ops_for = []
        for g in b["grads"]:
            cons = consumers.get(g, [])
            if (len(cons) == 1 and cons[0].type in _FUSED_UPDATE_OPS
                    and cons[0].inputs.get("Grad") == [g]):
                ops_for.append(cons[0])
            else:
                ops_for = None
                break
        if not ops_for:
            continue
        # block-aligned packing: each member starts on a quantization
        # block boundary so its slice of the wire image is whole blocks
        off, offsets = 0, []
        for s in b["shapes"]:
            offsets.append(off // bs_q)
            numel = int(np.prod(s))
            off += numel + (-numel) % bs_q
        raw = sum(int(np.prod(s)) for s in b["shapes"])
        if off > 2 * raw:
            # sub-block members: alignment padding would more than double
            # the wire payload — the HBM round-trip saved is worth less
            # than the extra ICI bytes, keep the unfused form (the same
            # size-adaptivity the ZeRO gather's sub-block gate applies)
            continue
        b["fused_update"] = True
        b["offsets"], b["aligned_elems"] = offsets, off
        for g, op in zip(b["grads"], ops_for):
            rewrites[id(op)] = (b, g)
    return rewrites


def _create_bucket_vars(block, buckets, num_devices, block_size,
                        quant_algo, quant_crossover_kb):
    """Resolve each bucket's collective algorithm (stamped once, used by
    the emission, the wire-bytes accounting and the q-var shapes) and
    create the fused buffer — plus, for fused-update buckets, the
    wire-format output vars of `c_allreduce_quant_keep` with the exact
    padded shapes the lowering produces."""
    from paddle_tpu.kernels import quantized_collectives as qc
    from paddle_tpu.kernels.ring_collectives import select_allreduce_algo

    bs_q = int(block_size)
    for k, b in enumerate(buckets):
        b["elements"] = (b["aligned_elems"] if b.get("fused_update")
                         else sum(int(np.prod(s)) for s in b["shapes"]))
        b["algo"] = select_allreduce_algo(
            b["elements"], num_devices, algo=quant_algo,
            crossover_kb=quant_crossover_kb, block_size=bs_q)
        b["fused"] = block.create_var(
            name=f"@FUSED_GRAD_QUANT@_{b['dtype']}_{k}",
            dtype=b["dtype"], shape=[b["elements"]])
        if b.get("fused_update"):
            padded = qc.quant_padded_elems(b["elements"], num_devices,
                                           bs_q, algo=b["algo"])
            base = f"@FUSED_GRAD_QUANT@_{b['dtype']}_{k}"
            b["qhi"] = block.create_var(name=base + "@QHI", dtype="int8",
                                        shape=[padded])
            b["qlo"] = block.create_var(name=base + "@QLO", dtype="int8",
                                        shape=[padded])
            b["qsc"] = block.create_var(name=base + "@QSCALE",
                                        dtype="float32",
                                        shape=[padded // bs_q])


def _make_fused_update_op(block, op, b, g, block_size):
    """Rewrite one sgd/adam op into its fused dequant→update variant:
    the `Grad` input becomes the bucket's wire-format triple plus the
    member's block offset/size attrs (kernels/fused_update.py)."""
    from paddle_tpu.fluid.framework import Operator

    i = b["grads"].index(g)
    inputs = {slot: list(names) for slot, names in op.inputs.items()
              if slot != "Grad"}
    inputs["QHi"] = [b["qhi"].name]
    inputs["QLo"] = [b["qlo"].name]
    inputs["QScale"] = [b["qsc"].name]
    attrs = dict(op.attrs)
    attrs.update(offset_blocks=int(b["offsets"][i]),
                 numel=int(np.prod(b["shapes"][i])),
                 block_size=int(block_size))
    return Operator(block, _FUSED_UPDATE_OPS[op.type], inputs=inputs,
                    outputs={s: list(n) for s, n in op.outputs.items()},
                    attrs=attrs)


def transpile_data_parallel(program, loss_name, num_devices,
                            gradient_scale="coeff_num_device",
                            sync_batch_norm_stats=True,
                            quant_grads=False, quant_block_size=None,
                            quant_bucket_mb=None, quant_algo=None,
                            quant_crossover_kb=None, overlap=None,
                            fused_update=None):
    """Rewrite `program` in place for data-parallel execution.

    Mirrors multi_devices_graph_pass: (1) the loss-gradient seed becomes
    1/ndev, (2) every optimizer-consumed gradient gets a c_allreduce_sum
    (ring 0 = the dp axis), (3) batch-norm running stats are averaged across
    devices so the single written copy is well-defined.

    quant_grads=True (FLAGS_quant_allreduce / DataParallelRunner knob)
    additionally runs the fuse_all_reduce_op_pass analog: same-dtype
    gradients coalesce into a few fused buffers and each buffer takes ONE
    block-scaled int8 `c_allreduce_quant` instead of a per-grad fp32
    `c_allreduce_sum`.  Explicitly excluded from quantization: DGC-encoded
    gradients (already compressed — requantizing would destroy the top-k
    sparsity the reference's SparseAllReduce relies on) and batch-norm
    running stats (small, fp32-averaged, quality-critical); both keep
    their exact collectives.

    quant_algo / quant_crossover_kb (FLAGS_quant_allreduce_algo /
    FLAGS_quant_allreduce_crossover_kb when None): each bucket's
    collective algorithm is resolved HERE — at transpile time, per bucket
    size, via kernels.ring_collectives.select_allreduce_algo — and
    stamped onto the op's `algo` attr, so the lowering runs exactly what
    the wire-bytes accounting (and the bench record) models.  "auto"
    sends small buckets through the one-shot O(1)-launch form and large
    ones through the ppermute ring (2*(n-1)/n of payload bytes, int8 on
    every hop) — the BIDIRECTIONAL ring (`ring_bidir`, both ICI
    directions at once) when the bucket clears `bidir_eligible`.

    overlap (FLAGS_overlap_allreduce when None, default ON): READY-ORDER
    bucket dispatch — each bucket's collective is emitted immediately
    after the last gradient it covers is produced (reverse-topological
    order of the backward), so XLA's async collective scheduling can
    overlap the ring hops with the remaining backward compute.  Off =
    every gradient collective (bucketed AND per-grad fp32) defers to
    after the full backward — the no-overlap baseline the
    PT_BENCH_OVERLAP A/B rung measures against.  The schedule lands in
    ``program._overlap_schedule`` (per-bucket insert point + the fraction
    of the backward already executed at dispatch) and feeds
    ``pt_overlap_buckets_ready_total``.

    fused_update (FLAGS_fused_update when None, default ON): buckets
    whose members each feed EXACTLY ONE sgd/adam optimizer op are kept in
    the wire format end to end — members pack block-ALIGNED
    (`coalesce_tensor` attr align), the collective becomes
    `c_allreduce_quant_keep` (int8 + scales out, no final dequant), the
    `uncoalesce_tensor` disappears, and each member's optimizer op is
    rewritten to its fused variant (`fused_adam_quant_grad` /
    `fused_sgd_quant_grad`) that dequantizes its block slice inline with
    the update — the reduced fp32 bucket never round-trips HBM
    (kernels/fused_update.py; saved bytes booked on
    ``pt_fused_update_bytes_saved_total``).  A gradient with any OTHER
    consumer (clip/regularizer/a second op) keeps the unfused form; note
    that fetching a fused-away gradient by name returns the local
    pre-reduce value, since nothing rewrites it in the fused program.
    """
    block = program.global_block()
    if loss_name is not None and gradient_scale == "coeff_num_device":
        seed_name = grad_var_name(loss_name)
        for op in block.ops:
            if op.type == "fill_constant" and seed_name in op.output_arg_names:
                op.attrs["value"] = float(op.attrs.get("value", 1.0)) / num_devices

    # Allreduce each RAW parameter gradient right after it is produced —
    # the reference inserts AllReduceOpHandle at the same point
    # (multi_devices_graph_pass.cc:594), so weight decay / gradient clipping
    # downstream operate on the full (averaged) gradient, not per-device
    # partials.  Raw grad names are recorded by Optimizer.apply_gradients.
    from paddle_tpu.fluid.framework import Operator

    raw_grads = {g for _, g in getattr(program, "_params_grads", [])}
    if not raw_grads:  # fallback: grads feeding optimizer ops directly
        raw_grads = {op.inputs["Grad"][0] for op in block.ops
                     if op.attrs.get("op_role") == "optimize" and "Grad" in op.inputs}
    # DGC moves the allreduce onto the compressed gradient (the reference's
    # SparseAllReduceOpHandle placement): watch the encoded var instead
    dgc_map = getattr(program, "_dgc_encoded", {})
    dgc_encoded = set(dgc_map.values())
    raw_grads = {dgc_map.get(g, g) for g in raw_grads}

    from paddle_tpu.fluid import flags as _flags

    if overlap is None:
        overlap = _flags.flag("overlap_allreduce")
    overlap = bool(overlap)
    if fused_update is None:
        fused_update = _flags.flag("fused_update")
    fused_update = bool(fused_update)

    # producer indices against the ORIGINAL op list (ops are only ever
    # appended after, so indices stay valid while the rewritten list
    # grows); backward_end = the op after which every raw gradient exists
    # (the no-overlap dispatch point), backward_start = the first
    # grad-producing op — ready_frac measures position WITHIN the
    # backward span, else a long forward would inflate every bucket
    # toward 1.0 and the overlap telemetry would read as no-headroom
    prod_index = {}
    backward_start = None
    for i, op in enumerate(block.ops):
        if backward_start is None and any(
                "@GRAD" in n for n in op.output_arg_names):
            backward_start = i
        for g in raw_grads.intersection(op.output_arg_names):
            prod_index[g] = i  # last producer wins
    backward_end = max(prod_index.values()) if prod_index else -1
    if backward_start is None or backward_start > backward_end:
        backward_start = 0

    # plan the quantized buckets
    buckets, bucketed = [], {}
    fused_rewrites = {}  # id(optimizer op) -> (bucket, grad name)
    if quant_grads:
        if quant_block_size is None:
            quant_block_size = _flags.flag("quant_allreduce_block_size")
        if quant_bucket_mb is None:
            quant_bucket_mb = _flags.flag("fuse_grad_size_in_MB")
        if quant_algo is None:
            quant_algo = _flags.flag("quant_allreduce_algo")
        if quant_crossover_kb is None:
            quant_crossover_kb = _flags.flag("quant_allreduce_crossover_kb")
        candidates = {g for g in raw_grads
                      if g in prod_index and g not in dgc_encoded}
        buckets, _left = _plan_quant_buckets(
            block, candidates, prod_index, quant_block_size,
            quant_bucket_mb)
        for b in buckets:
            for g in b["grads"]:
                bucketed[g] = b
        if fused_update and num_devices > 1:
            fused_rewrites = _plan_fused_updates(block, buckets,
                                                 quant_block_size)
        _create_bucket_vars(block, buckets, num_devices, quant_block_size,
                            quant_algo, quant_crossover_kb)

    # standing collective-payload accounting (docs/OBSERVABILITY.md):
    # per-device ICI bytes one step moves, both phases of each collective
    # counted (reduce-scatter + all-gather for fp32, the two int8 phase
    # boundaries for quant) — the runner adds these to
    # pt_collective_payload_bytes_total every step.  Dynamic-shape grads
    # are skipped (estimate, documented as such).
    collective_bytes = {"c_allreduce_sum": 0, "c_allreduce_quant": 0,
                        "c_allreduce_avg": 0}
    _itemsize = {"float32": 4, "float16": 2, "bfloat16": 2, "float64": 8}

    def _static_bytes(name):
        v = block._find_var_recursive(name)
        if v is None or not v.shape or any(
                d is None or d < 0 for d in v.shape):
            return 0
        return int(np.prod(v.shape)) * _itemsize.get(v.dtype, 4)

    quant_plan = {"block_size": int(quant_block_size or 0),
                  "algo": quant_algo, "crossover_kb": quant_crossover_kb,
                  "buckets": []}
    schedule = {"enabled": overlap, "backward_start": backward_start,
                "backward_end": backward_end, "buckets": []}
    bwd_span = max(1, backward_end - backward_start)
    fused_saved_bytes = 0

    def _emit_bucket(b, out, insert_at):
        from paddle_tpu.kernels import fused_update as fu
        from paddle_tpu.kernels import quantized_collectives as qc

        nonlocal fused_saved_bytes
        fused = b["fused"].name
        n_elems, algo = b["elements"], b["algo"]
        is_fused = bool(b.get("fused_update"))
        out.append(Operator(
            block, "coalesce_tensor",
            inputs={"Input": list(b["grads"])},
            outputs={"FusedOutput": [fused]},
            attrs={"dtype": b["dtype"], "op_role": "backward",
                   **({"align": int(quant_block_size)} if is_fused
                      else {})}))
        if is_fused:
            # keep the reduced bucket in the wire format — the rewritten
            # optimizer ops dequantize their block slice inline
            out.append(Operator(
                block, "c_allreduce_quant_keep",
                inputs={"X": [fused]},
                outputs={"QHi": [b["qhi"].name], "QLo": [b["qlo"].name],
                         "QScale": [b["qsc"].name]},
                attrs={"ring_id": 0, "use_calc_stream": True,
                       "block_size": int(quant_block_size),
                       "algo": algo, "op_role": "backward"}))
            fused_saved_bytes += fu.bytes_saved(n_elems)
        else:
            out.append(Operator(
                block, "c_allreduce_quant",
                inputs={"X": [fused]}, outputs={"Out": [fused]},
                attrs={"ring_id": 0, "use_calc_stream": True,
                       "block_size": int(quant_block_size),
                       "algo": algo, "op_role": "backward"}))
            out.append(Operator(
                block, "uncoalesce_tensor",
                inputs={"X": [fused]}, outputs={"Out": list(b["grads"])},
                attrs={"shapes": [list(s) for s in b["shapes"]],
                       "op_role": "backward"}))
        collective_bytes["c_allreduce_quant"] += qc.wire_bytes(
            n_elems, block_size=int(quant_block_size),
            n_devices=num_devices, algo=algo)
        quant_plan["buckets"].append({"elements": n_elems, "algo": algo,
                                      "fused_update": is_fused})
        schedule["buckets"].append({
            "elements": n_elems, "algo": algo, "fused_update": is_fused,
            "insert_at": insert_at,
            # fraction of the BACKWARD SPAN already executed when this
            # bucket's collective dispatches — 1.0 means zero overlap
            "ready_frac": round(min(1.0, max(
                0.0, (insert_at - backward_start) / bwd_span)), 4)
            if backward_end >= 0 else 1.0})

    new_ops = []
    deferred = []  # collectives held back until after the full backward
    pending = set(raw_grads)
    for op_idx, op in enumerate(block.ops):
        if id(op) in fused_rewrites:
            b, g = fused_rewrites[id(op)]
            new_ops.append(_make_fused_update_op(block, op, b, g,
                                                 quant_block_size))
            continue
        new_ops.append(op)
        produced = pending.intersection(op.output_arg_names)
        for g in produced:
            pending.discard(g)
            if g in bucketed:
                continue  # fused collective emitted at the bucket boundary
            ar = Operator(
                block, "c_allreduce_sum",
                inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"ring_id": 0, "use_calc_stream": True,
                       "op_role": "backward"})
            (new_ops if overlap else deferred).append(ar)
            collective_bytes["c_allreduce_sum"] += 2 * _static_bytes(g)
        for b in buckets:
            if b["insert_at"] == op_idx:
                _emit_bucket(b, new_ops if overlap else deferred,
                             op_idx if overlap else backward_end)
        if not overlap and op_idx == backward_end and deferred:
            # no-overlap baseline: every gradient collective dispatches
            # here, after the last gradient producer
            new_ops.extend(deferred)
            deferred = []
        if sync_batch_norm_stats and op.type == "batch_norm" and not op.attrs.get("is_test"):
            for slot in ("MeanOut", "VarianceOut"):
                names = op.outputs.get(slot, [])
                if names:
                    new_ops.append(Operator(
                        block, "c_allreduce_avg",
                        inputs={"X": [names[0]]}, outputs={"Out": [names[0]]},
                        attrs={"ring_id": 0, "op_role": "forward"}))
                    collective_bytes["c_allreduce_avg"] += \
                        2 * _static_bytes(names[0])
    block.ops = new_ops
    if num_devices <= 1:  # psum over one device moves nothing
        collective_bytes = {k: 0 for k in collective_bytes}
        fused_saved_bytes = 0
    program._collective_bytes_per_step = collective_bytes
    # per-bucket algorithm/size report for the PT_BENCH_QUANTAR rung —
    # lets the bench record BOTH algorithms' modeled bytes beside the one
    # that actually ran
    program._quant_allreduce_plan = quant_plan if quant_grads else None
    # ready-order scheduling report (the transpile summary): feeds the
    # bench record and pt_overlap_buckets_ready_total
    program._overlap_schedule = schedule if quant_grads else None
    program._fused_update_bytes_saved = fused_saved_bytes
    program._bump_version()
    return program


class DataParallelRunner:
    """Compiles + runs a data-parallel program over all local devices.

    Two execution lanes behind one API (docs/DISTRIBUTED.md "GSPMD
    execution core" decision matrix):

    - transpiler (default): the in-place multi-device graph rewrite
      below plus a shard_map — every gradient collective is an explicit
      program op this runner inserted.
    - gspmd=True (FLAGS_gspmd_executor / BuildStrategy.gspmd_executor):
      the UNmodified program compiles under the one jit-partitioned
      `parallel.gspmd.GSPMDExecutor` with a `DataParallelPolicy` — no
      collective ops inserted by Python, XLA places them all; the
      quantized wire format survives through the quant hook when
      ``quant_grads`` is on.  This runner is then a thin policy
      selection.  Fetch convention difference (documented): global-view
      fetches are the GLOBAL value (the loss is the global-batch mean
      scalar), where the transpiler lane stacks per-device values —
      `np.mean` of a scalar fetch agrees across both.
    """

    def __init__(self, program, loss_name, build_strategy=None, places=None,
                 quant_grads=None, quant_algo=None, overlap=None,
                 fused_update=None, gspmd=None, policy_pin=None):
        import jax

        n = len(places) if places else jax.device_count()
        self.num_devices = n
        self.mesh = pmesh.build_mesh({pmesh.DATA_AXIS: n})
        # autotune pin (docs/AUTOTUNE.md "Pinning"): an explicit pin — a
        # Candidate, a saved report (dict or path) — or the standing
        # FLAGS_autotune_report path overrides the lane/mesh/policy
        # selection below with the tuner's measured winner.
        if policy_pin is None:
            from paddle_tpu.fluid import flags as _flags

            policy_pin = _flags.flag("autotune_report") or None
        self.policy_pin = None
        if policy_pin is not None:
            from . import autotune as _autotune

            pin = _autotune.resolve_pin(policy_pin)
            if pin.n_devices != n:
                raise ValueError(
                    f"autotune pin {pin.label()} was tuned for "
                    f"{pin.n_devices} devices but this runner has {n}")
            self.policy_pin = pin
            gspmd = True          # a pin is always a GSPMD assignment
            quant_grads = pin.quant
            self.mesh = pin.build_mesh()
        # quantized-collective knob: explicit arg > BuildStrategy attr >
        # FLAGS_quant_allreduce (each layer may leave it None = defer)
        if quant_grads is None:
            quant_grads = getattr(build_strategy, "quant_allreduce", None)
        if quant_grads is None:
            from paddle_tpu.fluid import flags as _flags

            quant_grads = _flags.flag("quant_allreduce")
        self.quant_grads = bool(quant_grads)
        # same layering for the algorithm choice; None defers all the way
        # to FLAGS_quant_allreduce_algo inside the transpile — ditto the
        # ready-order overlap, fused-update and gspmd knobs
        if quant_algo is None:
            quant_algo = getattr(build_strategy, "quant_allreduce_algo",
                                 None)
        self.quant_algo = quant_algo
        if overlap is None:
            overlap = getattr(build_strategy, "overlap_allreduce", None)
        if fused_update is None:
            fused_update = getattr(build_strategy, "fused_update", None)
        if gspmd is None:
            gspmd = getattr(build_strategy, "gspmd_executor", None)
        if gspmd is None:
            from paddle_tpu.fluid import flags as _flags

            gspmd = _flags.flag("gspmd_executor")
        self.gspmd = bool(gspmd)
        # graph-optimization passes (FLAGS_graph_passes) run BEFORE any
        # lane transpile — framework.PASS_ORDER's declared contract (the
        # fused-update/bucket scans must see the final forward graph).
        # The gspmd branch applies them inside GSPMDExecutor instead.
        if not self.gspmd:
            from paddle_tpu import passes as _graph_passes

            _graph_passes.apply_graph_passes(program, lane="dp",
                                             loss_name=loss_name)
        self._gspmd_exec = None
        if self.gspmd:
            # GSPMD lane: the program stays UNTOUCHED — the global-view
            # loss mean over the sharded batch already yields averaged
            # gradients, and XLA inserts the collectives.  policy_for is
            # the one selection rule shared with the hybrid runner.
            from .gspmd import GSPMDExecutor, policy_for

            self.program = program
            policy = (self.policy_pin.build_policy()
                      if self.policy_pin is not None
                      else policy_for(self.mesh))
            self._gspmd_exec = GSPMDExecutor(
                program, self.mesh, policy,
                quant_hook=self.quant_grads, quant_algo=quant_algo,
                loss_name=loss_name)
            self._sentinel = None  # the shared executor owns it there
            self._cache = {}
            return
        # rewrite in place, like the reference's multi-device pass
        self.program = transpile_data_parallel(
            program, loss_name, n,
            sync_batch_norm_stats=(build_strategy is None
                                   or getattr(build_strategy, "sync_batch_norm", True) is not False),
            quant_grads=self.quant_grads, quant_algo=quant_algo,
            overlap=overlap, fused_update=fused_update)
        # health sentinel (FLAGS_health_sentinel, docs/DISTRIBUTED.md §6):
        # inserted AFTER the bucket pass so detection rides the fused
        # buckets' wire format (QScale) where they exist
        from paddle_tpu import health

        self._sentinel = health.attach(self.program, loss_name=loss_name,
                                       lane="dp")
        self._cache = {}

    def _cache_key(self, feed, fetch_names):
        feed_sig = tuple(
            (k, tuple(np.shape(v)),
             str(v.dtype if hasattr(v, "dtype") else np.asarray(v).dtype))
            for k, v in sorted(feed.items()))
        return (id(self.program), self.program._version, feed_sig,
                tuple(fetch_names))

    def run(self, executor, feed, fetch_list, scope, return_numpy=True):
        import time as _time

        from paddle_tpu.fluid import executor as ex
        from paddle_tpu.fluid.executor import (_m_cache, _m_compile_seconds,
                                               _record_step)

        scope = scope or ex.global_scope()
        feed = executor._coerce_feed(self.program, feed or {})
        fetch_names = [f.name if not isinstance(f, str) else f for f in (fetch_list or [])]
        for k, v in feed.items():
            if np.shape(v) and np.shape(v)[0] % self.num_devices != 0:
                raise ValueError(
                    f"feed {k!r} batch {np.shape(v)[0]} not divisible by "
                    f"{self.num_devices} devices")
        if self._gspmd_exec is not None:
            out = self._gspmd_exec.run(scope=scope, feed=feed,
                                       fetch_list=fetch_names,
                                       return_numpy=return_numpy)
            executor._step += 1
            return out
        sent = self._sentinel
        key = self._cache_key(feed, fetch_names)
        cb = self._cache.get(key)
        if cb is None:
            _m_cache().labels(path="dp", result="miss").inc()
            if sent is not None:
                sent.ensure_state(scope)  # before BlockPlan scope checks
            t0 = _time.perf_counter()  # observability: allow
            cb = _ShardedBlock(self.program, feed.keys(), fetch_names, self.mesh, scope)
            self._cache[key] = cb
            _m_compile_seconds().labels(
                path="dp", phase="trace").inc(_time.perf_counter() - t0)  # observability: allow
        else:
            _m_cache().labels(path="dp", result="hit").inc()
        def attempt():
            first_run = not getattr(cb, "_obs_ran", False)
            t0 = _time.perf_counter()  # observability: allow
            fetches = cb.run(scope, feed, executor._step)
            step_s = _time.perf_counter() - t0  # observability: allow
            _record_step("dp", step_s, first_run)
            cb._obs_ran = True
            self._report_throughput(feed, step_s)
            executor._step += 1
            return fetches

        from paddle_tpu.health import run_guarded

        fetches = run_guarded(sent, scope, fetch_names, attempt)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches

    def _report_throughput(self, feed, step_s):
        """Per-step throughput + collective-payload telemetry
        (docs/OBSERVABILITY.md): global examples ingested, last-step
        examples/sec, and the transpiler's per-step ICI byte estimate."""
        from paddle_tpu.fluid.executor import _feed_batch, _report_examples

        _report_examples("dp", _feed_batch(feed), step_s)
        per_step = getattr(self.program, "_collective_bytes_per_step", None)
        if per_step:
            fam = collective_payload_counter()
            for coll, nbytes in per_step.items():
                if nbytes:
                    fam.labels(collective=coll).inc(nbytes)
        sched = getattr(self.program, "_overlap_schedule", None)
        if sched and sched["enabled"] and sched["buckets"]:
            overlap_buckets_counter().inc(len(sched["buckets"]))
        saved = getattr(self.program, "_fused_update_bytes_saved", 0)
        if saved:
            fused_update_bytes_counter().inc(saved)

    def cost_analysis(self, executor, feed, fetch_list=None, scope=None):
        """XLA cost/memory analysis of the sharded step executable (the
        single-device Executor.cost_analysis counterpart): flops and —
        the quantized-collective bench rung's metric — bytes accessed.
        The (feed, fetch) signature must have run once already."""
        from paddle_tpu.fluid import executor as ex

        scope = scope or ex.global_scope()
        feed = executor._coerce_feed(self.program, feed or {})
        fetch_names = [f.name if not isinstance(f, str) else f
                       for f in (fetch_list or [])]
        if self._gspmd_exec is not None:
            return self._gspmd_exec.cost_analysis(feed,
                                                  fetch_list=fetch_names,
                                                  scope=scope)
        cb = self._cache.get(self._cache_key(feed, fetch_names))
        if cb is None:
            raise ValueError(
                "no compiled data-parallel executable for this (feed, "
                "fetch_list) signature — run the step once first")
        return cb.cost_analysis(scope, feed)


class _ShardedBlock(_JitExecutable):
    """One (program-version, feed-signature) → sharded XLA executable.
    _JitExecutable supplies cost_analysis/_jit_args over the shared
    (donated, readonly, feeds, step) calling convention, so the sharded
    executable introspects exactly like the single-device one."""

    def __init__(self, program, feed_names, fetch_names, mesh, scope):
        import jax
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.fluid.executor import BlockPlan

        plan = BlockPlan(program, program.global_block(), feed_names,
                         fetch_names, scope)
        if plan.host_pre_ops:
            raise NotImplementedError(
                "pre-stage host ops (distributed lookup) are only "
                "supported by the single-device Executor")
        self.plan = plan
        self.feed_names = plan.feed_names
        self.fetch_names = plan.fetch_names
        self.ops = plan.ops
        self.donated_names = plan.donated_names
        self.readonly_names = plan.readonly_names
        self.write_names = plan.write_names
        axis = pmesh.DATA_AXIS
        from paddle_tpu.health import wrap_body as _health_gate

        # the health gate sits INSIDE the shard_map: found_inf is
        # computed from post-allreduce (replica-identical) gradients, so
        # the masking needs no extra collective
        inner = _health_gate(program, plan.make_body(mesh_axes=(axis,)))

        def body(donated, readonly, feeds, step):
            import jax.numpy as jnp

            raw_fetches, out_writes = inner(donated, readonly, feeds, step)
            # scalar fetches become per-device [1] vectors so the dp-axis
            # concat (FetchOpHandle semantics) has a dim to stack on
            fetches = [jnp.reshape(v, (1,) + tuple(jnp.shape(v)))
                       if jnp.ndim(v) == 0 else v for v in raw_fetches]
            return fetches, out_writes

        in_specs = (
            {n: P() for n in self.donated_names},
            {n: P() for n in self.readonly_names},
            {n: P(axis) for n in self.feed_names},
            P(),
        )
        out_specs = ([P(axis) for _ in plan.jit_fetch_names],
                     {n: P() for n in self.write_names})
        sharded = jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_vma=False)
        self._jitted = jax.jit(sharded, donate_argnums=(0,))
        self.mesh = mesh
        self.label = f"dp_block@{id(self):x}"

    def run(self, scope, feeds, step):
        import warnings

        from paddle_tpu.fluid import profiler as _prof
        from paddle_tpu.observability import profiling as _profiling

        if not hasattr(self, "_prof_state"):
            self._prof_state = {"ran": False}
        # step_phases outermost; timed_run keeps its historic region
        # (staging..scope-writes) so the "run" span never absorbs the
        # host RPC tail — fetch_sync brackets accumulate across both
        with _profiling.step_phases("dp", self.label) as ph:
            with _prof.timed_run(f"dp_block@{id(self):x}",
                                 self._prof_state) as timer:
                with ph.phase("feed_prep"):
                    donated = {n: scope.get(n)
                               for n in self.donated_names}
                    readonly = {n: scope.get(n)
                                for n in self.readonly_names}
                with ph.phase("dispatch"):
                    with warnings.catch_warnings():
                        warnings.simplefilter("ignore")
                        fetches, out_writes = self._jitted(
                            donated, readonly, dict(feeds),
                            np.uint32(step))
                with ph.phase("device_wait"):
                    ph.wait((fetches, out_writes))
                with ph.phase("fetch_sync"):
                    for n, v in out_writes.items():
                        scope.set(n, v)
                    timer.done(fetches, out_writes)
            with ph.phase("fetch_sync"):
                # PS-mode programs carry host RPC ops — run them, don't
                # drop them
                self.plan.run_host_ops(scope)
                out = self.plan.assemble_fetches(fetches, scope)
        return out
