"""Device-mesh management: the TPU-native replacement for NCCL communicators.

Reference analogs: platform/nccl_helper.h:90 (NCCLContextMap),
collective_helper.h:63 (NCCLCommContext registry keyed by ring_id),
c_comm_init / c_gen_nccl_id bootstrap ops.  On TPU there is no uniqueId
handshake: a jax.sharding.Mesh over the slice IS the communicator, and a
``ring_id`` maps to a mesh axis name.  Intra-slice traffic rides ICI; a
multi-dimensional mesh (('dcn', 'dp', ...)) puts the leading axis over DCN for
multi-slice/multi-host — matching the reference's hierarchical allreduce
(nccl_helper.h:179 NCCLCommunicator) without any of its machinery.
"""

from __future__ import annotations

import contextlib

import numpy as np

# Standard axis names, in the order strategies usually nest them.
DATA_AXIS = "dp"
MODEL_AXIS = "mp"
PIPE_AXIS = "pp"
SEQ_AXIS = "sp"
EXPERT_AXIS = "ep"

# Paper-idiom spellings (the named 2-D ("batch", "model") mesh of the
# GSPMD literature) map onto the canonical short axis names above, so a
# PartitionSpec written either way addresses the same mesh axis.  The
# gspmd policy layer resolves through canonical_axis(); raw Mesh axis
# names stay the short forms everywhere (ring registry, ShardingRule).
AXIS_ALIASES = {
    "batch": DATA_AXIS,
    "data": DATA_AXIS,
    "model": MODEL_AXIS,
    "pipe": PIPE_AXIS,
    "seq": SEQ_AXIS,
    "expert": EXPERT_AXIS,
}


def canonical_axis(name):
    """Resolve an axis spelling ("batch"/"model"/...) to the canonical
    mesh axis name ("dp"/"mp"/...); canonical names pass through."""
    if name is None:
        return None
    return AXIS_ALIASES.get(str(name), str(name))

# ring_id → mesh axis name.  Ring 0 is the global/world ring in the reference
# (c_allreduce_op.h:73); by default it is the data-parallel axis.
_ring_axes: dict[int, str] = {0: DATA_AXIS}

_current_mesh = None


def set_ring_axis(ring_id: int, axis_name: str):
    _ring_axes[int(ring_id)] = axis_name


def axis_name_for_ring(ring_id: int):
    return _ring_axes.get(int(ring_id))


def current_mesh():
    return _current_mesh


@contextlib.contextmanager
def mesh_guard(mesh):
    global _current_mesh
    old = _current_mesh
    _current_mesh = mesh
    try:
        yield mesh
    finally:
        _current_mesh = old


def build_mesh(shape: dict[str, int] | None = None, devices=None):
    """Create a Mesh.  shape maps axis name → size, e.g. {'dp': 4, 'mp': 2}.
    Defaults to all local devices on a single data-parallel axis."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if not shape:
        shape = {DATA_AXIS: len(devices)}
    names = tuple(shape.keys())
    sizes = tuple(shape.values())
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, only {len(devices)} available")
    arr = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(arr, names)


def build_2d_mesh(batch=None, model=1, devices=None):
    """The named 2-D (batch, model) mesh of the GSPMD idiom: data
    parallelism on the leading axis, tensor parallelism innermost (the
    latency-sensitive collectives ride the nearest ICI links).  Axis
    names are the canonical short forms (``dp``, ``mp``); ``batch`` None
    uses every device not consumed by ``model``."""
    import jax

    if devices is None:
        devices = jax.devices()
    model = int(model)
    if batch is None:
        if len(devices) % model != 0:
            from paddle_tpu.analysis.findings import format_mesh_error

            raise ValueError(format_mesh_error(
                len(devices),
                {DATA_AXIS: None, MODEL_AXIS: model},
                leftover_axis=DATA_AXIS))
        batch = len(devices) // model
    shape = {DATA_AXIS: int(batch)}
    if model > 1:
        shape[MODEL_AXIS] = model
    return build_mesh(shape, devices=devices)


def build_3d_mesh(pp=1, batch=None, model=1, devices=None):
    """The named 3-D (pp, batch, model) mesh of the pipeline-as-policy
    layer: pipeline stages on the LEADING axis (the coarsest-grained,
    least latency-sensitive traffic — one activation transfer per stage
    boundary per microbatch, the natural DCN/far-ICI axis), data
    parallelism in the middle, tensor parallelism innermost (its
    collectives ride the nearest ICI links).  Axis names are the
    canonical short forms (``pp``, ``dp``, ``mp``); the paper spellings
    (``pipe``/``batch``/``model``) resolve through AXIS_ALIASES exactly
    like the 2-D mesh.  ``batch`` None uses every device not consumed by
    ``pp`` × ``model``; axes of size 1 are elided so a degenerate call
    (``pp=1``) reproduces :func:`build_2d_mesh`'s shape."""
    import jax

    if devices is None:
        devices = jax.devices()
    pp, model = int(pp), int(model)
    if batch is None:
        denom = pp * model
        if len(devices) % denom != 0:
            from paddle_tpu.analysis.findings import format_mesh_error

            raise ValueError(format_mesh_error(
                len(devices),
                {PIPE_AXIS: pp, DATA_AXIS: None, MODEL_AXIS: model},
                leftover_axis=DATA_AXIS))
        batch = len(devices) // denom
    shape = {}
    if pp > 1:
        shape[PIPE_AXIS] = pp
    shape[DATA_AXIS] = int(batch)
    if model > 1:
        shape[MODEL_AXIS] = model
    return build_mesh(shape, devices=devices)


def device_count():
    import jax

    return jax.device_count()
