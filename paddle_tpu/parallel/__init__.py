"""Parallelism subsystem: meshes, collectives, distributed strategies."""

from . import mesh  # noqa: F401
from .mesh import build_mesh, mesh_guard, current_mesh  # noqa: F401
