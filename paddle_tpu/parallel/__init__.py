"""Parallelism subsystem: meshes, collectives, distributed strategies."""

from . import mesh  # noqa: F401
from .mesh import build_mesh, mesh_guard, current_mesh  # noqa: F401
from . import hybrid  # noqa: F401
from .hybrid import (  # noqa: F401
    HybridParallelRunner, ShardingRule, megatron_rules, build_hybrid_mesh,
)
