"""Parallelism subsystem: meshes, collectives, distributed strategies."""

from . import mesh  # noqa: F401
from .mesh import build_mesh, mesh_guard, current_mesh  # noqa: F401
from . import hybrid  # noqa: F401
from .hybrid import (  # noqa: F401
    HybridParallelRunner, ShardingRule, megatron_rules, build_hybrid_mesh,
)
from . import data_parallel  # noqa: F401
from .data_parallel import DataParallelRunner, transpile_data_parallel  # noqa: F401
from . import gspmd  # noqa: F401
from .gspmd import (  # noqa: F401
    DataParallelPolicy, GSPMDExecutor, PipelinePolicy, ShardingPolicy,
    TensorParallelPolicy, Zero1Policy, policy_for,
)
from .mesh import build_3d_mesh  # noqa: F401
from . import local_sgd  # noqa: F401
from .local_sgd import LocalSGDRunner  # noqa: F401
from . import pipeline  # noqa: F401
from .pipeline import PipelineRunner  # noqa: F401
from . import autotune  # noqa: F401
from .autotune import (  # noqa: F401
    Candidate, autotune as autotune_mesh, enumerate_candidates,
    load_report, policy_summary, resolve_pin, save_report,
)
