"""Mesh autotuner: derive the (pp, batch, model) policy from measurement.

Every parallelism the system grew — DP, ZeRO-1, TP, pipeline — composes
over one 3-D mesh (`mesh.build_3d_mesh`), and the substrate already
measures everything a search needs: per-signature XLA flops/bytes
(`observability.profiling`), the exact-to-HLO `wire_bytes` collective
models (`kernels.quantized_collectives`, PR-8 precedent), and the
modeled pipeline bubble (`gspmd.pipeline_policy`).  This module closes
the loop (ROADMAP "Mesh autotuning"; arXiv:2004.13336 is the precedent
that sharding choice is derivable rather than hand-specified,
arXiv:2301.13062 the precedent for validating an analytic cost model
against what the compiler actually emits):

  1. **enumerate** every legal mesh factorization ``(pp, dp, mp)`` of N
     devices crossed with policy assignments (pure DP, `Zero1Policy`,
     `TensorParallelPolicy`, `PipelinePolicy` × schedule × microbatch
     count), rejecting illegal combos through the PR-16 verifier's
     sharding family (`analysis.verify`, device-free `AbstractMesh`) —
     NOT ad-hoc checks;
  2. **prune** with an analytic cost model — compute/memory roofline
     (`profiling.roofline` over XLA cost-analysis numbers), collective
     cost from the existing `wire_bytes`/`gather_wire_bytes`/ring-algo
     models (256 KB oneshot→ring crossover included), pipeline bubble
     from `modeled_bubble_fraction` — yielding a ranked candidate list
     with per-term attribution;
  3. **measure** the top-K shortlist with real compiles through
     `GSPMDExecutor` (AOT-/compile-cache-aware: re-tuning a seen shape
     is zero-compile), reading `hlo_collective_bytes` and step
     quantiles per candidate;
  4. **emit** a versioned JSON report (`autotune_report.json`) the
     runners accept as a pin (``DataParallelRunner(policy_pin=...)`` /
     ``HybridParallelRunner(policy_pin=...)`` / `FLAGS_autotune_report`).

Collective-bytes prediction is term-wise honest about its confidence
(validated against compiled HLO on the 8-device CPU mesh,
tests/test_autotune.py):

  dp grad all-reduce (fp32)   4 bytes × Σ grad elements — the SPMD
                              all-reduce's per-device image IS the full
                              tensor (measured exact + one 4-byte loss
                              scalar).
  dp grad reduce (quant)      the gspmd quant hook's own bucket model
                              replicated statically (plain bucket raw
                              elems + fused bucket block-padded elems,
                              `wire_bytes` each with the ring crossover)
                              — measured EXACT (ratio 1.0, PR-8 gate).
  zero1 param re-gather       4 bytes × Σ full param image over params
                              whose optimizer state shards (dim0
                              divisible by dp) — measured exact.
  tp activations              modeled (row-parallel psum images); the
                              partitioner's actual gather/reshard
                              choices vary — confidence "modeled", kept
                              out of the exactness gate.
  pipeline boundaries         `boundary_wire_bytes` per stage link —
                              confidence "modeled".

See docs/AUTOTUNE.md for the search space, report schema and pinning
workflow.
"""

from __future__ import annotations

import dataclasses
import json
import time

import numpy as np

from paddle_tpu import observability as obs

from . import mesh as pmesh
from .gspmd import specs as gspecs

__all__ = [
    "Candidate",
    "CostInputs",
    "autotune",
    "enumerate_candidates",
    "load_report",
    "measure_candidates",
    "policy_summary",
    "predict",
    "predict_collective_bytes",
    "resolve_pin",
    "save_report",
]

REPORT_SCHEMA = "paddle_tpu.autotune/v1"
REPORT_VERSION = 1

_FUSED_OPT_TYPES = ("sgd", "adam", "adamw", "lamb", "momentum")
_QUANT_DTYPES = ("float32", "float16", "bfloat16")
DEFAULT_MICROBATCHES = (2, 4, 8)


def _m_candidates():
    return obs.counter(
        "pt_autotune_candidates_total",
        "mesh-autotuner candidates by stage (enumerated / legal / "
        "rejected / measured)", labels=("stage",))


def _m_pred_err():
    return obs.gauge(
        "pt_autotune_prediction_error",
        "relative error |predicted - measured| / measured of the "
        "analytic collective-bytes model per measured candidate",
        labels=("candidate",))


def _m_winner_rank():
    return obs.gauge(
        "pt_autotune_winner_rank",
        "analytic rank (0 = predicted fastest) of the measured-fastest "
        "candidate — the cost model's headline accuracy")


# ---------------------------------------------------------------------------
# candidates
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the search space: a mesh factorization
    ``(pp, dp, mp)`` of N devices plus the policy assignment riding it.
    Frozen/hashable so symmetric duplicates dedup through a set."""

    pp: int = 1
    dp: int = 1
    mp: int = 1
    policy: str = "dp"  # "dp" | "zero1" | "tp" | "pipeline"
    zero_stage: int = 0
    schedule: str = None  # pipeline only: "gpipe" | "1f1b"
    microbatches: int = None  # pipeline only
    quant: bool = False

    @property
    def n_devices(self):
        return self.pp * self.dp * self.mp

    @property
    def mesh_dims(self):
        return {pmesh.PIPE_AXIS: self.pp, pmesh.DATA_AXIS: self.dp,
                pmesh.MODEL_AXIS: self.mp}

    def label(self):
        s = f"pp{self.pp}.dp{self.dp}.mp{self.mp}/{self.policy}"
        if self.policy == "tp" and self.zero_stage:
            s += f"+zero{self.zero_stage}"
        if self.policy == "pipeline":
            s += f"[{self.schedule},m{self.microbatches}"
            s += f",zero{self.zero_stage}]" if self.zero_stage else "]"
        if self.quant:
            s += "+quant"
        return s

    def abstract_mesh(self):
        """Device-free mesh stand-in for the verifier preflight —
        mirrors `build_3d_mesh`'s axis elision (size-1 pp/mp dropped,
        dp always present)."""
        from paddle_tpu.analysis import AbstractMesh

        axes = {}
        if self.pp > 1:
            axes[pmesh.PIPE_AXIS] = self.pp
        axes[pmesh.DATA_AXIS] = self.dp
        if self.mp > 1:
            axes[pmesh.MODEL_AXIS] = self.mp
        return AbstractMesh(axes)

    def build_mesh(self, devices=None):
        return pmesh.build_3d_mesh(pp=self.pp, batch=self.dp,
                                   model=self.mp, devices=devices)

    def build_policy(self, rules=None):
        """Instantiate the ShardingPolicy this candidate names — the
        same classes `policy_for` selects, made explicit so a pinned
        report reconstructs the exact assignment."""
        if self.policy == "dp":
            return gspecs.DataParallelPolicy()
        if self.policy == "zero1":
            return gspecs.Zero1Policy()
        if self.policy == "tp":
            return gspecs.TensorParallelPolicy(rules=rules,
                                               zero_stage=self.zero_stage)
        if self.policy == "pipeline":
            from .gspmd.pipeline_policy import PipelinePolicy

            return PipelinePolicy(schedule=self.schedule,
                                  num_microbatches=self.microbatches,
                                  zero_stage=self.zero_stage)
        raise ValueError(f"unknown candidate policy {self.policy!r}")

    def to_json(self):
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}

    @classmethod
    def from_json(cls, d):
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(
                f"autotune candidate has unknown fields {sorted(unknown)}"
                f" — report from a newer schema? ({REPORT_SCHEMA})")
        return cls(**d)


def _factorizations(n):
    """All ordered triples (pp, dp, mp) with pp*dp*mp == n."""
    out = []
    for pp in range(1, n + 1):
        if n % pp:
            continue
        rest = n // pp
        for dp in range(1, rest + 1):
            if rest % dp:
                continue
            out.append((pp, dp, rest // dp))
    return out


def _pipeline_stages(program):
    """Stage count the program's PipelineOptimizer metadata pins, or 0
    when the program carries no cut — pipeline candidates only exist
    where a cut does (resolve_cut_vars would raise otherwise)."""
    meta = getattr(program, "_pipeline", None)
    if not meta or not meta.get("cut_vars"):
        return 0
    return len(meta["cut_vars"]) + 1


def enumerate_candidates(program, n_devices, rules=None, quant=None,
                         microbatch_counts=DEFAULT_MICROBATCHES,
                         feed_shapes=None, verify=True):
    """Phase 1: every legal (mesh factorization × policy assignment)
    for ``program`` on ``n_devices``.

    The policy crossing only emits combos where each >1 mesh axis is
    actually consumed (mp>1 ⇒ TP, pp>1 ⇒ pipeline, ZeRO-1 ⇒ dp>1) —
    that IS the symmetric dedup: a pure-DP assignment on an (1, 1, 8)
    mesh is the replicated single-device program wearing a costume.
    pp>1 × mp>1 combos are excluded — PipelinePolicy's island maps
    (pp, batch) only and demotes model-axis params (its documented
    limit), so such a candidate would silently measure as pipeline-only.

    Legality is the PR-16 verifier's sharding family over a device-free
    `AbstractMesh` — one error-severity finding rejects the candidate.
    """
    if quant is None:
        from paddle_tpu.fluid import flags as _flags

        quant = bool(_flags.flag("quant_allreduce"))
    n = int(n_devices)
    if n < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices!r}")
    stages = _pipeline_stages(program)
    raw = set()
    for pp, dp, mp in _factorizations(n):
        if pp > 1 and (mp > 1 or pp != stages):
            continue
        if pp == 1 and mp == 1:
            raw.add(Candidate(pp=pp, dp=dp, mp=mp, policy="dp",
                              quant=quant and dp > 1))
            if dp > 1:
                raw.add(Candidate(pp=pp, dp=dp, mp=mp, policy="zero1",
                                  zero_stage=1, quant=quant))
        elif pp == 1:
            raw.add(Candidate(pp=pp, dp=dp, mp=mp, policy="tp",
                              quant=quant and dp > 1))
            if dp > 1:
                raw.add(Candidate(pp=pp, dp=dp, mp=mp, policy="tp",
                                  zero_stage=1, quant=quant))
        else:
            for sched in ("gpipe", "1f1b"):
                for m in microbatch_counts:
                    raw.add(Candidate(pp=pp, dp=dp, mp=mp,
                                      policy="pipeline", schedule=sched,
                                      microbatches=int(m),
                                      quant=quant and dp > 1))
                    if dp > 1:
                        raw.add(Candidate(
                            pp=pp, dp=dp, mp=mp, policy="pipeline",
                            schedule=sched, microbatches=int(m),
                            zero_stage=1, quant=quant))
    ordered = sorted(raw, key=lambda c: (c.pp, c.mp, c.dp, c.policy,
                                         c.zero_stage,
                                         c.schedule or "",
                                         c.microbatches or 0))
    _m_candidates().labels(stage="enumerated").inc(len(ordered))
    if not verify:
        return ordered
    from paddle_tpu import analysis

    legal = []
    for cand in ordered:
        report = analysis.verify(
            program, mesh=cand.abstract_mesh(),
            policy=cand.build_policy(rules=rules),
            feed_shapes=feed_shapes, quant_hook=cand.quant,
            families={"sharding"})
        if report.errors:
            _m_candidates().labels(stage="rejected").inc()
            continue
        legal.append(cand)
    _m_candidates().labels(stage="legal").inc(len(legal))
    return legal


# ---------------------------------------------------------------------------
# analytic cost model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CostInputs:
    """The per-step workload gauges the cost model consumes — XLA
    cost-analysis numbers of the UNPARTITIONED step (the
    `pt_xla_flops` / `pt_xla_bytes_accessed` surface) plus the feed's
    batch rows."""

    flops: float
    bytes_accessed: float
    batch_rows: int = 1


def _params_grads(program):
    pg = getattr(program, "_params_grads", None)
    if not pg:
        raise ValueError(
            "autotune needs an optimized program (minimize() stamps "
            "_params_grads) — got a forward-only program")
    block = program.global_block()
    out = []
    for p, g in pg:
        v = block._find_var_recursive(p)
        if v is None or not v.shape or any(d is None or d < 0
                                           for d in v.shape):
            continue
        gv = block._find_var_recursive(g)
        out.append((p, g, tuple(v.shape),
                    gv.dtype if gv is not None else "float32"))
    return out


def _quant_bucket_split(program, block_size=None):
    """Static replica of the gspmd quant hook's bucket planning
    (`quant_hook._plan_fused_updates` / `_model_wire_bytes`): grads
    whose ONLY consumer is their one fused-eligible optimizer op ride
    the block-padded fused bucket; everything else quantizable rides
    the plain bucket at raw element count.  Keeping this arithmetic
    identical is what makes the quant term measured-exact (ratio 1.0)
    against the compiled HLO."""
    from paddle_tpu.fluid import flags as _flags
    from paddle_tpu.kernels.quantized_collectives import DEFAULT_BLOCK_SIZE

    bs = int(block_size or _flags.flag("quant_allreduce_block_size")
             or DEFAULT_BLOCK_SIZE)
    fused_on = bool(_flags.flag("fused_update"))
    dgc = getattr(program, "_dgc_encoded", {})
    exempt = set(dgc.keys()) | set(dgc.values())
    quant = [(g, shape) for _p, g, shape, dt in _params_grads(program)
             if dt in _QUANT_DTYPES and g not in exempt]
    ops = program.global_block().ops
    consumers = {}
    for op in ops:
        for g in set(op.input_arg_names):
            consumers.setdefault(g, []).append(op)
    fused_padded, plain = 0, 0
    fused_raw = 0
    for g, shape in quant:
        elems = int(np.prod(shape))
        cons = consumers.get(g, [])
        if (fused_on and len(cons) == 1
                and cons[0].type in _FUSED_OPT_TYPES
                and cons[0].inputs.get("Grad") == [g]):
            fused_raw += elems
            fused_padded += elems + (-elems) % bs
        else:
            plain += elems
    if fused_padded > 2 * fused_raw:  # the hook's alignment-bloat guard
        plain += fused_raw
        fused_padded = 0
    return plain, fused_padded, bs


def predict_collective_bytes(program, candidate, rules=None,
                             batch_rows=1):
    """Per-step collective bytes the compiled executable will move
    (the `hlo_collective_bytes` surface), term-attributed.  Returns
    ``(total, terms, confidence)`` where confidence is "exact" when
    every non-zero term is HLO-validated (dp fp32/quant, zero1 gather)
    and "modeled" when a tp/pipeline estimate contributes."""
    from paddle_tpu.kernels import quantized_collectives as qc
    from paddle_tpu.kernels.ring_collectives import select_allreduce_algo

    dp, mp, pp = candidate.dp, candidate.mp, candidate.pp
    pg = _params_grads(program)
    terms = {}
    confidence = "exact"
    policy = candidate.build_policy(rules=rules)
    uses_model = mp > 1 and policy.uses_model_axis(
        program, candidate.abstract_mesh())
    if dp > 1:
        quant_active = candidate.quant and not uses_model
        if quant_active:
            plain, fused, bs = _quant_bucket_split(program)
            total_q = 0
            for elems in (plain, fused):
                if elems:
                    algo = select_allreduce_algo(elems, dp, block_size=bs)
                    total_q += qc.wire_bytes(elems, block_size=bs,
                                             n_devices=dp, algo=algo)
            terms["quant_allreduce"] = total_q
        else:
            grad_elems = sum(int(np.prod(shape)) for _p, _g, shape, dt
                             in pg)
            # + one 4-byte scalar: the global loss-mean all-reduce
            terms["grad_allreduce"] = 4 * grad_elems + 4
        if candidate.zero_stage >= 1 or candidate.policy == "zero1":
            gather = sum(4 * int(np.prod(shape))
                         for _p, _g, shape, _dt in pg
                         if shape and shape[0] % dp == 0)
            terms["zero1_gather"] = gather
    if uses_model:
        # modeled: row-parallel contractions psum a full activation
        # image forward and backward; the partitioner's own
        # gather/reshard choices on top are NOT predicted
        mesh = candidate.abstract_mesh()
        act = policy.activation_constraints(program, mesh)
        block = program.global_block()
        rows = max(int(batch_rows), 1) // max(dp, 1) or 1
        psum = 0
        for name, spec in act.items():
            if any(a for a in spec):
                continue  # column-parallel stays sharded — no psum
            v = block._find_var_recursive(name)
            if v is None or not v.shape:
                continue
            elems = int(np.prod([rows if d is None or d < 0 else d
                                 for d in v.shape]))
            psum += 2 * 4 * elems  # fwd psum + bwd input-grad psum
        terms["tp_activations"] = psum
        confidence = "modeled"
    if pp > 1:
        from paddle_tpu.kernels.pipeline_collectives import (
            boundary_wire_bytes)
        from .pipeline import boundary_sets, stage_partition

        # one microbatch's slice of the per-device batch crosses each
        # link per tick
        micro_rows = (max(int(batch_rows), 1)
                      // max(dp * (candidate.microbatches or 1), 1)) or 1
        try:
            cut_vars = policy.resolve_cut_vars(program)
            block = program.global_block()
            stages, _stage_of = stage_partition(program, list(block.ops),
                                                cut_vars)
            elems = 0
            for bset in boundary_sets(stages):
                for nm in bset:
                    v = block._find_var_recursive(nm)
                    if v is not None and v.shape:
                        elems += int(np.prod(
                            [micro_rows if d is None or d < 0 else d
                             for d in v.shape]))
            terms["pipeline_boundary"] = boundary_wire_bytes(
                elems, candidate.microbatches or 1)
        except Exception:
            terms["pipeline_boundary"] = 0
        confidence = "modeled"
    return sum(terms.values()), terms, confidence


def predict(program, candidate, cost_inputs, rules=None, peaks=None):
    """Phase 2 scoring: modeled step seconds with per-term attribution.

    compute/memory divide by the devices the policy actually uses
    (an unconsumed mesh axis buys nothing); collectives ride the ICI
    peak; the pipeline bubble inflates the compute leg by
    bubble/(1-bubble) per `modeled_bubble_fraction`."""
    from paddle_tpu.observability import profiling

    if peaks is None:
        _plat, pf, pbw, pici = profiling.device_peaks()
    else:
        pf, pbw, pici = peaks
    policy = candidate.build_policy(rules=rules)
    n_eff = candidate.dp * candidate.pp
    if candidate.mp > 1 and policy.uses_model_axis(
            program, candidate.abstract_mesh()):
        n_eff *= candidate.mp
    compute_s = float(cost_inputs.flops or 0) / n_eff / pf
    memory_s = float(cost_inputs.bytes_accessed or 0) / n_eff / pbw
    roofline_s = max(compute_s, memory_s)
    coll_bytes, coll_terms, confidence = predict_collective_bytes(
        program, candidate, rules=rules,
        batch_rows=cost_inputs.batch_rows)
    collective_s = coll_bytes / pici
    bubble_s = 0.0
    bubble_frac = 0.0
    if candidate.policy == "pipeline":
        from .gspmd.pipeline_policy import modeled_bubble_fraction

        bubble_frac = modeled_bubble_fraction(candidate.pp,
                                              candidate.microbatches or 1)
        bubble_s = roofline_s * bubble_frac / max(1.0 - bubble_frac, 1e-9)
    total_s = roofline_s + collective_s + bubble_s
    return {
        "total_s": total_s,
        "terms": {"compute_s": compute_s, "memory_s": memory_s,
                  "collective_s": collective_s, "bubble_s": bubble_s},
        "collective_bytes": int(coll_bytes),
        "collective_terms": {k: int(v) for k, v in coll_terms.items()},
        "bubble_fraction": bubble_frac,
        "effective_devices": n_eff,
        "confidence": confidence,
    }


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------


def _gspmd_cache_counts():
    """``pt_compile_cache_total{path="gspmd"}`` by result — the sample
    keys are (path, result) label tuples (metrics.snapshot contract)."""
    snap = obs.snapshot().get("pt_compile_cache_total") or {}
    out = {"hit": 0, "miss": 0, "aot_hit": 0, "aot_saved": 0}
    for key, v in (snap.get("samples") or {}).items():
        parts = tuple(key) if isinstance(key, (tuple, list)) else (key,)
        if "gspmd" not in parts:
            continue
        for res in out:
            if res in parts:
                out[res] += int(v)
    return out


def measure_candidates(build, candidates, feed, loss_name=None,
                       steps=None, rules=None, devices=None,
                       predictions=None):
    """Phase 3: real compiles for the shortlist through the one
    jit-partitioned executor.  ``build()`` must return a fresh
    ``(program, startup_program)`` pair per call (GSPMDExecutor attaches
    passes/sentinel in place, so candidates never share a program).

    The compile/AOT caches stay on: a re-tune of a seen (program, mesh,
    policy) shape books `pt_compile_cache_total{path="gspmd"}` hits and
    zero fresh compiles — the report records the per-candidate delta.
    Returns one record per candidate (None-measured entries mean the
    candidate failed to compile; the failure is recorded, not raised)."""
    import jax

    from paddle_tpu import fluid
    from .gspmd import GSPMDExecutor, hlo_collective_bytes

    if steps is None:
        from paddle_tpu.fluid import flags as _flags

        steps = int(_flags.flag("autotune_steps"))
    devices = devices or jax.devices()
    records = []
    for cand in candidates:
        rec = {"candidate": cand.to_json(), "label": cand.label()}
        pred = (predictions or {}).get(cand)
        before = _gspmd_cache_counts()
        try:
            program, startup = build()
            mesh = cand.build_mesh(devices=devices)
            policy = cand.build_policy(rules=rules)
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor()
                exe.run(startup)
                g = GSPMDExecutor(program, mesh, policy, scope=scope,
                                  quant_hook=cand.quant,
                                  loss_name=loss_name)
                fetch = [loss_name] if loss_name else None
                g.run(scope=scope, feed=feed, fetch_list=fetch)  # warm
                times = []
                for _ in range(int(steps)):
                    # candidate A/B quantiles, not a training step —
                    # deliberately outside the step_phases timer
                    t0 = time.perf_counter()  # observability: allow
                    g.run(scope=scope, feed=feed, fetch_list=fetch)
                    times.append(
                        time.perf_counter() - t0)  # observability: allow
                after = _gspmd_cache_counts()
                measured = {
                    "p50_s": round(float(np.percentile(times, 50)), 6),
                    "p95_s": round(float(np.percentile(times, 95)), 6),
                    "steps": int(steps),
                    "compile_cache": {k: after[k] - before[k]
                                      for k in after},
                }
                hlo = g.last_hlo
                if hlo:
                    measured["hlo_collective_bytes"] = \
                        hlo_collective_bytes(hlo)
                rec["measured"] = measured
        except Exception as e:  # candidate dies, sweep survives
            rec["measured"] = None
            rec["error"] = f"{type(e).__name__}: {e}"
            records.append(rec)
            continue
        _m_candidates().labels(stage="measured").inc()
        if pred is not None:
            rec["predicted"] = pred
            mb = rec["measured"].get("hlo_collective_bytes")
            pb = pred.get("collective_bytes")
            if mb and pb is not None:
                err = abs(pb - mb) / mb
                rec["measured"]["prediction_error"] = round(err, 4)
                _m_pred_err().labels(candidate=cand.label()).set(err)
        records.append(rec)
    return records


# ---------------------------------------------------------------------------
# the full loop + report
# ---------------------------------------------------------------------------


def autotune(build, feed, loss_name=None, n_devices=None, rules=None,
             cost_inputs=None, quant=None, top_k=None, steps=None,
             microbatch_counts=DEFAULT_MICROBATCHES, workload=None,
             report_path=None, devices=None):
    """Enumerate → prune → measure → report, end to end.

    ``build()`` returns a fresh ``(main_program, startup_program)``;
    ``cost_inputs`` (a `CostInputs`) defaults to a 1-device
    `GSPMDExecutor.cost_analysis` probe of the same program.  Returns
    the report dict (written to ``report_path`` when given)."""
    import jax

    from paddle_tpu.fluid import flags as _flags

    devices = devices or jax.devices()
    n = int(n_devices or len(devices))
    top_k = int(top_k or _flags.flag("autotune_topk"))
    program, _startup = build()
    feed_shapes = {k: tuple(np.shape(v)) for k, v in (feed or {}).items()}
    candidates = enumerate_candidates(
        program, n, rules=rules, quant=quant,
        microbatch_counts=microbatch_counts, feed_shapes=feed_shapes)
    if not candidates:
        raise ValueError(f"no legal candidates for {n} devices")
    if cost_inputs is None:
        cost_inputs = probe_cost_inputs(build, feed, loss_name=loss_name,
                                        devices=devices)
    predictions = {c: predict(program, c, cost_inputs, rules=rules)
                   for c in candidates}
    ranked = sorted(candidates,
                    key=lambda c: predictions[c]["total_s"])
    for i, c in enumerate(ranked):
        predictions[c]["rank"] = i
    shortlist = ranked[:top_k]
    measured = measure_candidates(
        build, shortlist, feed, loss_name=loss_name, steps=steps,
        rules=rules, devices=devices, predictions=predictions)
    ok = [r for r in measured if r.get("measured")]
    winner = (min(ok, key=lambda r: r["measured"]["p50_s"])
              if ok else None)
    report = {
        "schema": REPORT_SCHEMA,
        "version": REPORT_VERSION,
        "generated_unix": int(time.time()),  # observability: allow
        "n_devices": n,
        "workload": dict(workload or {}, feed_shapes={
            k: list(v) for k, v in feed_shapes.items()}),
        "cost_inputs": dataclasses.asdict(cost_inputs),
        "candidates": [
            dict(predicted=predictions[c], label=c.label(),
                 candidate=c.to_json())
            for c in ranked],
        "measured": measured,
        "winner": winner,
    }
    if winner is not None:
        winner_rank = predictions[
            Candidate.from_json(winner["candidate"])]["rank"]
        report["winner_rank"] = winner_rank
        report["analytic_top3_contains_winner"] = winner_rank < 3
        _m_winner_rank().set(winner_rank)
    if report_path:
        save_report(report, report_path)
    return report


def probe_cost_inputs(build, feed, loss_name=None, devices=None):
    """XLA cost-analysis numbers of the unpartitioned step (1-device
    mesh) — the same `pt_xla_flops`/`pt_xla_bytes_accessed` figures the
    roofline gauges publish, read straight from the probe compile."""
    from paddle_tpu import fluid
    from .gspmd import GSPMDExecutor

    import jax

    program, startup = build()
    devices = list(devices or jax.devices())
    mesh = pmesh.build_mesh({pmesh.DATA_AXIS: 1}, devices=devices[:1])
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        g = GSPMDExecutor(program, mesh, gspecs.DataParallelPolicy(),
                          scope=scope, quant_hook=False,
                          loss_name=loss_name)
        fetch = [loss_name] if loss_name else None
        g.run(scope=scope, feed=feed, fetch_list=fetch)
        cost = g.cost_analysis(feed, fetch_list=fetch, scope=scope) or {}
    # cost_analysis nests: {"cost": {...xla keys...}, "memory": {...}}.
    inner = cost.get("cost", cost) or {}
    rows = 0
    for v in (feed or {}).values():
        shape = np.shape(v)
        if shape:
            rows = max(rows, int(shape[0]))
    return CostInputs(flops=float(inner.get("flops") or 0.0),
                      bytes_accessed=float(inner.get("bytes accessed")
                                           or 0.0),
                      batch_rows=rows)


def save_report(report, path):
    with open(path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")


def load_report(path):
    with open(path) as f:
        report = json.load(f)
    schema = report.get("schema")
    if schema != REPORT_SCHEMA:
        raise ValueError(
            f"{path}: not an autotune report (schema {schema!r}, "
            f"expected {REPORT_SCHEMA!r})")
    return report


def resolve_pin(pin):
    """Runner pin plumbing: accept a `Candidate`, a report dict, a
    candidate-json dict, or a path to a saved report — return the
    `Candidate` to pin.  The ONE deserialization point both runners and
    `FLAGS_autotune_report` share."""
    if isinstance(pin, Candidate):
        return pin
    if isinstance(pin, str):
        pin = load_report(pin)
    if not isinstance(pin, dict):
        raise TypeError(
            f"policy_pin must be a Candidate, report dict or report "
            f"path, got {type(pin).__name__}")
    if pin.get("schema") == REPORT_SCHEMA:
        winner = pin.get("winner")
        if not winner:
            raise ValueError(
                "autotune report has no measured winner to pin")
        return Candidate.from_json(winner["candidate"])
    if "candidate" in pin:
        return Candidate.from_json(pin["candidate"])
    return Candidate.from_json(pin)


def stamp_gspmd_vs_transpiler(report, transpiler_p50_s, rel_tol=0.05):
    """Add the ``gspmd_vs_transpiler`` field (ISSUE 20 satellite): a
    win-or-tie check of the report's measured winner against the
    transpiler DP lane's p50 on the same workload.  The standing
    `FLAGS_gspmd_executor` default flip is gated on a committed report
    carrying ``win_or_tie: true`` from the on-chip tunnel session —
    instead of a hand-run A/B.  Tie = within ``rel_tol`` of the
    transpiler p50."""
    winner = report.get("winner") or {}
    gp = (winner.get("measured") or {}).get("p50_s")
    tp_ = float(transpiler_p50_s)
    entry = {"transpiler_p50_s": tp_, "gspmd_p50_s": gp,
             "rel_tol": rel_tol}
    if gp is None or tp_ <= 0:
        entry["win_or_tie"] = None
    else:
        entry["win_or_tie"] = bool(gp <= tp_ * (1.0 + rel_tol))
        entry["p50_ratio"] = round(gp / tp_, 4)
    report["gspmd_vs_transpiler"] = entry
    return entry


def policy_summary(mesh, policy):
    """``pp2.dp2.mp2/tp2d`` — mesh dims (canonical axis order, elided
    axes printed at 1) + the policy's class name.  The token bench
    records and `describe_policy` consumers stamp so sweeps across
    factorizations stay distinguishable after the fact."""
    shape = dict(getattr(mesh, "shape", {}) or {})
    dims = ".".join(f"{ax}{int(shape.get(ax, 1))}"
                    for ax in (pmesh.PIPE_AXIS, pmesh.DATA_AXIS,
                               pmesh.MODEL_AXIS))
    name = getattr(policy, "name", None) or type(policy).__name__
    inner = getattr(policy, "inner", None)
    if inner is not None:
        name += f"({getattr(inner, 'name', type(inner).__name__)})"
    return f"{dims}/{name}"
