"""Pipeline parallelism as a ShardingPolicy — stages over a ``pp`` mesh
axis INSIDE the one jit-partitioned step.

The legacy lane (`parallel/pipeline.py` PipelineRunner) cuts the program
into per-stage XLA programs and runs the GPipe schedule from the HOST:
one dispatch per (stage, microbatch, phase), activations round-tripping
through numpy between stages.  That spelling cannot compose with the
gspmd policy layer (no shared jit, no policy-resolved shardings), cannot
ride the quantized ring inside the partitioned graph, and pays
Python-loop dispatch ~S*M times per step — the perf ceiling this module
removes.

Here the whole schedule lowers into ONE computation:

  - ``PipelinePolicy`` composes with the existing policies: an ``inner``
    policy (DataParallel / ZeRO-1 by default) resolves parameter and
    feed placement on the non-pipeline axes, and the stage assignment
    (`parallel.pipeline.assign_stages` — the same dataflow cut the
    legacy lane uses) maps stages onto the ``pp`` axis of a 3-D
    ``(pp, batch, model)`` mesh (`mesh.build_3d_mesh`, paper-spelling
    aliases preserved).
  - The executor lowers the microbatched schedule as a ``lax.scan`` over
    schedule ticks inside a ``shard_map`` island mapped over
    ``(pp, batch)``: every device selects its stage's computation with
    ``lax.switch`` on ``lax.axis_index('pp')``, and stage-boundary
    activations/cotangents ride non-wrapping ``ppermute`` shifts through
    the lint-sanctioned `kernels.pipeline_collectives` surface.  Both
    ``FLAGS_pipeline_schedule`` spellings share the one tick body; only
    the slot formulas differ:

      ``gpipe``  fill/drain — all M forwards (M+S-1 ticks), then all M
                 backwards (M+S-1 ticks); the activation stash holds all
                 M microbatches.
      ``1f1b``   one-forward-one-backward interleaving — the SAME
                 2*(M+S-1) tick count and bubble fraction
                 ((S-1)/(M+S-1)), but a stage starts draining backwards
                 after at most S forwards, so the activation stash holds
                 ``min(M, S)`` microbatches instead of M (the memory win
                 that lets M scale; docs/DISTRIBUTED.md "Pipeline as a
                 policy").

  - Backward recomputes the stage forward from the stashed boundary
    activations (the legacy lane's stage-granular rematerialization,
    now in-graph), parameter gradients accumulate across microbatches,
    merge across stages (`stage_merge` — a zero-elsewhere ownership
    broadcast), and the batch-axis reduction keeps the EQuARX dual-int8
    adaptive ring (`adaptive_quantized_all_reduce`) with the same flags,
    wire-bytes model and payload-counter booking as the plain gspmd
    quant hook.  The optimizer leg traces in global view AFTER the
    island, where the inner policy's specs (ZeRO-1 state sharding)
    partition it.

Contract and limits:

  - The ``pp`` mesh axis size must equal the number of stages the cut
    produces.
  - Parameter specs the inner policy resolves onto a non-batch axis
    demote to replicated with a warning (the island maps ``(pp,
    batch)`` only — model-axis tensor parallelism inside a stage is the
    documented next step, not silently wrong math).
  - Stage-produced scope writes (batch_norm running stats) and
    island-produced optimizer-leg inputs beyond gradients are rejected
    loudly (NotImplementedError) instead of silently mis-averaged.
  - The schedule report (`program._pipeline_schedule`) and the
    ``pt_pipeline_bubble_frac`` / per-boundary
    ``pt_gspmd_resharding_bytes`` gauges are stamped at compile, the
    way the DP lane stamps ``_overlap_schedule``.
"""

from __future__ import annotations

import warnings

import numpy as np

from paddle_tpu.fluid.framework import grad_var_name

from .. import mesh as pmesh
from ..pipeline import boundary_sets, stage_partition
from . import specs as gspecs

__all__ = ["PipelinePolicy", "PipelinePlan", "plan_pipeline",
           "modeled_bubble_fraction", "schedule_slots", "SCHEDULES"]

SCHEDULES = ("gpipe", "1f1b")


def modeled_bubble_fraction(n_stages, n_microbatches):
    """Idle-slot fraction of the lockstep schedule: both spellings run
    2*(M+S-1) ticks of which each stage computes 2*M — the classic
    (S-1)/(M+S-1) pipeline bubble."""
    S, M = int(n_stages), int(n_microbatches)
    return float(S - 1) / float(M + S - 1) if S > 1 else 0.0


def schedule_ticks(n_stages, n_microbatches):
    return 2 * (int(n_microbatches) + int(n_stages) - 1)


def schedule_slots(schedule, n_stages, n_microbatches):
    """The per-tick slot formulas of one schedule, shared by the traced
    island (jnp inputs) and the tests/report (concrete ints — the same
    arithmetic evaluates eagerly).

    Returns ``(K, slots)`` where ``slots(t, stage)`` yields
    ``(m_f, fwd_valid, m_b, bwd_valid, m_arr, arr_valid)``:

      m_f / m_b    the microbatch this stage forwards / backwards at t
      m_arr        the stash slot of the activation payload ARRIVING at
                   t (sent by stage-1 at t-1 over the ppermute wire)

    Invariants (asserted by tests/test_pipeline_policy.py): every
    (stage, microbatch) gets exactly one forward and one backward slot;
    forwards respect the stage chain (+1 tick per hop); a backward's
    incoming cotangent is produced by stage+1 exactly one tick earlier
    (the backward wavefront — which is why the d-wire needs no stash).
    """
    import jax.numpy as jnp

    S, M = int(n_stages), int(n_microbatches)
    K = schedule_ticks(S, M)
    if schedule == "gpipe":

        def slots(t, stage):
            m_f = t - stage
            fv = (m_f >= 0) & (m_f < M) & (t <= M + S - 2)
            m_b = (2 * M + 2 * S - 3) - stage - t
            bv = (m_b >= 0) & (m_b < M) & (t >= M + S - 1)
            # sender (stage-1, t-1): m = (t-1)-(stage-1) = m_f — the
            # arrival lands in the slot consumed this same tick
            av = fv & (stage > 0)
            return m_f, fv, m_b, bv, m_f, av

        return K, slots
    if schedule != "1f1b":
        raise ValueError(
            f"pipeline_schedule must be one of {SCHEDULES}, got "
            f"{schedule!r}")

    def fwd_slot(t, stage):
        # warmup: stage s runs its first min(S-s, M) microbatches
        # back-to-back at t = s+m; steady state: one forward every
        # second tick at t = 2m+s, interleaved with backwards
        mw = t - stage
        wv = (mw >= 0) & (mw <= jnp.minimum(S - 1 - stage, M - 1))
        d = t - stage
        ms = d // 2
        sv = (d >= 0) & (d % 2 == 0) & (ms >= S - stage) & (ms < M)
        return jnp.where(wv, mw, ms), wv | sv

    def slots(t, stage):
        m_f, fv = fwd_slot(t, stage)
        db = t - (2 * S - 1) + stage  # t_b(s, m) = 2m + 2S-1 - s
        m_b = db // 2
        bv = (db >= 0) & (db % 2 == 0) & (m_b < M)
        m_arr, av = fwd_slot(t - 1, stage - 1)
        av = av & (stage > 0)
        return m_f, fv, m_b, bv, m_arr, av

    return K, slots


def _m_bubble():
    from paddle_tpu import observability as obs

    return obs.gauge(
        "pt_pipeline_bubble_frac",
        "Modeled pipeline bubble fraction (idle schedule slots / total "
        "slots, (S-1)/(M+S-1)) of the compiled gspmd pipeline "
        "schedule, per signature and schedule spelling",
        labels=("signature", "schedule"))


# ---------------------------------------------------------------------------
# the policy
# ---------------------------------------------------------------------------


class PipelinePolicy(gspecs.ShardingPolicy):
    """Pipeline stages over the ``pp`` mesh axis, everything else
    delegated to an ``inner`` policy (DataParallelPolicy by default,
    Zero1Policy with ``zero_stage=1``, or any explicit policy).

    ``cut_vars``/``num_microbatches``/``schedule`` default to the
    program's PipelineOptimizer metadata (``program._pipeline``) and the
    ``FLAGS_pipeline_*`` flags, so a program built for the legacy
    PipelineRunner runs on this lane unchanged."""

    name = "pipeline"

    def __init__(self, cut_vars=None, num_microbatches=None, schedule=None,
                 inner=None, zero_stage=0, batch_axis=pmesh.DATA_AXIS,
                 pipe_axis=pmesh.PIPE_AXIS):
        super().__init__(batch_axis=batch_axis)
        self.pipe_axis = pmesh.canonical_axis(pipe_axis)
        if inner is None:
            inner = (gspecs.Zero1Policy(batch_axis=batch_axis)
                     if int(zero_stage) >= 1
                     else gspecs.DataParallelPolicy(batch_axis=batch_axis))
        self.inner = inner
        cut = [getattr(v, "name", v) for v in (cut_vars or [])]
        self.cut_vars = cut or None
        self.num_microbatches = (int(num_microbatches)
                                 if num_microbatches else None)
        if schedule is not None and schedule not in SCHEDULES:
            raise ValueError(
                f"pipeline schedule must be one of {SCHEDULES}, got "
                f"{schedule!r}")
        self.schedule = schedule
        self._demote_warned = False

    # -- resolution ----------------------------------------------------
    def resolve_schedule(self):
        sched = self.schedule
        if sched is None:
            from paddle_tpu.fluid import flags as _flags

            sched = _flags.flag("pipeline_schedule")
        if sched not in SCHEDULES:
            raise ValueError(
                f"FLAGS_pipeline_schedule must be one of {SCHEDULES}, "
                f"got {sched!r}")
        return sched

    def resolve_cut_vars(self, program):
        if self.cut_vars:
            return list(self.cut_vars)
        meta = getattr(program, "_pipeline", None)
        if meta and meta.get("cut_vars"):
            return list(meta["cut_vars"])
        raise ValueError(
            "PipelinePolicy needs cut variables: pass cut_vars= or "
            "minimize() with PipelineOptimizer first")

    def resolve_microbatches(self, program):
        # precedence: explicit policy arg > the program's
        # PipelineOptimizer metadata (honored even at 1 — a pinned
        # M=1 must not silently become the flag default) > the flag
        if self.num_microbatches:
            return self.num_microbatches
        meta = getattr(program, "_pipeline", None)
        if meta and meta.get("num_microbatches"):
            return int(meta["num_microbatches"])
        from paddle_tpu.fluid import flags as _flags

        return int(_flags.flag("pipeline_microbatches"))

    # -- ShardingPolicy surface ----------------------------------------
    def param_spec(self, program, name, shape, mesh):
        spec = self.inner.param_spec(program, name, shape, mesh)
        if any(a and a != self.batch_axis for a in spec):
            # the island maps (pp, batch) only: a model-axis split
            # parameter would be materialized full-size per device —
            # demote to replicated and say so (once), the quant-hook
            # demotion precedent
            if not self._demote_warned:
                warnings.warn(
                    "PipelinePolicy demoted a non-batch-axis parameter "
                    f"spec ({name}: {spec}) to replicated — the pipeline "
                    "island maps (pp, batch) only; model-axis tensor "
                    "parallelism inside a stage is not yet composed")
                self._demote_warned = True
            spec = tuple(a if a == self.batch_axis else None for a in spec)
        return spec

    def feed_spec(self, program, name, shape, mesh):
        return self.inner.feed_spec(program, name, shape, mesh)

    def uses_model_axis(self, program, mesh):
        return False


# ---------------------------------------------------------------------------
# the compilation plan
# ---------------------------------------------------------------------------


class PipelinePlan:
    """Executor-side plan for one pipelined compilation: the stage
    partition, boundary wire layouts, gradient-bucket layout, fetch
    classification, modeled bubble/boundary bytes, and the island body
    builder the executor jits."""

    def __init__(self, plan, program, mesh, policy, feed_shapes,
                 feed_dtypes, feed_specs, scope, quant_hook,
                 block_size=None, algo=None, crossover_kb=None,
                 declared_feed_specs=None):
        from paddle_tpu.fluid import flags as _flags

        self.plan = plan
        self.program = program
        self.mesh = mesh
        self.policy = policy
        self.pipe_axis = policy.pipe_axis
        self.batch_axis = policy.batch_axis
        self.schedule = policy.resolve_schedule()
        self.M = policy.resolve_microbatches(program)
        cut_vars = policy.resolve_cut_vars(program)

        if self.pipe_axis not in mesh.axis_names:
            raise ValueError(
                f"PipelinePolicy needs a {self.pipe_axis!r} mesh axis; "
                f"mesh has {tuple(mesh.axis_names)} — build one with "
                "mesh.build_3d_mesh(pp=...)")
        self.stages, self._stage_of = stage_partition(
            program, plan.ops, cut_vars)
        self.S = len(self.stages)
        pp = int(mesh.shape[self.pipe_axis])
        if pp != self.S:
            raise ValueError(
                f"mesh pp axis {pp} != pipeline stages {self.S}")
        if self.S < 2:
            raise ValueError("pipeline needs at least 2 stages")
        self.dp = int(mesh.shape.get(self.batch_axis, 1))
        self.mapped_axes = (self.pipe_axis,) + (
            (self.batch_axis,) if self.batch_axis in mesh.axis_names
            else ())
        self.boundaries = boundary_sets(self.stages)
        self._validate_structure()

        # feed classification: feeds the CALLER declared replicated
        # (executor feed_specs={name: ()} — shared tables) enter the
        # island WHOLE; everything else splits into M microbatches on
        # dim 0 (the PipelineRunner contract) and keeps the batch-axis
        # component of its resolved placement.  The policy-RESOLVED spec
        # being empty (pp-only mesh, non-divisible batch) does NOT mean
        # replicated — those feeds still microbatch.
        self.feed_specs = dict(feed_specs or {})
        declared = dict(declared_feed_specs or {})
        self.split_feeds, self.whole_feeds = [], []
        self._feed_dp = {}
        for n in plan.feed_names:
            shape = tuple(feed_shapes.get(n) or ())
            if n in declared and not any(a for a in declared[n]):
                self.whole_feeds.append(n)
                continue
            # dp-sharded dim 0 (resolved by the executor) → the island
            # device sees B/dp local rows and splits THOSE into M
            # microbatches, so divisibility is over M*dp
            has_dp = (self.dp > 1 and bool(feed_specs.get(n))
                      and feed_specs[n][0] == self.batch_axis)
            denom = self.M * (self.dp if has_dp else 1)
            if not shape or shape[0] % denom:
                raise ValueError(
                    f"feed {n!r} batch {shape and shape[0]} not "
                    f"divisible by num_microbatches={self.M}"
                    + (f" x dp={self.dp}" if has_dp else "")
                    + " — declare it replicated via feed_specs="
                    "{name: ()} if it is not batch-like")
            self.split_feeds.append(n)
            self._feed_dp[n] = has_dp
        self._feed_shapes = {n: tuple(feed_shapes[n])
                             for n in plan.feed_names}
        self._feed_dtypes = dict(feed_dtypes or {})

        # scope vars the island branches read (params, not opt state)
        reads = set()
        scope_vars = set(plan.donated_names) | set(plan.readonly_names)
        for st in self.stages:
            for op in st.fwd_ops + st.bwd_ops:
                reads.update(set(op.input_arg_names) & scope_vars)
        self.scope_reads_island = sorted(reads)

        # the optimizer leg: global view, original program order
        self.ops_opt = [op for op in plan.ops
                        if op.attrs.get("op_role") == "optimize"]

        # gradient bucket: [quant..., exact...] — the quant section
        # rides the adaptive dual-int8 ring over the batch axis exactly
        # like the plain gspmd quant hook (same flags, same wire model)
        self.quant_hook = bool(quant_hook) and self.dp > 1
        self.block_size = int(block_size if block_size is not None
                              else _flags.flag("quant_allreduce_block_size"))
        self.algo = (algo if algo is not None
                     else _flags.flag("quant_allreduce_algo"))
        self.crossover_kb = crossover_kb
        self._plan_grad_bucket(scope)
        self._discovered = False
        self._model_wire_bytes()

    # -- validation ----------------------------------------------------
    @staticmethod
    def _grad_base(name):
        return name.split("@GRAD")[0] if "@GRAD" in name else None

    def _validate_structure(self):
        plan, program = self.plan, self.program
        # incoming backward cotangents must be gradients OF the boundary
        # the wire carries (a multi-consumer cut activation crosses
        # under its accumulated spelling, `v@GRAD@ACC`); anything else
        # is beyond the ring topology.  The resolved per-boundary wire
        # name map (`dnames[b][var]`) is what the island packs/unpacks.
        self.dnames = []
        for st in self.stages:
            if st.index == self.S - 1:
                if st.grads_in:
                    raise NotImplementedError(
                        "last pipeline stage expects no incoming "
                        f"gradients, got {st.grads_in}")
                continue
            boundary = list(self.boundaries[st.index])
            dmap = {}
            extra = []
            for n in st.grads_in:
                base = self._grad_base(n)
                if base in boundary and base not in dmap:
                    dmap[base] = n
                else:
                    extra.append(n)
            if extra:
                raise NotImplementedError(
                    f"stage {st.index} consumes backward values {extra} "
                    "that are not boundary-activation gradients — this "
                    "program's cross-stage gradient topology needs the "
                    "host-scheduled PipelineRunner")
            # boundary vars nobody differentiates (stop_gradient
            # pass-throughs) still occupy a wire slot: zeros cross
            for v in boundary:
                dmap.setdefault(v, grad_var_name(v))
            self.dnames.append(dmap)
        # island-produced values the optimizer leg (or scope write-back)
        # would need beyond gradients: reject loudly
        produced = set()
        for st in self.stages:
            for op in st.fwd_ops + st.bwd_ops:
                produced.update(op.output_arg_names)
        consumed_opt = set()
        for op in plan.ops:
            if op.attrs.get("op_role") == "optimize":
                consumed_opt.update(op.input_arg_names)
        grads = {g for _p, g in getattr(program, "_params_grads", [])}
        carries = sorted(
            ((consumed_opt | set(plan.write_names)) & produced) - grads)
        if carries:
            raise NotImplementedError(
                f"pipeline policy cannot carry {carries} out of the "
                "stage island (batch_norm running stats / non-gradient "
                "optimizer inputs) — use the host-scheduled "
                "PipelineRunner for this program")

    # -- gradient bucket -----------------------------------------------
    def _plan_grad_bucket(self, scope):
        block = self.plan.block
        pg = dict(getattr(self.program, "_params_grads", []))
        dgc = set(getattr(self.program, "_dgc_encoded", {}).keys()) | \
            set(getattr(self.program, "_dgc_encoded", {}).values())
        owned = []  # (param, grad, stage)
        for st in self.stages:
            for p, g in st.param_grads:
                owned.append((p, g, st.index))
        missing = sorted(set(pg.values())
                         - {g for _p, g, _s in owned})
        if missing:
            raise NotImplementedError(
                f"gradients {missing} are produced by no pipeline "
                "stage's backward ops")

        def info(p, g):
            v = block._find_var_recursive(g)
            dtype = getattr(v, "dtype", None) or "float32"
            shape = getattr(v, "shape", None)
            if shape is None or any(d is None or d < 0 for d in shape):
                pv = scope.get(p)
                shape = tuple(np.shape(pv)) if pv is not None else None
            if shape is None:
                raise ValueError(f"cannot resolve shape of gradient {g}")
            return tuple(shape), str(dtype)

        quant, exact = [], []
        for p, g, s in owned:
            shape, dtype = info(p, g)
            if dtype not in ("float32", "float16", "bfloat16",
                             "float64"):
                # the gradient bucket is one fp32 buffer (packed,
                # psum-merged over pp, mean-divided) — a non-float
                # payload would be silently corrupted by the round
                # trip, so reject it loudly (the module's contract)
                raise NotImplementedError(
                    f"gradient {g} has non-float dtype {dtype} — the "
                    "pipeline policy's fp32 gradient bucket cannot "
                    "carry it; use the host-scheduled PipelineRunner")
            entry = (p, g, s, shape, dtype)
            if self.quant_hook and g not in dgc and dtype != "float64":
                quant.append(entry)
            else:
                exact.append(entry)
        layout, off = [], 0
        for p, g, s, shape, dtype in quant + exact:
            size = int(np.prod(shape, dtype=np.int64)) if shape else 1
            layout.append({"param": p, "grad": g, "stage": s,
                           "shape": shape, "dtype": dtype,
                           "offset": off, "size": size})
            off += size
        self.grad_layout = layout
        self.quant_elems = sum(e["size"] for e in layout[:len(quant)])
        self.total_grad_elems = max(off, 1)

    def _model_wire_bytes(self):
        from paddle_tpu.kernels import quantized_collectives as qc
        from paddle_tpu.kernels.ring_collectives import (
            select_allreduce_algo)

        total, buckets = 0, []
        if self.quant_hook and self.quant_elems:
            resolved = select_allreduce_algo(
                self.quant_elems, self.dp, algo=self.algo,
                crossover_kb=self.crossover_kb,
                block_size=self.block_size)
            total = qc.wire_bytes(self.quant_elems,
                                  block_size=self.block_size,
                                  n_devices=self.dp, algo=resolved)
            buckets.append({"elements": self.quant_elems,
                            "algo": resolved, "fused_update": False})
        self.wire_bytes_per_step = total
        self.bucket_report = buckets

    # -- shape discovery + layouts --------------------------------------
    def _discover(self, trace_stage, scope):
        """Chain jax.eval_shape over the stage forwards to resolve every
        boundary activation's LOCAL (per-device microbatch) shape/dtype
        plus the island-fetch shapes — no reliance on the program's
        declared (-1) shapes, and any unsupported topology fails here
        with a stage-indexed error instead of deep inside the jit."""
        import jax
        import jax.numpy as jnp

        def canon(dt):
            return jax.dtypes.canonicalize_dtype(np.dtype(str(dt)))

        def abs_of(v):
            return jax.ShapeDtypeStruct(tuple(np.shape(v)),
                                        canon(v.dtype))

        scope_abs = {n: abs_of(scope.get(n))
                     for n in self.scope_reads_island}
        mb_abs = {}
        for n in self.plan.feed_names:
            shape = self._feed_shapes[n]
            dt = canon(self._feed_dtypes.get(n, "float32"))
            if n in self.split_feeds:
                denom = self.M * (self.dp if self._feed_dp[n] else 1)
                shape = (shape[0] // denom,) + tuple(shape[1:])
            mb_abs[n] = jax.ShapeDtypeStruct(tuple(shape), dt)
        step_abs = jax.ShapeDtypeStruct((), jnp.uint32)

        # owner stage of each island fetch (produced by a stage forward)
        fwd_producer = {}
        for st in self.stages:
            for op in st.fwd_ops:
                for n in op.output_arg_names:
                    fwd_producer.setdefault(n, st.index)
        self.island_fetches = [n for n in self.plan.jit_fetch_names
                               if n in fwd_producer]
        self.fetch_owner = {n: fwd_producer[n]
                            for n in self.island_fetches}

        known = {}  # boundary var -> (shape, dtype)
        fetch_info = {}
        for st in self.stages:
            s = st.index
            acts_abs = {}
            if s > 0:
                acts_abs = {n: jax.ShapeDtypeStruct(*known[n])
                            for n in self.boundaries[s - 1]}
            wanted = list(self.boundaries[s]) if s < self.S - 1 else []
            wanted += [n for n, o in self.fetch_owner.items() if o == s]

            def f(scope_a, mb_a, acts_a, step_a, _s=s, _w=wanted):
                env = {}
                env.update(scope_a)
                env.update(mb_a)
                env.update(acts_a)
                trace_stage(env, step_a, self.stages[_s].fwd_ops,
                            mesh_axes=self.mapped_axes)
                return {n: env[n] for n in _w}

            try:
                out = jax.eval_shape(f, scope_abs, mb_abs, acts_abs,
                                     step_abs)
            except KeyError as e:
                raise NotImplementedError(
                    f"pipeline stage {s} forward needs value {e} that "
                    "crosses stages outside the boundary wire — use "
                    "the host-scheduled PipelineRunner") from None
            for n, a in out.items():
                if n in (self.boundaries[s] if s < self.S - 1 else ()) \
                        and not jnp.issubdtype(a.dtype, jnp.floating):
                    # the stage wire is one fp32 buffer: an integer
                    # activation above 2^24 (or a bool) would be
                    # silently quantized by the int->f32->int round
                    # trip — reject loudly instead
                    raise NotImplementedError(
                        f"boundary activation {n} has non-float dtype "
                        f"{a.dtype} — the pipeline policy's fp32 stage "
                        "wire cannot carry it; use the host-scheduled "
                        "PipelineRunner")
                known[n] = (tuple(a.shape), a.dtype)
                if n in self.fetch_owner:
                    fetch_info[n] = (tuple(a.shape), a.dtype)

        def layout_of(names):
            out, off = [], 0
            for n in names:
                shape, dt = known[n]
                size = int(np.prod(shape, dtype=np.int64)) if shape else 1
                out.append({"name": n, "shape": shape, "dtype": dt,
                            "offset": off, "size": size})
                off += size
            return out, off

        self.b_layout, widths = [], []
        for b in range(self.S - 1):
            lay, w = layout_of(self.boundaries[b])
            self.b_layout.append(lay)
            widths.append(w)
        self.wire_elems = max(widths + [1])
        self.f_layout, off = [], 0
        for n in self.island_fetches:
            shape, dt = fetch_info[n]
            if not jnp.issubdtype(dt, jnp.floating):
                raise NotImplementedError(
                    f"island fetch {n} has non-float dtype {dt} — the "
                    "fp32 fetch stash cannot carry it; fetch it from a "
                    "non-pipelined program")
            size = int(np.prod(shape, dtype=np.int64)) if shape else 1
            self.f_layout.append({"name": n, "shape": shape, "dtype": dt,
                                  "offset": off, "size": size})
            off += size
        self.fetch_elems = max(off, 1)
        self.boundary_elems = widths
        self._discovered = True

    # -- schedule report -------------------------------------------------
    def schedule_report(self):
        """The per-stage schedule report stamped on the program
        (`program._pipeline_schedule`), the `_overlap_schedule` way:
        bubble fraction per microbatch count, boundary payloads, stash
        depth — what the bench record and docs table read."""
        from paddle_tpu.kernels import pipeline_collectives as pcol

        S, M = self.S, self.M
        per_m = {m: round(modeled_bubble_fraction(S, m), 6)
                 for m in (1, 2, 4, 8, 16, 32) if m >= 1}
        report = {
            "schedule": self.schedule,
            "n_stages": S,
            "num_microbatches": M,
            "ticks": schedule_ticks(S, M),
            "bubble_frac": round(modeled_bubble_fraction(S, M), 6),
            "bubble_frac_per_microbatches": per_m,
            "stash_depth": min(M, S) if self.schedule == "1f1b" else M,
            "wire_elems": getattr(self, "wire_elems", None),
            "boundaries": [
                {"link": f"{b}->{b + 1}",
                 "vars": list(self.boundaries[b]),
                 "elements": self.boundary_elems[b],
                 "bytes_per_step": pcol.boundary_wire_bytes(
                     self.boundary_elems[b], M)}
                for b in range(S - 1)
            ] if self._discovered else [],
            "grad_reduction": {
                "batch_axis_devices": self.dp,
                "quant_hook": self.quant_hook,
                "quant_elements": self.quant_elems,
                "wire_bytes_per_step": self.wire_bytes_per_step,
                "buckets": self.bucket_report,
            },
        }
        return report

    # -- the island ------------------------------------------------------
    def island_body(self, trace_stage, scope):
        """Build ``fn(scope_vals, feeds, step) -> (grads, fetches)``: the
        whole microbatched schedule under ONE shard_map over
        ``(pp, batch)``.  ``trace_stage`` is the executor's one
        LowerContext assembly point, shared with the optimizer leg."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.kernels import pipeline_collectives as pcol
        from paddle_tpu.kernels.ring_collectives import (
            adaptive_quantized_all_reduce)

        if not self._discovered:
            self._discover(trace_stage, scope)

        S, M = self.S, self.M
        D = min(M, S) if self.schedule == "1f1b" else M
        K, slots = schedule_slots(self.schedule, S, M)
        W, G, F = self.wire_elems, self.total_grad_elems, self.fetch_elems
        pp, axis = self.pipe_axis, self.batch_axis
        dp_mapped = axis in self.mapped_axes
        f32 = jnp.float32
        grad_names = [e["grad"] for e in self.grad_layout]

        def pack(env, layout, width):
            flat = jnp.zeros((width,), f32)
            for e in layout:
                flat = flat.at[e["offset"]:e["offset"] + e["size"]].set(
                    jnp.ravel(env[e["name"]]).astype(f32))
            return flat

        def unpack(flat, layout, rename=None):
            out = {}
            for e in layout:
                v = flat[e["offset"]:e["offset"] + e["size"]] \
                    .reshape(e["shape"]).astype(e["dtype"])
                out[rename[e["name"]] if rename else e["name"]] = v
            return out

        def island(scope_vals, feeds, step):
            stage = lax.axis_index(pp)

            # stacked-microbatch feeds: [M, micro, ...] on dim 0
            stacked = {}
            for n, v in feeds.items():
                if n in self.split_feeds:
                    stacked[n] = jnp.reshape(
                        v, (M, v.shape[0] // M) + tuple(v.shape[1:]))
                else:
                    stacked[n] = v

            def mb_at(m):
                return {n: (lax.dynamic_index_in_dim(v, m, 0,
                                                     keepdims=False)
                            if n in self.split_feeds else v)
                        for n, v in stacked.items()}

            def fwd_branch(s):
                def br(a_slot, d_recv, mb, mstep, _s=s):
                    env = dict(scope_vals)
                    env.update(mb)
                    if _s > 0:
                        env.update(unpack(a_slot, self.b_layout[_s - 1]))
                    trace_stage(env, mstep, self.stages[_s].fwd_ops,
                                mesh_axes=self.mapped_axes)
                    wire = (pack(env, self.b_layout[_s], W)
                            if _s < S - 1 else jnp.zeros((W,), f32))
                    fl = [e for e in self.f_layout
                          if self.fetch_owner[e["name"]] == _s]
                    fb = pack({e["name"]: env[e["name"]] for e in fl},
                              fl, F) if fl else jnp.zeros((F,), f32)
                    return (wire, jnp.zeros((W,), f32),
                            jnp.zeros((G,), f32), fb)
                return br

            def bwd_branch(s):
                def br(a_slot, d_recv, mb, mstep, _s=s):
                    env = dict(scope_vals)
                    env.update(mb)
                    incoming = {}
                    if _s > 0:
                        env.update(unpack(a_slot, self.b_layout[_s - 1]))
                    if _s < S - 1:
                        incoming = unpack(d_recv, self.b_layout[_s],
                                          rename=self.dnames[_s])
                        env.update(incoming)
                    st = self.stages[_s]
                    trace_stage(env, mstep, st.fwd_ops + st.bwd_ops,
                                mesh_axes=self.mapped_axes)
                    if _s > 0:
                        dparts = {}
                        passthru = (set(self.boundaries[_s])
                                    if _s < S - 1 else set())
                        for e in self.b_layout[_s - 1]:
                            # the consumer stage's expected spelling
                            # (possibly the accumulated `@GRAD@ACC`
                            # form) — produced by this stage's traced
                            # backward under the SAME program var name
                            gname = self.dnames[_s - 1][e["name"]]
                            mine = env.get(gname)
                            thru = (incoming.get(
                                self.dnames[_s][e["name"]])
                                    if e["name"] in passthru else None)
                            # a stage both consuming AND forwarding a
                            # skip activation owns the sum of its own
                            # cotangent and the downstream one
                            if mine is not None and thru is not None \
                                    and mine is not thru:
                                dparts[gname] = (
                                    mine.astype(f32) + thru.astype(f32))
                            elif mine is not None:
                                dparts[gname] = mine
                            elif thru is not None:
                                dparts[gname] = thru
                            else:
                                dparts[gname] = jnp.zeros(e["shape"], f32)
                        dwire = pack(
                            {e["name"]:
                             dparts[self.dnames[_s - 1][e["name"]]]
                             for e in self.b_layout[_s - 1]},
                            self.b_layout[_s - 1], W)
                    else:
                        dwire = jnp.zeros((W,), f32)
                    gb = jnp.zeros((G,), f32)
                    for e in self.grad_layout:
                        if e["stage"] != _s:
                            continue
                        gb = gb.at[e["offset"]:e["offset"] + e["size"]] \
                            .set(jnp.ravel(env[e["grad"]]).astype(f32))
                    return (jnp.zeros((W,), f32), dwire, gb,
                            jnp.zeros((F,), f32))
                return br

            def noop(a_slot, d_recv, mb, mstep):
                return (jnp.zeros((W,), f32), jnp.zeros((W,), f32),
                        jnp.zeros((G,), f32), jnp.zeros((F,), f32))

            branches = ([fwd_branch(s) for s in range(S)]
                        + [bwd_branch(s) for s in range(S)] + [noop])

            def tick(carry, t):
                wire, dwire, stash, gacc, fstash = carry
                # stage-boundary transfers: the lint-sanctioned surface
                wire_r = pcol.stage_shift(wire, pp, S)
                dwire_r = pcol.stage_shift(dwire, pp, S, reverse=True)
                m_f, fv, m_b, bv, m_arr, av = slots(t, stage)
                slot_arr = jnp.clip(m_arr, 0, M - 1) % D
                stash = stash.at[slot_arr].set(
                    jnp.where(av, wire_r, stash[slot_arr]))
                m_sel = jnp.clip(jnp.where(fv, m_f, m_b), 0, M - 1)
                mb = mb_at(m_sel)
                mstep = (step * np.uint32(M)
                         + m_sel.astype(jnp.uint32))
                a_slot = stash[m_sel % D]
                idx = jnp.where(fv, stage,
                                jnp.where(bv, S + stage, 2 * S))
                w_out, d_out, gb, fb = lax.switch(
                    idx, branches, a_slot, dwire_r, mb, mstep)
                fstash = fstash.at[m_sel].set(
                    jnp.where(fv, fb, fstash[m_sel]))
                return (w_out, d_out, stash, gacc + gb, fstash), None

            carry0 = (jnp.zeros((W,), f32), jnp.zeros((W,), f32),
                      jnp.zeros((D, W), f32), jnp.zeros((G,), f32),
                      jnp.zeros((M, F), f32))
            (_, _, _, gacc, fstash), _ = lax.scan(
                tick, carry0, jnp.arange(K, dtype=jnp.int32))

            # ownership merges over pp (zero off-stage, bit-exact)
            g = pcol.stage_merge(gacc, pp) / M
            fstash = pcol.stage_merge(fstash, pp)

            # batch-axis gradient reduction: the EQuARX dual-int8 ring
            # for the quant section (transpiler seed scaling at the
            # boundary), exact fp32 mean for the rest
            if dp_mapped and self.dp > 1:
                parts = []
                if self.quant_elems:
                    parts.append(adaptive_quantized_all_reduce(
                        g[:self.quant_elems] / self.dp, axis,
                        block_size=self.block_size,
                        algo=self.algo or "auto",
                        crossover_kb=self.crossover_kb))
                if self.quant_elems < G:
                    # exact fp32 mean (DGC/non-float payloads the wire
                    # format must not touch — quant_hook._reduce_exact
                    # parity)
                    parts.append(lax.psum(                       # collective: allow
                        g[self.quant_elems:] / self.dp, axis))
                g = jnp.concatenate(parts) if len(parts) > 1 else parts[0]

            grads = {}
            for e in self.grad_layout:
                grads[e["grad"]] = (
                    g[e["offset"]:e["offset"] + e["size"]]
                    .reshape(e["shape"]).astype(e["dtype"]))
            fetches = [
                fstash[:, e["offset"]:e["offset"] + e["size"]]
                .reshape((M,) + tuple(e["shape"])).astype(e["dtype"])
                for e in self.f_layout]
            return grads, fetches

        def feed_spec(n):
            # the microbatch reshape happens INSIDE the island, so the
            # in_spec covers the raw [B, ...] feed: dp-sharded dim 0
            # when the executor resolved one, replicated otherwise
            rank = len(self._feed_shapes[n])
            if n in self.split_feeds and self._feed_dp[n]:
                return P(*((axis,) + (None,) * max(0, rank - 1)))
            return P(*((None,) * rank))

        in_specs = (
            {n: P() for n in self.scope_reads_island},
            {n: feed_spec(n) for n in self.plan.feed_names},
            P(),
        )
        fetch_spec = P(axis) if (dp_mapped and self.dp > 1) else P()
        out_specs = ({n: P() for n in grad_names},
                     [fetch_spec for _ in self.f_layout])
        mapped = jax.shard_map(island, mesh=self.mesh,
                               in_specs=in_specs, out_specs=out_specs,
                               check_vma=False)

        def body(scope_vals, feeds, step):
            # stacked split feeds enter as [M, micro, ...] inside the
            # island; the reshape itself traces in the island so the
            # global dispatch keeps the executor's plain feed signature
            return mapped(scope_vals, dict(feeds), step)

        return body


def plan_pipeline(plan, program, mesh, policy, feed_shapes, feed_dtypes,
                  feed_specs, scope, quant_hook, block_size=None,
                  algo=None, crossover_kb=None,
                  declared_feed_specs=None):
    """Build the PipelinePlan for one compilation.  Pipeline execution
    is an EXPLICIT policy choice, so structural problems raise instead
    of demoting (the quant hook demotes because it is an optimization;
    a pipeline that silently fell back to no-pipeline would train a
    different program than asked for)."""
    return PipelinePlan(plan, program, mesh, policy, feed_shapes,
                        feed_dtypes, feed_specs, scope, quant_hook,
                        block_size=block_size, algo=algo,
                        crossover_kb=crossover_kb,
                        declared_feed_specs=declared_feed_specs)
