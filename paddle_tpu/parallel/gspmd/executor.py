"""The one jit-partitioned GSPMD executor.

Compiles a whole Program ONCE under `jax.jit` with in/out shardings
resolved by a `ShardingPolicy` (specs.py) and
`with_sharding_constraint` annotations applied at the producing op
during the trace — no per-gradient collective ops are ever inserted by
Python.  XLA's SPMD partitioner places every collective; the compiled
HLO is inspected to publish how many bytes of resharding/collective
traffic it chose (``pt_gspmd_resharding_bytes``), which is also how the
tests PROVE the collectives came from XLA and not from the program
(tests/test_gspmd_core.py asserts no ``c_allreduce*`` op types exist in
the program it runs).

Shares the `_JitExecutable` plumbing of `fluid/executor.py` — the
compile-cache counters (``pt_compile_cache_total{path="gspmd"}``), step
histograms (``pt_step_seconds``), cost/memory analysis, and the
BlockPlan prune/analyze/write-back contract — so a GSPMD step
introspects exactly like a single-device or shard_map one.

The DP and hybrid runners are thin policy selections over this class
(`DataParallelRunner(gspmd=True)` / `HybridParallelRunner(gspmd=True)`,
FLAGS_gspmd_executor); the quantized gradient wire format rides along
through `quant_hook.py` when the quant path is opted in.
"""

from __future__ import annotations

import warnings

import numpy as np

from paddle_tpu.fluid import registry
from paddle_tpu.fluid.executor import _JitExecutable, trace_block
from paddle_tpu.observability.profiling import (hlo_collective_bytes,
                                                hlo_collective_counts,
                                                hlo_inventory)

from .. import mesh as pmesh
from . import specs as gspecs
from .quant_hook import plan_quant_hook

__all__ = ["GSPMDExecutor", "hlo_collective_bytes",
           "hlo_collective_counts", "hlo_inventory", "prep_feed"]


def prep_feed(feed, fetch_list):
    """Coerce feed values and build the (feed_sig, fetch_names) cache
    identity — THE shared dispatch-key helper of the partitioned lanes
    (HybridParallelRunner._prep delegates here).  v.dtype directly:
    np.asarray on a device-resident jax array would force a host
    transfer just to read the dtype."""
    feed = {k: np.asarray(v) if not hasattr(v, "dtype") else v
            for k, v in (feed or {}).items()}
    fetch_names = [f if isinstance(f, str) else f.name
                   for f in (fetch_list or [])]
    feed_sig = tuple((k, tuple(np.shape(v)), str(v.dtype))
                     for k, v in sorted(feed.items()))
    return feed, fetch_names, feed_sig


# ---------------------------------------------------------------------------
# compiled-HLO inspection: what did XLA's partitioner insert?
#
# The parser lives in observability/profiling.py now (promoted into the
# general per-category HLO inventory the MFU/roofline accounting reads);
# re-exported from the module imports above because this module is where
# the GSPMD acceptance gates and the bench rungs historically import it.
# ---------------------------------------------------------------------------


def _m_resharding():
    from paddle_tpu import observability as obs

    return obs.gauge(
        "pt_gspmd_resharding_bytes",
        "Per-step collective/resharding bytes the GSPMD-partitioned "
        "executable moves, from compiled-HLO inspection, per signature",
        labels=("signature",))


# ---------------------------------------------------------------------------
# the compiled block
# ---------------------------------------------------------------------------


class _GSPMDBlock(_JitExecutable):
    """One (program version, feed signature, fetch list) → GSPMD-
    partitioned XLA executable, with policy-resolved in/out shardings."""

    def __init__(self, executor, scope, feed_names, fetch_names,
                 feed_shapes=None, feed_dtypes=None, n_steps=1,
                 stacked_feed=False):
        import jax

        from paddle_tpu.fluid.executor import (BlockPlan,
                                               HostOpsUnsupported)

        program, mesh, policy = (executor.program, executor.mesh,
                                 executor.policy)
        feed_shapes = dict(feed_shapes or {})
        plan = BlockPlan(program, program.global_block(), feed_names,
                         fetch_names, scope)
        if plan.host_pre_ops:
            raise NotImplementedError(
                "pre-stage host ops (distributed lookup) are only "
                "supported by the single-device Executor")
        n_steps = int(n_steps)
        chain_mode = n_steps > 1 or stacked_feed
        if chain_mode and (plan.host_ops or plan.host_fetch_names):
            raise HostOpsUnsupported(
                "run_steps chains the whole loop on-device; host ops "
                f"({[op.type for op in plan.host_ops]}) need the host "
                "between steps — use run() per step")
        self.n_steps = n_steps
        self.stacked_feed = bool(stacked_feed)
        self.plan = plan
        self.program = program
        self.mesh = mesh
        self.policy = policy
        self.feed_names = plan.feed_names
        self.fetch_names = plan.fetch_names
        self.donated_names = plan.donated_names
        self.readonly_names = plan.readonly_names
        self.write_names = plan.write_names
        self.label = (f"gspmd@{id(program):x}/v{program._version}"
                      f"/{policy.name}")
        self.last_hlo = None
        self._prof_state = {"ran": False}

        # resolved feed placement, ONE source for the jit in_shardings
        # and the quant island's in_specs: explicit executor.feed_specs
        # win (alias-canonicalized); otherwise the policy resolves
        # against the REAL feed shape, so feed_spec's divisibility gate
        # (non-divisible batch -> graceful replication) actually engages.
        # stacked_feed: the leading [n_steps] axis is the loop index —
        # the policy resolves against the PER-STEP shape and the jit
        # shardings prepend a replicated dim.
        axis = policy.batch_axis

        def per_step_shape(n):
            shape = feed_shapes.get(n)
            if shape is not None and self.stacked_feed:
                return tuple(shape[1:])
            return shape

        self._feed_specs = {}
        for n in self.feed_names:
            if n in executor.feed_specs:
                spec = tuple(pmesh.canonical_axis(a)
                             for a in executor.feed_specs[n])
            else:
                spec = policy.feed_spec(program, n, per_step_shape(n),
                                        mesh)
            self._feed_specs[n] = spec

        # pipeline policy: the microbatched stage island replaces BOTH
        # the plain trace and the quant-hook split — its batch-axis
        # gradient reduction embeds the same EQuARX ring
        # (pipeline_policy.py), so executor.quant_hook still decides the
        # wire format
        self.pplan = None
        self.qplan = None
        from .pipeline_policy import PipelinePolicy, plan_pipeline

        if isinstance(policy, PipelinePolicy):
            self.pplan = plan_pipeline(
                plan, program, mesh, policy,
                {n: per_step_shape(n) for n in self.feed_names},
                feed_dtypes, self._feed_specs, scope,
                executor.quant_hook,
                block_size=executor.quant_block_size,
                algo=executor.quant_algo,
                crossover_kb=executor.quant_crossover_kb,
                declared_feed_specs=executor.feed_specs)
        elif executor.quant_hook:
            self.qplan = plan_quant_hook(
                plan, program, mesh, policy,
                block_size=executor.quant_block_size,
                algo=executor.quant_algo,
                crossover_kb=executor.quant_crossover_kb,
                impl=executor.quant_impl)
            if self.qplan is not None:
                # the island maps only the batch axis: keep the batch
                # component of each feed's placement, replicate the rest
                self.qplan.feed_island_specs = {
                    n: tuple(a if a == axis else None for a in spec)
                    for n, spec in self._feed_specs.items()}

        cons_specs = policy.activation_constraints(program, mesh)
        cons = {n: (lambda v, s=s: gspecs.constrain(v, mesh, s))
                for n, s in cons_specs.items()}
        self.constraint_names = sorted(cons_specs)

        def trace_stage(env, step, ops, mesh_axes=()):
            """The ONE LowerContext assembly point for both stages —
            constraints apply only in global view (inside the quant
            island the batch axis is mapped, not partitioned)."""
            ctx = registry.LowerContext(
                step=step,
                is_test=getattr(program, "_is_test", False),
                block=plan.block, mesh_axes=mesh_axes)
            ctx.program = program
            ctx.dtype_policy = getattr(program, "_dtype_policy", None)
            ctx.place = None
            if not mesh_axes and cons:
                ctx.sharding_constraints = cons
            trace_block(plan.block, env, ctx, ops=ops)
            return env

        if self.pplan is not None:
            pl = self.pplan
            island = pl.island_body(
                lambda env, step, ops, mesh_axes=(): trace_stage(
                    env, step, ops, mesh_axes), scope)
            fetch_names_jit = plan.jit_fetch_names
            write_names = plan.write_names
            island_fetch_pos = {n: i
                                for i, n in enumerate(pl.island_fetches)}
            self._island_fetches = list(pl.island_fetches)

            def body(donated, readonly, feeds, step):
                scope_vals = {}
                scope_vals.update(donated)
                scope_vals.update(readonly)
                island_in = {n: scope_vals[n]
                             for n in pl.scope_reads_island}
                grads, stacked = island(island_in, dict(feeds), step)
                env = dict(scope_vals)
                env.update(grads)
                # optimizer leg in GLOBAL view: the inner policy's specs
                # (ZeRO-1 state sharding) partition it
                trace_stage(env, step, pl.ops_opt)
                fetches = [stacked[island_fetch_pos[n]]
                           if n in island_fetch_pos else env[n]
                           for n in fetch_names_jit]
                out_writes = {n: env[n] for n in write_names if n in env}
                return fetches, out_writes

            # stamp the schedule report the _overlap_schedule way, and
            # book the modeled surfaces: bubble fraction per signature +
            # per-stage-boundary payloads on the resharding gauge
            from paddle_tpu.kernels import pipeline_collectives as pcol

            from .pipeline_policy import _m_bubble

            report = pl.schedule_report()
            program._pipeline_schedule = report
            _m_bubble().labels(signature=self.label,
                               schedule=pl.schedule).set(
                report["bubble_frac"])
            for b, elems in enumerate(pl.boundary_elems):
                _m_resharding().labels(
                    signature=f"{self.label}/pp{b}-{b + 1}").set(
                    float(pcol.boundary_wire_bytes(elems, pl.M)))
        elif self.qplan is None:
            ops_all = plan.ops
            fetch_names_jit = plan.jit_fetch_names
            write_names = plan.write_names

            def body(donated, readonly, feeds, step):
                env = {}
                env.update(donated)
                env.update(readonly)
                env.update(feeds)
                trace_stage(env, step, ops_all)
                fetches = [env[n] for n in fetch_names_jit]
                out_writes = {n: env[n] for n in write_names if n in env}
                return fetches, out_writes

            self._island_fetches = []
        else:
            qp = self.qplan
            island = qp.island_body(
                lambda env, step, ops, mesh_axes=(): trace_stage(
                    env, step, ops, mesh_axes))
            fetch_names_jit = plan.jit_fetch_names
            write_names = plan.write_names
            island_fetch_pos = {n: i
                                for i, n in enumerate(qp.island_fetches)}
            self._island_fetches = list(qp.island_fetches)

            def body(donated, readonly, feeds, step):
                scope_vals = {}
                scope_vals.update(donated)
                scope_vals.update(readonly)
                island_in = {n: scope_vals[n]
                             for n in qp.scope_reads_island}
                carry, grads, fusedq, stacked = island(
                    island_in, dict(feeds), step)
                env = dict(scope_vals)
                env.update(carry)
                env.update(grads)
                # the fused-update leg's keep-quant wire triple: the
                # rewritten optimizer ops (qp.ops_opt_fused) dequantize
                # their block slice inline — the reduced fp32 bucket
                # never materializes on this lane either
                env.update(fusedq)
                trace_stage(env, step, qp.ops_opt_fused)
                fetches = [stacked[island_fetch_pos[n]]
                           if n in island_fetch_pos else env[n]
                           for n in fetch_names_jit]
                out_writes = {n: env[n] for n in write_names if n in env}
                return fetches, out_writes

        # read AFTER island_body construction: a demoted
        # custom_partitioning reducer zeroes the plan's modeled bytes
        active_plan = self.pplan or self.qplan
        self.wire_bytes_per_step = (active_plan.wire_bytes_per_step
                                    if active_plan else 0)
        self.fused_bytes_saved = (self.qplan.fused_bytes_saved
                                  if self.qplan else 0)

        from paddle_tpu.health import wrap_body as _health_gate

        body = _health_gate(program, body)

        if chain_mode:
            # run_steps: the whole n-step loop in ONE jitted call — the
            # ONE chain combinator every lane shares
            # (fluid.executor.chain_step_body): fori_loop threads the
            # donated params/opt-state on-device, the step counter
            # advances per iteration, only the final step's fetches
            # return.
            from paddle_tpu.fluid.executor import chain_step_body

            body = chain_step_body(body, n_steps, self.stacked_feed)

        def mesh_body(*args):
            # mesh-adaptive lowerings (ring attention) read current_mesh()
            with pmesh.mesh_guard(mesh):
                return body(*args)

        def shard_of(name, v):
            shape = tuple(np.shape(v)) if v is not None else None
            return gspecs.named_sharding(
                mesh, policy.param_spec(program, name, shape, mesh))

        don_sh = {n: shard_of(n, scope.get(n)) for n in self.donated_names}
        ro_sh = {n: shard_of(n, scope.get(n)) for n in self.readonly_names}

        def feed_sharding(n):
            spec = self._feed_specs[n]
            if self.stacked_feed:
                # leading [n_steps] axis is the loop index — replicated;
                # the batch dim (now dim 1) keeps its resolved sharding
                spec = (None,) + tuple(spec)
            return gspecs.named_sharding(mesh, spec)

        feeds_sh = {n: feed_sharding(n) for n in self.feed_names}
        repl = gspecs.named_sharding(mesh, ())
        stacked_sh = gspecs.named_sharding(mesh, (axis,)) \
            if axis in mesh.axis_names else repl
        fetch_sh = [stacked_sh if n in self._island_fetches else repl
                    for n in plan.jit_fetch_names]
        out_sh = (fetch_sh,
                  {n: don_sh.get(n, repl) for n in self.write_names})
        self._in_shardings = (don_sh, ro_sh, feeds_sh, repl)
        self._jitted = jax.jit(mesh_body,
                               in_shardings=self._in_shardings,
                               out_shardings=out_sh,
                               donate_argnums=(0,))
        self._don_sh, self._ro_sh, self._feeds_sh = don_sh, ro_sh, feeds_sh
        self.capture_hlo = executor.capture_hlo

    def _capture_hlo(self, args):
        """AOT-lower the same computation and record its OPTIMIZED
        (post-partitioner) HLO: feeds .last_hlo, the resharding gauge and
        the acceptance gates.  The XLA compile dedupes against the
        dispatch compile through jax's compilation cache, so this costs
        one extra trace, not one extra compile.  A failure latches
        (_hlo_capture_failed) — retrying the whole-program retrace every
        step would tax pt_step_seconds and re-warn forever."""
        try:
            self.last_hlo = self._jitted.lower(*args).compile().as_text()
        except Exception as e:  # backend without as_text
            self._hlo_capture_failed = True
            warnings.warn(f"gspmd HLO capture failed: {e}")
            return
        inv = hlo_inventory(self.last_hlo)
        _m_resharding().labels(signature=self.label).set(
            float(inv["total"]["bytes"]))
        # feed the attribution layer: the collective inventory joins the
        # cost-model flops/bytes into the per-signature roofline verdict
        from paddle_tpu.observability import profiling as _profiling

        _profiling.note_collectives(
            self.label, inv["total"]["bytes"],
            counts={k: v["count"] for k, v in inv.items()
                    if k != "total"})

    def run(self, scope, feeds, step):
        from paddle_tpu.fluid import profiler as _prof
        from paddle_tpu.observability import profiling as _profiling

        # step_phases outermost; timed_run keeps its historic region
        # (staging..scope-writes) so the "run" span never absorbs the
        # host-op tail — fetch_sync brackets accumulate across both
        with _profiling.step_phases("gspmd", self.label) as ph:
            with _prof.timed_run(self.label, self._prof_state) as timer:
                with ph.phase("feed_prep"):
                    donated = {n: scope.get(n)
                               for n in self.donated_names}
                    readonly = {n: scope.get(n)
                                for n in self.readonly_names}
                    args = (donated, readonly, dict(feeds),
                            np.uint32(step))
                    if (self.capture_hlo and self.last_hlo is None
                            and not getattr(self, "_hlo_capture_failed",
                                            False)):
                        self._capture_hlo(
                            self._jit_args(scope, feeds, step))
                with ph.phase("dispatch"):
                    with warnings.catch_warnings():
                        warnings.simplefilter("ignore")  # donation unsupported on CPU
                        fetches, out_writes = self._jitted(*args)
                with ph.phase("device_wait"):
                    ph.wait((fetches, out_writes))
                with ph.phase("fetch_sync"):
                    for n, v in out_writes.items():
                        scope.set(n, v)
                    timer.done(fetches, out_writes)
            with ph.phase("fetch_sync"):
                self.plan.run_host_ops(scope)
                out = self.plan.assemble_fetches(fetches, scope)
        return out


# ---------------------------------------------------------------------------
# the executor
# ---------------------------------------------------------------------------


class GSPMDExecutor:
    """Compile + run a Program GSPMD-partitioned under one policy.

    The runners' shared core: `DataParallelRunner(gspmd=True)` selects a
    `DataParallelPolicy`, `HybridParallelRunner(gspmd=True)` a
    `TensorParallelPolicy` — both delegate run/cost_analysis here, so
    there is exactly one partitioned compile path (ROADMAP "GSPMD-native
    sharding core").

    quant_hook (None = FLAGS_quant_allreduce): keep gradient reduction
    on the dual-int8 adaptive ring inside the partitioned graph
    (quant_hook.py) — wire bytes book on the same
    ``pt_collective_payload_bytes_total{collective="c_allreduce_quant"}``
    family the transpiler lane uses.
    """

    def __init__(self, program, mesh, policy=None, scope=None,
                 feed_specs=None, quant_hook=None, quant_block_size=None,
                 quant_algo=None, quant_crossover_kb=None,
                 quant_impl=None, capture_hlo=True, loss_name=None):
        from paddle_tpu.fluid import flags as _flags

        self.program = program
        self.mesh = mesh
        self.policy = policy or gspecs.DataParallelPolicy()
        self.feed_specs = dict(feed_specs or {})
        self._default_scope = scope
        # graph-optimization passes (FLAGS_graph_passes) BEFORE the
        # health transpile and any compile — the program stays free of
        # collective ops (the pass layer only rewrites compute
        # subgraphs), so the "zero c_allreduce in program" contract of
        # this lane is untouched
        from paddle_tpu import passes as _graph_passes

        _graph_passes.apply_graph_passes(program, lane="gspmd",
                                         loss_name=loss_name)
        # health sentinel (FLAGS_health_sentinel, docs/DISTRIBUTED.md
        # §6): transpiled into the program BEFORE any compile — the
        # check lands in the optimizer leg (post-reduction, global
        # view), and the gspmd lane's in-graph gate rides wrap_body
        from paddle_tpu import health

        self._sentinel = health.attach(program, loss_name=loss_name,
                                       lane="gspmd")
        if quant_hook is None:
            quant_hook = _flags.flag("quant_allreduce")
        self.quant_hook = bool(quant_hook)
        self.quant_block_size = quant_block_size
        self.quant_algo = quant_algo
        self.quant_crossover_kb = quant_crossover_kb
        self.quant_impl = quant_impl
        self.capture_hlo = bool(capture_hlo)
        self._cache = {}
        self._ran_keys = set()
        self._step = 0

    # -- introspection -------------------------------------------------
    def describe_policy(self, scope=None):
        """The resolved ParamSpec table (specs.ShardingPolicy.describe)
        against the bound scope — what docs/DISTRIBUTED.md's policy table
        renders."""
        scope = self._resolve_scope(scope)
        return self.policy.describe(self.program, scope, self.mesh)

    def compiled_blocks(self):
        return list(self._cache.values())

    @property
    def last_hlo(self):
        for cb in self._cache.values():
            if cb.last_hlo:
                return cb.last_hlo
        return None

    # -- dispatch ------------------------------------------------------
    def _resolve_scope(self, scope):
        if scope is not None:
            return scope
        if self._default_scope is not None:
            return self._default_scope
        from paddle_tpu.fluid.executor import global_scope

        return global_scope()

    _prep = staticmethod(prep_feed)

    def run(self, scope=None, feed=None, fetch_list=None,
            return_numpy=True):
        scope = self._resolve_scope(scope)
        feed, fetch_names, feed_sig = self._prep(feed, fetch_list)
        key = (self.program._version, feed_sig, tuple(fetch_names))
        return self._dispatch(key, scope, feed, fetch_names, 1, False,
                              return_numpy)

    def run_steps(self, feed, n_steps, fetch_list=None, scope=None,
                  return_numpy=True, stacked_feed=False):
        """``n_steps`` partitioned steps in ONE jitted call — the
        fori_loop carries the policy-sharded params/opt-state on-device
        (the big-training scan-over-steps pattern), amortizing dispatch
        exactly like the classic lane's chain (fluid/executor.py
        run_steps).  stacked_feed=True: feed arrays carry a leading
        [n_steps] axis (replicated across the mesh), one slice per
        iteration.  Only the final step's fetches return."""
        scope = self._resolve_scope(scope)
        n = int(n_steps)
        if n < 1:
            raise ValueError(f"n_steps must be >= 1, got {n_steps!r}")
        feed, fetch_names, feed_sig = self._prep(feed, fetch_list)
        if stacked_feed:
            bad = {k: np.shape(v) for k, v in feed.items()
                   if not np.shape(v) or np.shape(v)[0] != n}
            if bad:
                raise ValueError(
                    f"stacked_feed arrays need a leading [{n}] axis; "
                    f"got {bad}")
        key = (self.program._version, feed_sig, tuple(fetch_names),
               "chain", n, bool(stacked_feed))
        return self._dispatch(key, scope, feed, fetch_names, n,
                              bool(stacked_feed), return_numpy)

    def _verify_preflight(self, feed, fetch_names, scope,
                          stacked_feed=False):
        """FLAGS_program_verify hook for the gspmd lane: the shared
        dataflow/shape families plus (mesh, policy, quant-hook)
        legality.  ProgramVerifyError propagates; analyzer crashes
        degrade to a warning (the executor must never die on its own
        diagnostics)."""
        from paddle_tpu.fluid import flags as _flags

        if str(_flags.flag("program_verify")).lower() in (
                "off", "0", "false", "none", ""):
            return
        import warnings

        from paddle_tpu import analysis

        feed_shapes, feed_dtypes = {}, {}
        for name, val in (feed or {}).items():
            shp = tuple(np.shape(val))
            if stacked_feed and shp:
                shp = shp[1:]  # leading dim is the step axis
            feed_shapes[name] = shp
            feed_dtypes[name] = str(getattr(val, "dtype", "") or "") or None
        try:
            analysis.preflight(
                self.program, lane="gspmd", mesh=self.mesh,
                policy=self.policy, quant_hook=self.quant_hook,
                feed_names=list((feed or {}).keys()),
                feed_shapes=feed_shapes, feed_dtypes=feed_dtypes,
                fetch_names=list(fetch_names or []),
                scope_keys=list(scope.keys()) if scope is not None else None)
        except analysis.ProgramVerifyError:
            raise
        except Exception as e:
            warnings.warn(f"program verification failed to run "
                          f"({type(e).__name__}: {e}) — continuing "
                          f"without preflight")

    def _dispatch(self, key, scope, feed, fetch_names, n_steps,
                  stacked_feed, return_numpy):
        import time as _time

        from paddle_tpu.fluid.executor import (_feed_batch, _m_cache,
                                               _m_compile_seconds,
                                               _record_step,
                                               _report_examples)

        sent = self._sentinel
        cb = self._cache.get(key)
        if cb is None:
            _m_cache().labels(path="gspmd", result="miss").inc()
            # static verification at the compile boundary: the gspmd
            # lane adds (mesh, policy, quant hook) legality on top of
            # the dataflow/shape families (FLAGS_program_verify)
            self._verify_preflight(feed, fetch_names, scope,
                                   stacked_feed=bool(stacked_feed))
            if sent is not None:
                sent.ensure_state(scope)  # before BlockPlan scope checks
            t0 = _time.perf_counter()  # observability: allow
            cb = _GSPMDBlock(self, scope, list(feed.keys()), fetch_names,
                             feed_shapes={k: tuple(np.shape(v))
                                          for k, v in feed.items()},
                             feed_dtypes={k: str(v.dtype)
                                          for k, v in feed.items()},
                             n_steps=n_steps, stacked_feed=stacked_feed)
            self._cache[key] = cb
            _m_compile_seconds().labels(
                path="gspmd", phase="trace").inc(_time.perf_counter() - t0)  # observability: allow
        else:
            _m_cache().labels(path="gspmd", result="hit").inc()
        def attempt():
            first_run = key not in self._ran_keys
            t0 = _time.perf_counter()  # observability: allow
            fetches = cb.run(scope, feed, self._step)
            step_s = _time.perf_counter() - t0  # observability: allow
            _record_step("gspmd", step_s, first_run)
            self._ran_keys.add(key)
            if cb.wire_bytes_per_step:
                from ..data_parallel import collective_payload_counter

                collective_payload_counter().labels(
                    collective="c_allreduce_quant").inc(
                    cb.wire_bytes_per_step * n_steps)
            if cb.fused_bytes_saved:
                from ..data_parallel import fused_update_bytes_counter

                fused_update_bytes_counter().inc(
                    cb.fused_bytes_saved * n_steps)
            # stacked_feed: leading feed axis is the step index, not batch
            batch = 0 if stacked_feed else _feed_batch(feed) * n_steps
            _report_examples("gspmd", batch, step_s)
            self._step += n_steps
            return fetches

        from paddle_tpu.health import run_guarded

        fetches = run_guarded(sent, scope, fetch_names, attempt,
                              chain=n_steps > 1)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches

    def cost_analysis(self, feed, fetch_list=None, scope=None):
        """XLA cost/memory analysis of an already-run signature — the
        shared _JitExecutable surface (pt_xla_* gauges included)."""
        scope = self._resolve_scope(scope)
        feed, fetch_names, feed_sig = self._prep(feed, fetch_list)
        cb = self._cache.get((self.program._version, feed_sig,
                              tuple(fetch_names)))
        if cb is None:
            raise ValueError(
                "no compiled GSPMD executable for this (feed, fetch_list) "
                "signature — run the step once first")
        return cb.cost_analysis(scope, feed)
