"""GSPMD-native sharding core (docs/DISTRIBUTED.md "GSPMD execution
core"): sharding policies over the named mesh, one jit-partitioned
executor, and the quantized-ring gradient hook — the subsystem the DP
and hybrid runners select policies over instead of rewriting programs."""

from . import specs  # noqa: F401
from .specs import (  # noqa: F401
    DataParallelPolicy,
    ParamSpec,
    ShardingPolicy,
    TensorParallelPolicy,
    Zero1Policy,
    policy_for,
)
from . import executor  # noqa: F401
from .executor import (  # noqa: F401
    GSPMDExecutor,
    hlo_collective_bytes,
    hlo_collective_counts,
    hlo_inventory,
)
from . import quant_hook  # noqa: F401
from .quant_hook import plan_quant_hook, resolve_quant_impl  # noqa: F401
from . import pipeline_policy  # noqa: F401
from .pipeline_policy import (  # noqa: F401
    PipelinePolicy,
    PipelinePlan,
    modeled_bubble_fraction,
    plan_pipeline,
    schedule_slots,
)
