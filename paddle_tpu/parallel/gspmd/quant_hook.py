"""Quantized gradient reduction INSIDE the partitioned graph.

The transpiler lane routes gradients through explicit `c_allreduce_quant`
ops.  The GSPMD executor inserts no collective ops at all — XLA reduces
gradients implicitly because the loss is a mean over the globally-sharded
batch — which would silently drop the EQuARX dual-int8 wire format
(arXiv:2506.17615) the comms lane depends on.  This module puts it back
without touching the program:

  **shard_map island** (the 0.4.3x-safe default): the executor splits the
  pruned op list at the gradient frontier (the last raw-gradient
  producer).  Forward+backward trace inside ONE `jax.shard_map` mapped
  over the batch axis, so each device computes the partial gradients of
  its local batch shard; the island scales them by 1/n (the transpiler's
  ScaleLossGradOp seed, applied at the boundary — backward is linear in
  the seed) and reduces the same-dtype concatenation through
  `kernels.ring_collectives.adaptive_quantized_all_reduce` — identical
  FLAGS_quant_allreduce semantics: block size, algorithm selection,
  crossover, `wire_bytes` accounting.  The optimizer leg then traces in
  global view where the policy's sharding specs (ZeRO-1) partition it.
  The island is manual partitioning embedded inside the jit-partitioned
  computation — exactly the "shard_map island" escape GSPMD reserves for
  collectives XLA cannot be trusted to pick.

  **custom_partitioning** (`FLAGS_gspmd_quant_impl=custom_partitioning`,
  ``auto`` selects it on TPU backends): the island instead emits the
  per-device partials STACKED over the batch axis, and the reduction is a
  `jnp.sum(axis=0)` carrying a `jax.custom_partitioning` rule whose
  per-device lowering is the quantized ring — GSPMD integrates (and can
  reschedule) the reduction like any other partitioned op.  Documented
  fallback: the jaxlib-0.4.3x XLA:CPU GSPMD lane miscompiles
  custom-partitioned calls (the same line that aborts multi-axis GSPMD,
  see tests/cpu_mesh.py), so ``auto`` never picks it off-TPU and a build
  failure demotes to the island with a warning.

Contract and limits (docs/DISTRIBUTED.md "GSPMD execution core"):

  - Applies to float gradients only; DGC-encoded gradients keep the
    exact fp32 psum (requantizing a top-k-sparse payload destroys it).
  - Demotes itself (warning) on policies that shard parameters over a
    non-batch axis: the island maps only the batch axis, so a
    model-split parameter would be materialized full-size per device —
    defeating tensor parallelism to quantize its gradient.
  - batch_norm running stats produced in the island are averaged across
    the axis (the transpiler's c_allreduce_avg semantics); other
    island-produced carries are computed from replicated inputs and
    leave as-is.
  - Fetches produced by the forward/backward stack per-device over the
    batch axis — the DataParallelRunner's FetchOpHandle convention, so
    loss parity gates compare like with like.
"""

from __future__ import annotations

import warnings

import numpy as np

from paddle_tpu.fluid.framework import grad_var_name  # noqa: F401 (doc ref)
from .. import mesh as pmesh

__all__ = ["QuantHookPlan", "plan_quant_hook", "resolve_quant_impl"]

_QUANT_IMPLS = ("auto", "shard_map", "custom_partitioning")


def resolve_quant_impl(impl=None):
    """Resolve FLAGS_gspmd_quant_impl: ``auto`` = custom_partitioning on
    TPU backends, the shard_map island everywhere else (the documented
    0.4.3x CPU fallback)."""
    if impl in (None, "auto"):
        from paddle_tpu.fluid import flags as _flags

        impl = _flags.flag("gspmd_quant_impl")
    if impl not in _QUANT_IMPLS:
        raise ValueError(
            f"gspmd_quant_impl must be one of {_QUANT_IMPLS}, got {impl!r}")
    if impl != "auto":
        return impl
    try:
        import jax

        return ("custom_partitioning" if jax.default_backend() == "tpu"
                else "shard_map")
    except Exception:
        return "shard_map"


class QuantHookPlan:
    """The executor-side compilation plan for one hooked program: the
    op-list split, the gradient/carry/fetch classification, and the
    modeled per-step wire bytes (booked by the executor on
    ``pt_collective_payload_bytes_total{collective="c_allreduce_quant"}``,
    the same family the transpiler path uses)."""

    def __init__(self, plan, program, mesh, axis, block_size, algo,
                 crossover_kb, impl, fused_update=None):
        self.plan = plan
        self.program = program
        self.mesh = mesh
        self.axis = axis
        self.n = int(mesh.shape[axis])
        self.block_size = int(block_size)
        self.algo = algo
        self.crossover_kb = crossover_kb
        self.impl = impl
        if fused_update is None:
            from paddle_tpu.fluid import flags as _flags

            fused_update = _flags.flag("fused_update")
        self.fused_update = bool(fused_update)
        # per-feed island in_spec axes, set by the executor from its
        # RESOLVED feed specs (feed_specs override > policy.feed_spec,
        # projected onto the batch axis — the only axis the island
        # maps); default: dim 0 on the batch axis
        self.feed_island_specs = {}
        self._classify()
        self._plan_fused_updates()
        self._model_wire_bytes()

    # -- planning ------------------------------------------------------
    def _classify(self):
        plan, program = self.plan, self.program
        block = plan.block
        raw = {g for _, g in getattr(program, "_params_grads", [])}
        if not raw:
            raw = {op.inputs["Grad"][0] for op in plan.ops
                   if op.attrs.get("op_role") == "optimize"
                   and "Grad" in op.inputs}
        # _dgc_encoded maps RAW grad name -> encoded var name; the raw
        # names are what `raw` holds here (no transpiler remap on this
        # lane), so exempt by KEY — values included for robustness
        # against a caller that pre-remapped
        dgc_map = getattr(program, "_dgc_encoded", {})
        dgc = set(dgc_map.keys()) | set(dgc_map.values())
        prod = {}
        for i, op in enumerate(plan.ops):
            for g in raw.intersection(op.output_arg_names):
                prod[g] = i
        if not prod:
            raise ValueError(
                "gspmd quant hook: program has no raw parameter "
                "gradients (forward-only or optimizer-less program)")
        self.cut = max(prod.values()) + 1
        self.ops_fwdbwd = plan.ops[: self.cut]
        self.ops_opt = plan.ops[self.cut:]
        produced1 = set()
        for op in self.ops_fwdbwd:
            produced1.update(op.output_arg_names)
        consumed2 = set()
        for op in self.ops_opt:
            consumed2.update(op.input_arg_names)
        self.grads = sorted(g for g in raw if g in produced1)
        self.exact_grads = [g for g in self.grads if g in dgc]
        quant = []
        for g in self.grads:
            v = block._find_var_recursive(g)
            dt = v.dtype if v is not None else None
            if g in dgc or dt not in ("float32", "float16", "bfloat16"):
                if g not in self.exact_grads:
                    self.exact_grads.append(g)
            else:
                quant.append(g)
        self.quant_grads = quant
        gset = set(self.grads)
        # values the optimizer leg (or the scope write-back / fetch
        # assembly) needs from the island, beyond the gradients
        self.carries = sorted(
            (consumed2 | set(plan.write_names)).intersection(produced1)
            - gset)
        # gradient fetches are NOT island fetches: the reduced gradient
        # is replicated, and fetching it from the post-reduction env
        # keeps the value (global mean) and shape identical across the
        # shard_map and custom_partitioning impls — stacking the
        # island-local value would return raw unscaled partials on the
        # cp impl, where reduction happens outside the island
        self.island_fetches = [n for n in plan.jit_fetch_names
                               if n in produced1 and n not in gset]
        # batch_norm running stats get the transpiler's c_allreduce_avg
        self.mean_carries = set()
        for op in self.ops_fwdbwd:
            if op.type == "batch_norm" and not op.attrs.get("is_test"):
                for slot in ("MeanOut", "VarianceOut"):
                    for n in op.outputs.get(slot, []):
                        if n in self.carries:
                            self.mean_carries.add(n)
        # scope vars the island stage reads (the optimizer leg reads
        # straight from the body's full scope_vals dict)
        reads1 = set()
        scope_vars = set(plan.donated_names) | set(plan.readonly_names)
        for op in self.ops_fwdbwd:
            reads1.update(set(op.input_arg_names) & scope_vars)
        self.scope_reads_island = sorted(reads1)

    # fused dequant→update→requant leg (the DP transpiler rewrite ported
    # to this lane, plan-level — the PROGRAM stays unrewritten, the
    # "zero c_allreduce ops in program" contract holds)
    _FUSED_OPT_TYPES = {"sgd": "fused_sgd_quant_grad",
                        "adam": "fused_adam_quant_grad",
                        "adamw": "fused_adamw_quant_grad",
                        "lamb": "fused_lamb_quant_grad",
                        "momentum": "fused_momentum_quant_grad"}
    FUSED_Q_HI = "@GSPMD_FUSED_Q@HI"
    FUSED_Q_LO = "@GSPMD_FUSED_Q@LO"
    FUSED_Q_SCALE = "@GSPMD_FUSED_Q@SCALE"

    def _plan_fused_updates(self):
        """FLAGS_fused_update on this lane: quant grads whose ONLY
        consumer is one sgd/adam/adamw/momentum op keep the reduced
        bucket in the wire format (``adaptive_quantized_all_reduce_keep``
        inside the island) and their optimizer ops are replaced — in the
        TRACE op list only, never the program — by the fused
        ``*_quant_grad`` forms that dequant their block slice inline.

        Demotions (each leaves the grad on the plain dequantized path):
        a second consumer (gradient clip, a health_check op covering raw
        grads — the sentinel's detection surface), a fetch of the grad,
        the custom_partitioning impl (its reducer returns one fp32
        tensor; the keep-quant form is island-only), 1-device axes, and
        alignment bloat past 2x the raw payload (the DP transpiler's
        sub-block guard)."""
        self.fused_grads = []
        self.plain_quant_grads = list(self.quant_grads)
        self.ops_opt_fused = list(self.ops_opt)
        self.fused_offsets = {}
        self.fused_elems = 0
        self.fused_bytes_saved = 0
        if (not self.fused_update or self.n <= 1
                or self.impl == "custom_partitioning"
                or not self.quant_grads):
            return
        from paddle_tpu.fluid.framework import Operator
        from paddle_tpu.kernels import fused_update as fu

        block = self.plan.block
        consumers = {}
        for op in self.plan.ops:
            for g in set(op.input_arg_names):
                if g in self.quant_grads:
                    consumers.setdefault(g, []).append(op)
        fetched = set(self.plan.jit_fetch_names)
        opt_ids = {id(op) for op in self.ops_opt}
        cand = []
        for g in self.quant_grads:
            cons = consumers.get(g, [])
            if (g not in fetched and len(cons) == 1
                    and id(cons[0]) in opt_ids
                    and cons[0].type in self._FUSED_OPT_TYPES
                    and cons[0].inputs.get("Grad") == [g]):
                cand.append((g, cons[0]))
        if not cand:
            return
        bs = self.block_size
        off, offsets, shapes = 0, {}, {}
        raw = 0
        for g, _op in cand:
            v = block._find_var_recursive(g)
            numel = int(np.prod(v.shape))
            shapes[g] = tuple(v.shape)
            offsets[g] = off // bs
            raw += numel
            off += numel + (-numel) % bs
        if off > 2 * raw:
            return  # alignment bloat: keep the plain path (DP guard)
        rewritten = {}
        for g, op in cand:
            inputs = {slot: list(names) for slot, names in op.inputs.items()
                      if slot != "Grad"}
            inputs["QHi"] = [self.FUSED_Q_HI]
            inputs["QLo"] = [self.FUSED_Q_LO]
            inputs["QScale"] = [self.FUSED_Q_SCALE]
            attrs = dict(op.attrs)
            attrs.update(offset_blocks=int(offsets[g]),
                         numel=int(np.prod(shapes[g])),
                         block_size=int(bs))
            rewritten[id(op)] = Operator(
                block, self._FUSED_OPT_TYPES[op.type], inputs=inputs,
                outputs={s: list(n) for s, n in op.outputs.items()},
                attrs=attrs)
        self.ops_opt_fused = [rewritten.get(id(op), op)
                              for op in self.ops_opt]
        self.fused_grads = [g for g, _op in cand]
        self.plain_quant_grads = [g for g in self.quant_grads
                                  if g not in set(self.fused_grads)]
        self.fused_offsets = offsets
        self.fused_elems = off
        self.fused_bytes_saved = fu.bytes_saved(off)

    def _model_wire_bytes(self):
        from paddle_tpu.kernels import quantized_collectives as qc
        from paddle_tpu.kernels.ring_collectives import select_allreduce_algo

        block = self.plan.block
        total, buckets = 0, []
        if self.n > 1:
            elems = 0
            for g in self.plain_quant_grads:
                v = block._find_var_recursive(g)
                if v is not None and v.shape and not any(
                        d is None or d < 0 for d in v.shape):
                    elems += int(np.prod(v.shape))
            for nelems, fused in ((elems, False),
                                  (self.fused_elems, True)):
                if not nelems:
                    continue
                resolved = select_allreduce_algo(
                    nelems, self.n, algo=self.algo,
                    crossover_kb=self.crossover_kb,
                    block_size=self.block_size)
                total += qc.wire_bytes(nelems, block_size=self.block_size,
                                       n_devices=self.n, algo=resolved)
                buckets.append({"elements": nelems, "algo": resolved,
                                "fused_update": fused})
        self.wire_bytes_per_step = total
        self.bucket_report = buckets

    # -- the reduction -------------------------------------------------
    def _reduce_quant_bucket(self, env):
        """Concatenate the plain (non-fused) quantizable gradients into
        one bucket — the fuse_all_reduce analog at trace level — scale
        by 1/n, reduce on the adaptive dual-int8 ring, split back."""
        import jax.numpy as jnp

        from paddle_tpu.kernels.ring_collectives import (
            adaptive_quantized_all_reduce)

        if not self.plain_quant_grads:
            return
        shapes = [jnp.shape(env[g]) for g in self.plain_quant_grads]
        flat = jnp.concatenate(
            [jnp.ravel(env[g]).astype(jnp.float32)
             for g in self.plain_quant_grads]) / self.n
        red = adaptive_quantized_all_reduce(
            flat, self.axis, block_size=self.block_size,
            algo=self.algo or "auto", crossover_kb=self.crossover_kb)
        off = 0
        for g, s in zip(self.plain_quant_grads, shapes):
            size = int(np.prod(s)) if s else 1
            env[g] = red[off:off + size].reshape(s).astype(env[g].dtype)
            off += size

    def _reduce_fused_bucket(self, env):
        """Reduce the fused-update bucket KEEPING the wire format: each
        member pads to a block boundary (the dequant_slice layout the
        rewritten optimizer ops address by ``offset_blocks``), the
        concatenation scales by 1/n and rides
        ``adaptive_quantized_all_reduce_keep`` — the reduced fp32 bucket
        never materializes (the DP lane's ``c_allreduce_quant_keep``
        semantics, at trace level)."""
        import jax.numpy as jnp

        from paddle_tpu.kernels.ring_collectives import (
            adaptive_quantized_all_reduce_keep)

        if not self.fused_grads:
            return {}
        bs = self.block_size
        parts = []
        for g in self.fused_grads:
            flat = jnp.ravel(env[g]).astype(jnp.float32)
            pad = (-flat.size) % bs
            if pad:
                flat = jnp.pad(flat, (0, pad))
            parts.append(flat)
        bucket = jnp.concatenate(parts) / self.n
        hi, lo, sc = adaptive_quantized_all_reduce_keep(
            bucket, self.axis, block_size=bs, algo=self.algo or "auto",
            crossover_kb=self.crossover_kb)
        return {self.FUSED_Q_HI: hi, self.FUSED_Q_LO: lo,
                self.FUSED_Q_SCALE: sc}

    def _reduce_exact(self, env):
        from jax import lax

        for g in self.exact_grads:
            # exact fp32 mean for payloads the wire format must not
            # touch (DGC-encoded, non-float) — transpiler parity
            env[g] = lax.psum(env[g] / self.n, self.axis)  # collective: allow

    def _average_carries(self, env):
        from jax import lax

        for n in self.mean_carries:
            # batch_norm running stats: the transpiler's c_allreduce_avg
            env[n] = lax.pmean(env[n], self.axis)  # collective: allow

    # -- body construction ----------------------------------------------
    def island_body(self, trace_stage):
        """Build fn(scope_vals, feeds, step) -> (carry, grads, stacked
        fetches) where the forward+backward trace runs under shard_map
        over the batch axis and gradients leave reduced (shard_map impl)
        or as ONE stacked partial bucket (custom_partitioning impl — the
        same concatenated bucket the island impl and the wire-bytes
        model use, so the metric books what actually moves).
        ``trace_stage(env, step, ops)`` is the executor's trace callback
        (one LowerContext assembly point, shared with the global-view
        stage)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        axis, n = self.axis, self.n
        cp = self.impl == "custom_partitioning" and n > 1
        carries = self.carries
        fused = set(self.fused_grads)
        # fused grads leave as the wire triple, never as fp32 tensors
        gset = [g for g in self.grads if g not in fused]
        fetches = self.island_fetches
        # the trace records each quant grad's (shape, dtype) here so the
        # post-island bucket split (with_cp_reduce below, traced strictly
        # AFTER the island in the same jit trace) can restore them
        meta = {}

        def island(scope_vals, feeds, step):
            env = dict(scope_vals)
            env.update(feeds)
            trace_stage(env, step, self.ops_fwdbwd, mesh_axes=(axis,))
            if cp:
                # exact grads leave as raw [1, ...] partials (the
                # P(axis) out_spec CONCATENATES on dim 0, so the stacked
                # global is [n, ...] and a plain sum is the exact fp32
                # reduction); quant grads leave as ONE flat [1, total]
                # bucket the custom_partitioning sum reduces on the ring
                grads = {g: jnp.reshape(env[g],
                                        (1,) + tuple(jnp.shape(env[g])))
                         for g in self.exact_grads}
                bucket = None
                if self.quant_grads:
                    meta["quant"] = [(jnp.shape(env[g]), env[g].dtype)
                                     for g in self.quant_grads]
                    bucket = jnp.reshape(jnp.concatenate(
                        [jnp.ravel(env[g]).astype(jnp.float32)
                         for g in self.quant_grads]), (1, -1))
            else:
                self._reduce_quant_bucket(env)
                self._reduce_exact(env)
                grads = {g: env[g] for g in gset}
                bucket = None
                fusedq = self._reduce_fused_bucket(env)
            self._average_carries(env)
            carry = {c: env[c] for c in carries if c in env}
            stacked = [jnp.reshape(env[f], (1,) + tuple(jnp.shape(env[f])))
                       if jnp.ndim(env[f]) == 0 else env[f]
                       for f in fetches]
            if cp:
                fusedq = {}
            return carry, grads, bucket, fusedq, stacked

        in_specs = (
            {nme: P() for nme in self.scope_reads_island},
            # honor the executor's resolved feed placement, projected
            # onto the batch axis: a feed the user declared replicated
            # (a shared table) enters the island WHOLE, not sliced
            {nme: P(*self.feed_island_specs.get(nme, (axis,)))
             for nme in self.plan.feed_names},
            P(),
        )
        grad_names = self.exact_grads if cp else gset
        bucket_spec = P(axis) if (cp and self.quant_grads) else None
        # the keep-quant wire triple is replica-identical post-reduction
        fusedq_names = ((self.FUSED_Q_HI, self.FUSED_Q_LO,
                         self.FUSED_Q_SCALE)
                        if (not cp and self.fused_grads) else ())
        out_specs = ({c: P() for c in carries},
                     {g: (P(axis) if cp else P()) for g in grad_names},
                     bucket_spec,
                     {nme: P() for nme in fusedq_names},
                     [P(axis) for _ in fetches])
        mapped = jax.shard_map(island, mesh=self.mesh, in_specs=in_specs,
                               out_specs=out_specs, check_vma=False)
        if not cp:
            def plain(scope_vals, feeds, step):
                carry, grads, _bucket, fusedq, stacked = mapped(
                    scope_vals, feeds, step)
                return carry, grads, fusedq, stacked

            return plain

        reduce_quant, is_quant = _cp_sum_reducer(
            self.mesh, axis, self.block_size, self.algo,
            self.crossover_kb)
        if not is_quant:
            # demoted to XLA's fp32 all-reduce (warned inside the
            # builder): the modeled int8 bytes must NOT book — this
            # metric exists precisely to expose silent fp32 wire traffic
            self.wire_bytes_per_step = 0
            self.bucket_report = []

        def with_cp_reduce(scope_vals, feeds, step):
            carry, grads, bucket, _fusedq, stacked = mapped(
                scope_vals, feeds, step)
            # exact grads: stacked partials [n, ...] — sum is the exact
            # fp32 reduction, scale folded in
            out = {g: jnp.sum(v, axis=0) / n for g, v in grads.items()}
            if bucket is not None:
                red = reduce_quant(bucket / n)  # [total], ring-reduced
                off = 0
                for g, (shape, dtype) in zip(self.quant_grads,
                                             meta["quant"]):
                    size = int(np.prod(shape)) if shape else 1
                    out[g] = red[off:off + size].reshape(shape) \
                        .astype(dtype)
                    off += size
            return carry, out, {}, stacked

        return with_cp_reduce


def _cp_sum_reducer(mesh, axis, block_size, algo, crossover_kb):
    """`jnp.sum(x, axis=0)` over shard-stacked partials, carrying a
    `jax.custom_partitioning` rule whose per-device lowering is the
    dual-int8 adaptive ring — the TPU-native spelling of the hook.
    Returns ``(reducer, is_quant)``: falls back to the plain sum (XLA's
    own fp32 all-reduce, ``is_quant=False`` so the caller zeroes the
    modeled int8 bytes) with a warning when the toolchain cannot build
    the rule (the documented 0.4.3x path never reaches here:
    resolve_quant_impl keeps ``auto`` on the island off-TPU)."""
    import jax.numpy as jnp

    from paddle_tpu import jax_compat

    cp = jax_compat.get_custom_partitioning()
    if cp is None:
        warnings.warn(
            "jax.custom_partitioning unavailable on this toolchain; "
            "gspmd quant hook falling back to XLA's fp32 all-reduce for "
            "the reduction (set FLAGS_gspmd_quant_impl=shard_map for the "
            "int8 island)")
        return (lambda x: jnp.sum(x, axis=0)), False

    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.kernels.ring_collectives import (
        adaptive_quantized_all_reduce)

    @cp
    def qsum(x):
        return jnp.sum(x, axis=0)

    def _infer(mesh_, arg_shapes, result_shape):
        return NamedSharding(mesh, P())

    def _partition(mesh_, arg_shapes, result_shape):
        arg_sh = (NamedSharding(mesh, P(axis)),)
        res_sh = NamedSharding(mesh, P())

        def lower_fn(x):
            local = jnp.sum(x, axis=0)  # this shard's partial(s)
            return adaptive_quantized_all_reduce(
                local, axis, block_size=block_size, algo=algo or "auto",
                crossover_kb=crossover_kb)

        return mesh, lower_fn, res_sh, arg_sh

    try:
        qsum.def_partition(partition=_partition,
                           infer_sharding_from_operands=_infer)
        return qsum, True
    except Exception as e:  # toolchain-specific signature drift
        warnings.warn(
            f"custom_partitioning rule construction failed ({e}); gspmd "
            "quant hook falling back to XLA's fp32 all-reduce — set "
            "FLAGS_gspmd_quant_impl=shard_map for the int8 island")
        return (lambda x: jnp.sum(x, axis=0)), False


def plan_quant_hook(plan, program, mesh, policy, block_size=None,
                    algo=None, crossover_kb=None, impl=None):
    """Build the QuantHookPlan for one compilation, or None when the hook
    must stay off: 1-device batch axis (nothing to reduce), a policy that
    shards parameters over a non-batch axis (island would defeat TP), or
    a program without raw gradients.  Demotions warn — silent fp32 wire
    traffic is the failure mode this hook exists to prevent."""
    from paddle_tpu.fluid import flags as _flags

    axis = policy.batch_axis
    if axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return None
    if policy.uses_model_axis(program, mesh):
        warnings.warn(
            "gspmd quant hook demoted: the policy shards parameters over "
            "a non-batch axis and the hook's island maps only the batch "
            "axis — gradient reduction stays on XLA's fp32 collectives")
        return None
    if block_size is None:
        block_size = _flags.flag("quant_allreduce_block_size")
    if algo is None:
        algo = _flags.flag("quant_allreduce_algo")
    if crossover_kb is None:
        crossover_kb = _flags.flag("quant_allreduce_crossover_kb")
    try:
        return QuantHookPlan(plan, program, mesh, axis, block_size, algo,
                             crossover_kb, resolve_quant_impl(impl))
    except ValueError as e:
        warnings.warn(f"gspmd quant hook demoted: {e}")
        return None
