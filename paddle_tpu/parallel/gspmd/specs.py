"""Sharding policies: program variables → PartitionSpecs over the named mesh.

The transpiler lane (`parallel/data_parallel.py`) expresses parallelism
as a graph rewrite — clone the loss seed, insert one collective op per
gradient.  This module is the GSPMD-native inverse: a *policy* maps every
program variable (parameters, optimizer state, feeds, selected
activations) to a `jax.sharding.PartitionSpec` over the named mesh
(`parallel/mesh.py`), and the partitioned executor
(`parallel/gspmd/executor.py`) hands those specs to `jax.jit` as
in/out shardings plus `with_sharding_constraint` annotations.  XLA's
SPMD partitioner then inserts every collective itself — the reference's
multi_devices_graph_pass, fuse_all_reduce and coalesce passes all
disappear into the sharding spec (SNIPPETS.md [1]–[3] pattern).

Policies are deliberately thin, so the runners stay thin too:

  ``DataParallelPolicy``   params/state replicated, feeds batch-sharded —
                           gradient averaging falls out of the global-view
                           mean over the sharded batch.
  ``Zero1Policy``          + optimizer-state vars dp-sharded on dim 0
                           (cross-replica weight-update sharding,
                           arXiv:2004.13336): XLA keeps each replica's
                           shard resident and all-gathers the updated
                           parameters because the spec says so — nothing
                           is hand-rolled.
  ``TensorParallelPolicy`` + 2-D (batch, model) layout: parameter specs
                           come from a `ShardingRule` (Megatron
                           column/row split on the model axis by
                           default) and matmul activations get
                           with_sharding_constraint annotations derived
                           from the weight layout.

Axis names accept both the canonical short forms (``dp``/``mp``) and the
paper spellings (``batch``/``model``) via `mesh.canonical_axis`.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .. import mesh as pmesh

__all__ = [
    "ParamSpec",
    "ShardingPolicy",
    "DataParallelPolicy",
    "Zero1Policy",
    "TensorParallelPolicy",
    "policy_for",
    "named_sharding",
    "constrain",
]


@dataclasses.dataclass(frozen=True)
class ParamSpec:
    """One variable's resolved placement: the PartitionSpec axes (tuple of
    mesh-axis names / None per tensor dim) plus the role the policy
    assigned it — the policy table docs/DISTRIBUTED.md renders."""

    name: str
    spec: tuple
    role: str  # "param" | "opt_state" | "feed" | "activation" | "misc"


def _canon_spec(spec):
    return tuple(pmesh.canonical_axis(a) for a in (spec or ()))


def _fits(spec, shape, mesh):
    """Drop axes the mesh lacks and axes that do not evenly divide the
    dim (the ShardingRule.spec_for gates, shared here so every policy
    protects scalar accumulators the same way)."""
    spec = _canon_spec(spec)
    if shape is not None:
        spec = spec[: len(shape)] + (None,) * max(0, len(shape) - len(spec))
    out = []
    for d, a in enumerate(spec):
        if a is None or mesh is None or a not in mesh.axis_names:
            out.append(None)
            continue
        if shape is not None and (shape[d] is None or shape[d] < 0
                                  or shape[d] % mesh.shape[a] != 0):
            out.append(None)
            continue
        out.append(a)
    return tuple(out)


def named_sharding(mesh, spec):
    """`NamedSharding(mesh, PartitionSpec(*spec))` with axis aliases
    resolved — the ONE place the gspmd layer mints shardings (the
    collectives lint sanctions exactly this module)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(*_canon_spec(spec)))


def constrain(value, mesh, spec):
    """`with_sharding_constraint` through the sanctioned surface: pins
    ``value``'s layout inside a jit-partitioned computation so GSPMD
    propagates from an annotation instead of guessing.  Identity outside
    a trace-compatible context (1-device mesh still fine)."""
    import jax

    return jax.lax.with_sharding_constraint(value,
                                            named_sharding(mesh, spec))


class ShardingPolicy:
    """Base policy: everything replicated except feeds (batch-sharded on
    dim 0).  Subclasses override `param_spec` / `state_spec` /
    `activation_constraints`; the executor only ever calls the public
    trio plus `describe()`."""

    name = "replicated"

    def __init__(self, batch_axis=pmesh.DATA_AXIS):
        self.batch_axis = pmesh.canonical_axis(batch_axis)

    # -- variable classification -------------------------------------
    def param_spec(self, program, name, shape, mesh):
        """Spec for a scope-resident variable (parameter, optimizer
        state, BN stat, lr).  Default: replicated."""
        return ()

    def feed_spec(self, program, name, shape, mesh):
        """Spec for a fed batch: dim 0 over the batch axis when present
        and divisible."""
        if self.batch_axis not in mesh.axis_names:
            return ()
        return _fits((self.batch_axis,), shape, mesh)

    def activation_constraints(self, program, mesh):
        """{var name: spec} with_sharding_constraint annotations applied
        at the producing op during the trace.  Default: none — GSPMD
        propagation decides."""
        return {}

    # -- introspection -----------------------------------------------
    def uses_model_axis(self, program, mesh):
        """True when any parameter spec touches a non-batch mesh axis —
        the quant hook demotes itself on such policies (its island maps
        only the batch axis, see quant_hook.py)."""
        return False

    def describe(self, program, scope, mesh):
        """Resolved ParamSpec table for every scope-read variable — the
        policy surface docs/DISTRIBUTED.md documents and tests assert."""
        out = []
        block = program.global_block()
        for name in sorted(scope.keys()):
            v = block._find_var_recursive(name)
            if v is None:
                continue
            val = scope.get(name)
            shape = tuple(np.shape(val)) if val is not None else None
            role = ("opt_state" if getattr(v, "is_optimizer_state", False)
                    else "param")
            out.append(ParamSpec(name,
                                 self.param_spec(program, name, shape,
                                                 mesh), role))
        return out


class DataParallelPolicy(ShardingPolicy):
    """Pure DP: parameters and optimizer state replicated, feeds sharded
    over the batch axis.  The loss mean over the globally-sharded batch
    makes XLA insert the gradient all-reduce — no seed scaling, no
    c_allreduce ops (the global-view property parallel/hybrid.py
    documents)."""

    name = "dp"


class Zero1Policy(DataParallelPolicy):
    """DP + ZeRO stage 1: optimizer-state vars (tagged
    ``is_optimizer_state`` by Optimizer._add_accumulator) shard dim 0
    over the batch axis when divisible.  The weight-update all-gather and
    the moment-shard residency both FALL OUT of this spec — XLA sees
    sharded moments feeding a replicated ParamOut and partitions the
    optimizer ops accordingly (arXiv:2004.13336 §4 as a sharding
    annotation)."""

    name = "zero1"

    def param_spec(self, program, name, shape, mesh):
        if self.batch_axis not in mesh.axis_names:
            return ()
        if mesh.shape[self.batch_axis] <= 1 or not shape:
            return ()
        v = program.global_block()._find_var_recursive(name)
        if v is None or not getattr(v, "is_optimizer_state", False):
            return ()
        return _fits((self.batch_axis,), shape, mesh)


class TensorParallelPolicy(ShardingPolicy):
    """2-D (batch, model) layout: parameter placement delegated to a
    `ShardingRule` (megatron_rules() when None — QKV/FFN-in columns on
    the model axis, FFN-out/attention-out rows, embeddings vocab-split),
    optionally composed with the ZeRO-1 state sharding for parameters
    the rules leave replicated.  Matmul activations whose weight is
    column-split get a with_sharding_constraint pinning their last dim to
    the model axis, so GSPMD's propagation is anchored where it matters
    instead of inferred."""

    name = "tp2d"

    def __init__(self, rules=None, zero_stage=0,
                 batch_axis=pmesh.DATA_AXIS, model_axis=pmesh.MODEL_AXIS):
        super().__init__(batch_axis=batch_axis)
        if rules is None:
            from ..hybrid import megatron_rules

            rules = megatron_rules()
        self.rules = rules
        self.model_axis = pmesh.canonical_axis(model_axis)
        self.zero_stage = int(zero_stage)
        self._zero = Zero1Policy(batch_axis=batch_axis)

    def param_spec(self, program, name, shape, mesh):
        spec = self.rules.spec_for(name, shape=shape, mesh=mesh)
        spec = _fits(spec, shape, mesh)
        if any(spec):
            return spec
        if self.zero_stage >= 1:
            return self._zero.param_spec(program, name, shape, mesh)
        return ()

    def uses_model_axis(self, program, mesh):
        block = program.global_block()
        for name, v in block.vars.items():
            shape = tuple(v.shape) if v.shape else None
            if any(a and a != self.batch_axis
                   for a in self.param_spec(program, name, shape, mesh)):
                return True
        return False

    # ops whose Y operand is a weight the rules split — their output
    # inherits the split (column-parallel) or completes a row-parallel
    # contraction (output replicated after XLA's implicit reduce)
    _MATMUL_OPS = ("mul", "matmul", "matmul_v2")

    def activation_constraints(self, program, mesh):
        if self.model_axis not in mesh.axis_names \
                or mesh.shape[self.model_axis] <= 1:
            return {}
        block = program.global_block()
        out = {}
        for op in block.ops:
            if op.type not in self._MATMUL_OPS:
                continue
            w = (op.inputs.get("Y") or [None])[0]
            outs = op.outputs.get("Out") or []
            if w is None or not outs:
                continue
            v = block._find_var_recursive(w)
            shape = tuple(v.shape) if (v is not None and v.shape) else None
            spec = self.param_spec(program, w, shape, mesh)
            if len(spec) < 2:
                continue
            ov = block._find_var_recursive(outs[0])
            orank = len(ov.shape) if (ov is not None and ov.shape) else 2
            if spec[-1] == self.model_axis:
                # column-parallel: activation's feature dim is split
                out[outs[0]] = ((None,) * (orank - 1)
                                + (self.model_axis,))
            elif spec[0] == self.model_axis:
                # row-parallel: the contraction reduces over the split
                # dim — the output is full-size once XLA psums it
                out[outs[0]] = (None,) * orank
        return out


def policy_for(mesh, rules=None, zero_stage=0, batch_axis=None):
    """The runners' thin policy selection: a >1 ``pp`` mesh axis →
    PipelinePolicy (stage assignment from the program's PipelineOptimizer
    metadata, inner policy selected recursively for the remaining axes);
    else a >1 non-batch mesh axis or a non-empty `ShardingRule` →
    TensorParallelPolicy; else zero_stage >= 1 → Zero1Policy; else pure
    DP.  One decision point so the DP and hybrid runners cannot drift
    (both call this).  An EMPTY rule set on a batch-only mesh
    deliberately does NOT select the TP policy — its per-var regex scan
    would run for nothing."""
    batch_axis = pmesh.canonical_axis(batch_axis or pmesh.DATA_AXIS)
    pipe = pmesh.PIPE_AXIS
    has_pipe = pipe in mesh.axis_names and mesh.shape[pipe] > 1
    has_model_axis = any(a not in (batch_axis, pipe) and mesh.shape[a] > 1
                         for a in mesh.axis_names)
    has_rules = rules is not None and bool(getattr(rules, "_rules", True))
    if has_model_axis or has_rules:
        inner = TensorParallelPolicy(rules=rules, zero_stage=zero_stage,
                                     batch_axis=batch_axis)
    elif zero_stage >= 1:
        inner = Zero1Policy(batch_axis=batch_axis)
    else:
        inner = DataParallelPolicy(batch_axis=batch_axis)
    if has_pipe:
        from .pipeline_policy import PipelinePolicy

        return PipelinePolicy(inner=inner, zero_stage=zero_stage,
                              batch_axis=batch_axis)
    return inner
