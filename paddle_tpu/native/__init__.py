"""Native (C++) data runtime bindings.

The reference implements its data path in C++ (recordio/, data_feed.cc,
lod_tensor_blocking_queue.h) so ingestion never blocks the training loop on
the Python GIL.  This package does the same for the TPU build: a small C++
shared library (src/data_runtime.cc) provides RecordIO, a blocking queue,
and a MultiSlot text-feed parser with a background reader thread; Python
binds it with ctypes (no pybind11 in this image).

The library is compiled on first use with g++ (cached next to the source,
keyed by source hash) — the moral equivalent of the reference's cmake step,
but zero-config for users.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
import time

import numpy as np

__all__ = ["lib", "RecordIOWriter", "RecordIOScanner", "BlockingQueue",
           "MultiSlotFeed", "NativePredictor", "is_available",
           "PSError", "PSConnectionError", "PSServerError",
           "PSTimeoutError"]

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_SRCS = [os.path.join(_SRC_DIR, "data_runtime.cc"),
         os.path.join(_SRC_DIR, "ps_runtime.cc"),
         os.path.join(_SRC_DIR, "infer_runtime.cc")]
# base compile flags shared with the C++ unit-test build (tests/test_native_cc.py)
CXX_BASE_FLAGS = ["-O2", "-std=c++17", "-pthread"]
_lib = None
_lib_lock = threading.Lock()
_build_error = None


def _build() -> str:
    h = hashlib.sha256()
    for src in (*_SRCS, os.path.join(_SRC_DIR, "native_api.h")):
        with open(src, "rb") as f:
            h.update(f.read())
    tag = h.hexdigest()[:16]
    out_dir = os.path.join(os.path.dirname(__file__), "_build")
    os.makedirs(out_dir, exist_ok=True)
    so_path = os.path.join(out_dir, f"libptq_data_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    # per-process tmp name: concurrent first-use builds (pytest-xdist, two
    # jobs) must not interleave writes to the same output file
    tmp = f"{so_path}.{os.getpid()}.tmp"
    cmd = ["g++", *CXX_BASE_FLAGS, "-shared", "-fPIC", *_SRCS,
           "-lz", "-o", tmp]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so_path)
    return so_path


def lib():
    """Load (building if needed) the native library; raises on failure."""
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            raise _build_error
        try:
            path = _build()
            L = ctypes.CDLL(path)
        except Exception as e:  # remember: don't retry the build every call
            _build_error = RuntimeError(f"native data runtime build failed: {e}")
            raise _build_error
        L.ptq_free.argtypes = [ctypes.c_char_p]
        L.ptq_recordio_writer_open.restype = ctypes.c_void_p
        L.ptq_recordio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        L.ptq_recordio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                                ctypes.c_int64]
        L.ptq_recordio_writer_close.argtypes = [ctypes.c_void_p]
        L.ptq_recordio_scanner_open.restype = ctypes.c_void_p
        L.ptq_recordio_scanner_open.argtypes = [ctypes.c_char_p]
        L.ptq_recordio_scanner_next.restype = ctypes.c_int64
        L.ptq_recordio_scanner_next.argtypes = [ctypes.c_void_p,
                                                ctypes.POINTER(ctypes.c_void_p)]
        L.ptq_recordio_scanner_close.argtypes = [ctypes.c_void_p]
        L.ptq_queue_new.restype = ctypes.c_void_p
        L.ptq_queue_new.argtypes = [ctypes.c_int64]
        L.ptq_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64, ctypes.c_double]
        L.ptq_queue_pop.restype = ctypes.c_int64
        L.ptq_queue_pop.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_void_p),
                                    ctypes.c_double]
        L.ptq_queue_size.restype = ctypes.c_int64
        L.ptq_queue_size.argtypes = [ctypes.c_void_p]
        L.ptq_queue_waiters.restype = ctypes.c_int64
        L.ptq_queue_waiters.argtypes = [ctypes.c_void_p]
        L.ptq_queue_close.argtypes = [ctypes.c_void_p]
        L.ptq_queue_free.argtypes = [ctypes.c_void_p]
        L.ptq_feed_new.restype = ctypes.c_void_p
        L.ptq_feed_new.argtypes = [ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
                                   ctypes.c_char_p, ctypes.c_int, ctypes.c_int64,
                                   ctypes.c_int]
        L.ptq_feed_next.restype = ctypes.c_int64
        L.ptq_feed_next.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_void_p)]
        L.ptq_feed_error.restype = ctypes.c_int64
        L.ptq_feed_error.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_void_p)]
        L.ptq_feed_free.argtypes = [ctypes.c_void_p]
        # --- native inference runtime (infer_runtime.cc) ---
        L.pti_create.restype = ctypes.c_void_p
        L.pti_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        L.pti_error.restype = ctypes.c_char_p
        L.pti_error.argtypes = [ctypes.c_void_p]
        L.pti_num_inputs.restype = ctypes.c_int
        L.pti_num_inputs.argtypes = [ctypes.c_void_p]
        L.pti_input_name.restype = ctypes.c_char_p
        L.pti_input_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        L.pti_num_outputs.restype = ctypes.c_int
        L.pti_num_outputs.argtypes = [ctypes.c_void_p]
        L.pti_output_name.restype = ctypes.c_char_p
        L.pti_output_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        L.pti_set_input.restype = ctypes.c_int
        L.pti_set_input.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_int64),
                                    ctypes.c_int, ctypes.c_int]
        L.pti_run.restype = ctypes.c_int
        L.pti_run.argtypes = [ctypes.c_void_p]
        L.pti_get_output.restype = ctypes.c_int64
        L.pti_get_output.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_void_p),
                                     ctypes.POINTER(ctypes.POINTER(
                                         ctypes.c_int64)),
                                     ctypes.POINTER(ctypes.c_int),
                                     ctypes.POINTER(ctypes.c_int)]
        L.pti_free.argtypes = [ctypes.c_void_p]
        # --- parameter-server transport (ps_runtime.cc) ---
        L.pts_server_start.restype = ctypes.c_void_p
        L.pts_server_start.argtypes = [ctypes.c_int, ctypes.c_int]
        L.pts_server_port.restype = ctypes.c_int
        L.pts_server_port.argtypes = [ctypes.c_void_p]
        L.pts_server_set_barrier_timeout_ms.argtypes = [ctypes.c_void_p,
                                                        ctypes.c_int]
        L.pts_server_enable_elastic.argtypes = [ctypes.c_void_p,
                                                ctypes.c_int]
        L.pts_server_drain_spans.restype = ctypes.c_int64
        L.pts_server_drain_spans.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64]
        L.pts_server_stat.restype = ctypes.c_int64
        L.pts_server_stat.argtypes = [ctypes.c_void_p, ctypes.c_int]
        L.pts_server_reconcile_committed.restype = ctypes.c_int
        L.pts_server_reconcile_committed.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64]
        L.pts_server_wait_round.restype = ctypes.c_int
        L.pts_server_wait_round.argtypes = [ctypes.c_void_p]
        L.pts_server_grad_count.restype = ctypes.c_int64
        L.pts_server_grad_count.argtypes = [ctypes.c_void_p]
        L.pts_server_grad_at.restype = ctypes.c_int64
        L.pts_server_grad_at.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                         ctypes.POINTER(ctypes.c_void_p),
                                         ctypes.POINTER(ctypes.c_void_p)]
        L.pts_server_grad_name_len.restype = ctypes.c_int64
        L.pts_server_grad_name_len.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        L.pts_server_pop_grad.restype = ctypes.c_int64
        L.pts_server_pop_grad.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.POINTER(ctypes.c_void_p),
                                          ctypes.POINTER(ctypes.c_void_p)]
        L.pts_server_publish.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_char_p, ctypes.c_int64]
        L.pts_server_bump_version.argtypes = [ctypes.c_void_p]
        L.pts_server_release_send.argtypes = [ctypes.c_void_p]
        L.pts_server_end_round.restype = ctypes.c_int
        L.pts_server_end_round.argtypes = [ctypes.c_void_p]
        L.pts_server_table_get.restype = ctypes.c_int64
        L.pts_server_table_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                           ctypes.POINTER(ctypes.c_void_p)]
        L.pts_server_wait_table.restype = ctypes.c_int
        L.pts_server_wait_table.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.pts_server_save.restype = ctypes.c_int
        L.pts_server_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.pts_server_load.restype = ctypes.c_int
        L.pts_server_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.pts_server_stop.argtypes = [ctypes.c_void_p]
        L.pts_connect.restype = ctypes.c_void_p
        L.pts_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_double]
        L.pts_request.restype = ctypes.c_int
        L.pts_request.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                  ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_uint64,
                                  ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.POINTER(ctypes.c_void_p),
                                  ctypes.POINTER(ctypes.c_int64)]
        L.pts_client_close.argtypes = [ctypes.c_void_p]
        _lib = L
        return _lib


def is_available() -> bool:
    try:
        lib()
        return True
    except Exception:
        return False


def _take(ptr, length, free=True):
    """Copy `length` bytes from a returned buffer into Python, freeing it."""
    data = ctypes.string_at(ptr, length)
    if free and length >= 0 and ptr:
        lib().ptq_free(ctypes.cast(ptr, ctypes.c_char_p))
    return data


class RecordIOWriter:
    """Chunked record file writer (reference recordio/writer.cc)."""

    def __init__(self, path, compressor=1):
        self._h = lib().ptq_recordio_writer_open(path.encode(), compressor)
        if not self._h:
            raise IOError(f"cannot open {path} for writing")

    def write(self, data: bytes):
        if not self._h:
            raise ValueError("writer is closed")
        rc = lib().ptq_recordio_writer_write(self._h, data, len(data))
        if rc != 0:
            raise IOError("recordio write failed")

    def close(self):
        if self._h:
            rc = lib().ptq_recordio_writer_close(self._h)
            self._h = None
            if rc != 0:
                raise IOError("recordio flush failed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        # a dropped writer must still flush its buffered chunk — silently
        # losing up to 1 MiB of records is worse than late IO in a finalizer
        try:
            self.close()
        except Exception:
            pass


class RecordIOScanner:
    """Iterates records of a RecordIO file (reference recordio/scanner.cc)."""

    def __init__(self, path):
        self._h = lib().ptq_recordio_scanner_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        if not self._h:
            raise StopIteration
        out = ctypes.c_void_p()
        n = lib().ptq_recordio_scanner_next(self._h, ctypes.byref(out))
        if n == -1:
            raise StopIteration
        if n == -2:
            raise IOError("corrupt recordio chunk (crc/format mismatch)")
        return _take(out, n, free=False)  # buffer owned by scanner

    def close(self):
        if self._h:
            lib().ptq_recordio_scanner_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class BlockingQueue:
    """Bounded byte-blob queue (LoDTensorBlockingQueue analog) backed by C++
    so producers on any thread never contend on the GIL."""

    def __init__(self, capacity=64):
        self._h = lib().ptq_queue_new(capacity)

    def push(self, data: bytes, timeout=None) -> bool:
        rc = lib().ptq_queue_push(self._h, data, len(data),
                                  -1.0 if timeout is None else timeout)
        if rc == 2:
            raise RuntimeError("queue closed")
        return rc == 0

    def pop(self, timeout=None):
        out = ctypes.c_void_p()
        n = lib().ptq_queue_pop(self._h, ctypes.byref(out),
                                -1.0 if timeout is None else timeout)
        if n == -1:
            return None  # timeout
        if n == -2:
            raise EOFError("queue closed and drained")
        return _take(out, n)

    def size(self):
        return lib().ptq_queue_size(self._h)

    def waiters(self):
        """Number of threads currently blocked in push/pop."""
        return lib().ptq_queue_waiters(self._h)

    def close(self):
        lib().ptq_queue_close(self._h)

    def __del__(self):
        # ptq_queue_free closes first and waits for blocked push/pop callers
        # to leave before destroying the mutex/cvs
        try:
            if self._h:
                lib().ptq_queue_free(self._h)
                self._h = None
        except Exception:
            pass


def _decode_batch(blob: bytes):
    """Decode the C++ batch wire format → {slot_index: (padded, lens)} lists.

    Returns list of (type, lens, flat_values) per slot; padding to numpy
    arrays happens in MultiSlotFeed.__next__ (needs slot names/shapes).
    """
    off = 0
    (nslots,) = np.frombuffer(blob, "<u4", 1, off)
    off += 4
    slots = []
    for _ in range(int(nslots)):
        t = chr(blob[off])
        off += 1
        (bs,) = np.frombuffer(blob, "<u4", 1, off)
        off += 4
        lens = np.frombuffer(blob, "<u4", int(bs), off).copy()
        off += 4 * int(bs)
        (total,) = np.frombuffer(blob, "<u4", 1, off)
        off += 4
        if t == "f":
            vals = np.frombuffer(blob, "<f4", int(total), off).copy()
            off += 4 * int(total)
        else:
            vals = np.frombuffer(blob, "<i8", int(total), off).copy()
            off += 8 * int(total)
        slots.append((t, lens, vals))
    return slots


class MultiSlotFeed:
    """Background C++ parser of MultiSlot text files → padded numpy batches
    (reference framework/data_feed.cc MultiSlotDataFeed).

    slots: list of (name, 'f'|'u').  Iterating yields
    {name: padded [B, maxlen] array, name+'__len': int32 lengths}; slots
    whose samples all have length 1 are squeezed to [B, 1].
    """

    def __init__(self, files, slots, batch_size, queue_capacity=32,
                 n_threads=1):
        self.slot_names = [n for n, _ in slots]
        desc = ";".join(f"{n}:{t}" for n, t in slots).encode()
        arr = (ctypes.c_char_p * len(files))(*[f.encode() for f in files])
        self._h = lib().ptq_feed_new(arr, len(files), desc, batch_size,
                                     queue_capacity, n_threads)
        if not self._h:
            raise ValueError("bad slot description or empty slot list")

    def __iter__(self):
        return self

    def __next__(self):
        if not self._h:
            raise StopIteration
        out = ctypes.c_void_p()
        n = lib().ptq_feed_next(self._h, ctypes.byref(out))
        if n == -1:
            raise StopIteration
        if n == -3:
            err = ctypes.c_void_p()
            m = lib().ptq_feed_error(self._h, ctypes.byref(err))
            raise IOError(_take(err, m).decode())
        blob = _take(out, n)
        feed = {}
        for name, (t, lens, vals) in zip(self.slot_names, _decode_batch(blob)):
            bs = len(lens)
            maxlen = int(lens.max()) if bs else 0
            dtype = "float32" if t == "f" else "int64"
            padded = np.zeros((bs, maxlen), dtype=dtype)
            pos = 0
            for i, L in enumerate(lens):
                padded[i, :L] = vals[pos:pos + L]
                pos += L
            feed[name] = padded
            feed[name + "__len"] = lens.astype("int32")
        return feed

    def close(self):
        if self._h:
            lib().ptq_feed_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Parameter-server transport (ps_runtime.cc) — reference analog:
# operators/distributed/ gRPC SendRecvService + listen_and_serv sync loop.
# Tensors travel as opaque blobs: 1-byte dtype code + raw bytes; shape comes
# from the program's VarDesc on each side.
# ---------------------------------------------------------------------------

CMD_SEND_GRAD = 1
CMD_GET_PARAM = 2
CMD_SEND_BARRIER = 3
CMD_FETCH_BARRIER = 4
CMD_SEND_PARAM = 5
CMD_STOP = 6
CMD_LOOKUP_ROWS = 7
CMD_CHECKPOINT_NOTIFY = 8
CMD_LEASE = 9
CMD_JOIN = 10
CMD_LEAVE = 11
CMD_COMMIT_EPOCH = 12

_CMD_NAMES = {CMD_SEND_GRAD: "send_grad", CMD_GET_PARAM: "get_param",
              CMD_SEND_BARRIER: "send_barrier",
              CMD_FETCH_BARRIER: "fetch_barrier",
              CMD_SEND_PARAM: "send_param", CMD_STOP: "stop",
              CMD_LOOKUP_ROWS: "lookup_rows",
              CMD_CHECKPOINT_NOTIFY: "checkpoint_notify",
              CMD_LEASE: "lease", CMD_JOIN: "join", CMD_LEAVE: "leave",
              CMD_COMMIT_EPOCH: "commit_epoch"}


def _rpc_latency():
    """Per-command RPC latency histogram in the shared registry
    (docs/OBSERVABILITY.md).  Lazy: observability is stdlib-only, so this
    keeps `native` importable without jax."""
    from paddle_tpu import observability as obs

    return obs.histogram(
        "pt_ps_rpc_latency_seconds",
        "Client-observed wire latency of one PS RPC attempt "
        "(retries are separate samples)", labels=("cmd",))


def _rpc_total():
    from paddle_tpu import observability as obs

    return obs.counter(
        "pt_ps_rpc_total",
        "PS RPC attempts by command and outcome "
        "(ok/timeout/server_error/transport_error)",
        labels=("cmd", "status"))


def _record_rpc(cmd, seconds, status, span_id=None):
    """Book one wire attempt: latency histogram + outcome counter, a
    profiler span (when a profiling session is live — checked via
    sys.modules so telemetry never triggers the fluid import), and the
    attempt's span id in the JSONL event log (when enabled).  `span_id`
    is the SAME id that rode the RPC frame, so the server's journaled
    handling record for this attempt correlates exactly."""
    name = _CMD_NAMES.get(cmd, str(cmd))
    _rpc_latency().labels(cmd=name).observe(seconds)
    _rpc_total().labels(cmd=name, status=status).inc()
    import sys as _sys

    prof = _sys.modules.get("paddle_tpu.fluid.profiler")
    if prof is not None and prof.is_profiler_enabled():
        prof._record("rpc", f"rpc:{name}", seconds)
    from paddle_tpu.observability import events as _events

    if _events.enabled():
        from paddle_tpu.observability import tracing as _tracing

        _events.emit("rpc", cmd=name, status=status,
                     seconds=round(seconds, 6),
                     span_id=span_id or _tracing.new_span_id())

# barrier frames carry the trainer's completed-round count; this high bit
# marks the retry of a timed-out wait (server must not re-count the
# arrival) — mirrors kPtsRewaitBit in native_api.h
_REWAIT_BIT = 1 << 63


class PSError(IOError):
    """Base of all parameter-server RPC failures (an IOError so existing
    `except IOError` teardown paths keep working)."""


class PSConnectionError(PSError):
    """Transport broken / peer unreachable — retryable with reconnect."""


class PSServerError(PSError):
    """The server answered with an error status — NOT retryable (the
    request itself is wrong, or the server was deliberately stopped)."""


class PSTimeoutError(PSError):
    """The server's liveness deadline expired while the request waited
    (status 2) — retryable; barriers rewait without re-arriving.
    `server_round` (when set) is the effective round the server parked
    the arrival on — the rewait echoes it."""

    server_round = None

# payload magic distinguishing a row-sparse gradient (SelectedRows: ids +
# row values) from a dense tensor blob.  Dense blobs start with the dtype
# code length (1..8); 0xSR can never collide.
_SPARSE_MAGIC = 0xE5


def _encode_tensor(arr) -> bytes:
    a = np.ascontiguousarray(arr)
    code = a.dtype.str.encode()  # e.g. b'<f4'
    return bytes([len(code)]) + code + a.tobytes()


def _decode_tensor(blob: bytes, shape=None):
    n = blob[0]
    dtype = np.dtype(blob[1:1 + n].decode())
    a = np.frombuffer(blob, dtype, offset=1 + n).copy()
    return a.reshape(shape) if shape is not None else a


def encode_sparse(rows, values) -> bytes:
    """SelectedRows wire form: magic | u64 ids_len | ids blob | values blob.
    `rows` is an int64 id vector, `values` the matching [n, width...] rows
    (reference framework/selected_rows.h)."""
    ids = _encode_tensor(np.ascontiguousarray(rows, dtype=np.int64))
    vals = _encode_tensor(values)
    import struct

    return bytes([_SPARSE_MAGIC]) + struct.pack("<Q", len(ids)) + ids + vals


def is_sparse_blob(blob: bytes) -> bool:
    return len(blob) > 0 and blob[0] == _SPARSE_MAGIC


def decode_sparse(blob: bytes):
    """-> (rows int64[n], values [n, ...])"""
    import struct

    (ids_len,) = struct.unpack_from("<Q", blob, 1)
    rows = _decode_tensor(blob[9:9 + ids_len])
    values = _decode_tensor(blob[9 + ids_len:])
    return rows, values.reshape(len(rows), -1) if len(rows) else values


def _decode_membership(blob: bytes) -> dict:
    """The 40-byte elastic-membership reply (kJoin/kLease): epoch, round,
    version, active count, and the requester's index among the sorted
    active members (-1 while pending / not a member)."""
    import struct

    epoch, rnd, version, count, index = struct.unpack("<5Q", blob)
    return {"epoch": int(epoch), "round": int(rnd), "version": int(version),
            "count": int(count),
            "index": -1 if index == 0xffffffffffffffff else int(index)}


def _decode_committed(blob: bytes) -> dict:
    """The 24-byte kCommitEpoch reply: the shard's quorum-committed epoch
    record (epoch, round, dataset position)."""
    import struct

    epoch, rnd, pos = struct.unpack("<3Q", blob)
    return {"epoch": int(epoch), "round": int(rnd), "position": int(pos)}


class PSServer:
    """Sync-mode parameter-server transport endpoint.

    The driver loop above it (transpiler.run_pserver / listen_and_serv
    lowering) is: wait_round() → grads() → optimize → publish() →
    bump_version() → release_send() → end_round(), mirroring
    listen_and_serv_op.cc:109 RunSyncLoop.
    """

    def __init__(self, port=0, n_trainers=1, barrier_timeout_ms=None):
        self._h = lib().pts_server_start(int(port), int(n_trainers))
        if not self._h:
            raise IOError(f"cannot bind pserver port {port}")
        self._elastic = False
        self._membership_mirrored = {}
        if barrier_timeout_ms is None:
            from paddle_tpu.fluid import flags
            barrier_timeout_ms = flags.flag("ps_barrier_timeout_ms")
        self.set_barrier_timeout(barrier_timeout_ms)

    @property
    def port(self):
        return lib().pts_server_port(self._h)

    def set_barrier_timeout(self, ms):
        """Liveness deadline on barrier / versioned-get waits: a request
        parked longer than `ms` is answered with a retryable timeout
        (status 2) instead of wedging behind a dead peer; 0 = wait
        forever (reference behavior)."""
        lib().pts_server_set_barrier_timeout_ms(self._h, int(ms))

    def enable_elastic(self, lease_timeout_ms=None):
        """Elastic membership: the barrier quorum becomes the live member
        set (kJoin/kLeave under a lease) instead of the fixed n_trainers.
        A member whose lease goes unrenewed for `lease_timeout_ms` is
        evicted at the next driver wait, renegotiating the round's
        arrival count downward so the survivors complete it.  Call BEFORE
        load() so a snapshot's member section restores the quorum."""
        if lease_timeout_ms is None:
            from paddle_tpu.fluid import flags
            lease_timeout_ms = flags.flag("ps_lease_timeout_ms")
        lib().pts_server_enable_elastic(self._h, int(lease_timeout_ms))
        self._elastic = True

    def stats(self):
        """Server-side resilience counters (stale-trainer detection:
        nonzero barrier timeouts mean some peer stopped arriving), plus
        the elastic-membership surface (epoch / members / joins / leaves /
        evictions).

        The pre-elastic keys are the frozen back-compat view; each read
        also mirrors the values into `pt_ps_server_stat{key=...}` gauges
        and the `pt_ps_membership_*` / `pt_ps_lease_*` families in the
        shared registry (the sync loop calls stats() every round, so
        /metricsz tracks the live C++ counters round-granular)."""
        st = lib().pts_server_stat
        out = {"send_barrier_timeouts": st(self._h, 0),
               "fetch_barrier_timeouts": st(self._h, 1),
               "get_param_timeouts": st(self._h, 2),
               "rounds": st(self._h, 3),
               "version": st(self._h, 4),
               "epoch": st(self._h, 5),
               "members": st(self._h, 6),
               "joins": st(self._h, 7),
               "leaves": st(self._h, 8),
               "evictions": st(self._h, 9),
               "committed_epoch": st(self._h, 10),
               "committed_round": st(self._h, 11),
               "committed_pos": st(self._h, 12)}
        from paddle_tpu import observability as obs

        g = obs.gauge("pt_ps_server_stat",
                      "PSServer transport counters (mirrored from the "
                      "native runtime on each stats() read)",
                      labels=("key",))
        for k, v in out.items():
            g.labels(key=k).set(float(v))
        # the pt_ps_membership_*/pt_ps_lease_* families exist only on
        # elastic servers — a legacy fixed-quorum job must not surface a
        # misleading membership_size == 0
        if self._elastic:
            obs.gauge("pt_ps_membership_epoch",
                      "Elastic membership epoch (bumps on every applied "
                      "join/leave/eviction)").set(float(out["epoch"]))
            obs.gauge("pt_ps_membership_size",
                      "Active members of the elastic barrier quorum").set(
                float(out["members"]))
            ev = obs.counter(
                "pt_ps_membership_events_total",
                "Applied elastic membership transitions by kind",
                labels=("event",))
            lease_exp = obs.counter(
                "pt_ps_lease_expirations_total",
                "Members evicted because their lease went unrenewed")
            last = self._membership_mirrored
            for key, event in (("joins", "join"), ("leaves", "leave"),
                               ("evictions", "evict")):
                delta = int(out[key]) - last.get(key, 0)
                if delta > 0:
                    ev.labels(event=event).inc(delta)
                    if key == "evictions":
                        lease_exp.inc(delta)
                last[key] = int(out[key])
        return out

    def drain_spans(self, max_records=4096):
        """Drain the server's span journal: [(cmd_name, span_str,
        wall_start_s, dur_s)] for every served frame that carried a span
        id — the server half of client↔server RPC attribution.  The
        driver loop drains per round and re-emits as `serve_rpc` events /
        `rpc_serve:` profiler spans."""
        from paddle_tpu.observability import tracing as _tracing

        buf = (ctypes.c_uint64 * (4 * int(max_records)))()
        n = lib().pts_server_drain_spans(self._h, buf, int(max_records))
        out = []
        for i in range(int(n)):
            cmd, span, start_us, dur_us = buf[i * 4:i * 4 + 4]
            out.append((_CMD_NAMES.get(int(cmd), str(int(cmd))),
                        _tracing.format_wire_span(int(span)),
                        start_us / 1e6, dur_us / 1e6))
        return out

    def wait_round(self) -> bool:
        """Block until every trainer hit send_barrier; False = stopped."""
        return bool(lib().pts_server_wait_round(self._h))

    def grads(self):
        """All grads received this round as [(name, payload)] — payload is
        a dense np array or a (rows, values) SelectedRows pair."""
        out = []
        n = lib().pts_server_grad_count(self._h)
        for i in range(n):
            name_p, data_p = ctypes.c_void_p(), ctypes.c_void_p()
            dlen = lib().pts_server_grad_at(self._h, i, ctypes.byref(name_p),
                                            ctypes.byref(data_p))
            nlen = lib().pts_server_grad_name_len(self._h, i)
            name = _take(name_p, nlen).decode()
            blob = _take(data_p, dlen)
            payload = (decode_sparse(blob) if is_sparse_blob(blob)
                       else _decode_tensor(blob))
            out.append((name, payload))
        return out

    def pop_grad(self, timeout=0.1):
        """Async-mode: block up to `timeout` s for one pushed grad.
        Returns (name, payload) where payload is a dense np array or a
        (rows, values) SelectedRows pair; None on timeout; raises
        StopIteration when the server was stopped and drained
        (listen_and_serv RunAsyncLoop's queue pop)."""
        name_p, data_p = ctypes.c_void_p(), ctypes.c_void_p()
        n = lib().pts_server_pop_grad(self._h, int(timeout * 1000),
                                      ctypes.byref(name_p),
                                      ctypes.byref(data_p))
        if n == -2:
            raise StopIteration
        if n == -1:
            return None
        name = ctypes.string_at(name_p.value).decode()
        lib().ptq_free(ctypes.cast(name_p, ctypes.c_char_p))
        blob = _take(data_p, n)
        if is_sparse_blob(blob):
            return name, decode_sparse(blob)
        return name, _decode_tensor(blob)

    def publish(self, name, arr):
        blob = _encode_tensor(arr)
        lib().pts_server_publish(self._h, name.encode(), blob, len(blob))

    def bump_version(self):
        lib().pts_server_bump_version(self._h)

    def release_send(self):
        """Ack this round's SEND_BARRIERs (call after publish+bump)."""
        lib().pts_server_release_send(self._h)

    def end_round(self) -> bool:
        return bool(lib().pts_server_end_round(self._h))

    def wait_table(self, name) -> bool:
        """Block until `name` was pushed (trainer-0 init); False = stopped."""
        return bool(lib().pts_server_wait_table(self._h, name.encode()))

    def save(self, path) -> bool:
        """Snapshot the table (+version/round) to `path` — the server-local
        half of the CheckpointNotify contract."""
        return bool(lib().pts_server_save(self._h, str(path).encode()))

    def load(self, path) -> bool:
        """Restore a snapshot written by save()/CheckpointNotify — a
        restarted pserver resumes with its shard state."""
        return bool(lib().pts_server_load(self._h, str(path).encode()))

    def reconcile_committed(self, epoch, round, position=0) -> bool:
        """Adopt the QUORUM committed epoch record (gathered from the
        surviving peers by `elastic.agree_epoch`): when the quorum round
        is ahead of this shard's restored counter, the round/epoch fast-
        forward so the survivors' barrier arithmetic lines up.  Returns
        True when the counters moved — i.e. the snapshot was STALE and
        this shard would otherwise have parked the job behind a round
        count only it believed in."""
        return bool(lib().pts_server_reconcile_committed(
            self._h, int(epoch), int(round), int(position)))

    def table_get(self, name, shape=None):
        out = ctypes.c_void_p()
        n = lib().pts_server_table_get(self._h, name.encode(),
                                       ctypes.byref(out))
        if n < 0:
            return None
        return _decode_tensor(_take(out, n), shape)

    def stop(self):
        if self._h:
            lib().pts_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class PSClient:
    """Trainer-side connection to one pserver endpoint.

    Fault tolerance: every RPC runs under a `RetryPolicy`
    (FLAGS_rpc_retry_times / FLAGS_rpc_retry_backoff_ms unless overridden
    per client) — transport failures reconnect transparently and retry
    with exponential backoff + jitter, server liveness deadlines
    (status 2) retry in place, and server-rejected requests raise
    immediately.  `retry_times=0` restores the seed's fail-fast behavior.
    When retries exhaust, the client marks itself `broken` so the channel
    cache (`ops.dist_ops.get_channel`) evicts it.
    """

    def __init__(self, host="127.0.0.1", port=0, timeout=30.0,
                 retry_times=None, retry_backoff_ms=None, uid=None):
        self._host, self._port = host, int(port)
        self._timeout = float(timeout)
        self._retry_times = retry_times
        self._retry_backoff_ms = retry_backoff_ms
        self._policy_cache = None
        self._lock = threading.RLock()
        self.broken = False
        self._rounds_done = 0  # completed sync rounds (internal barriers)
        # stable identity for barrier-arrival dedup: survives reconnects
        # AND supervised relaunches (PADDLE_TRAINER_ID is stable across a
        # trainer's incarnations), so neither a re-arrive on a surviving
        # server nor a relaunched trainer replaying a still-open round can
        # double-count.  Processes outside the launcher env contract
        # (tests simulating trainers with threads) fall back to a uuid.
        # An explicit `uid` overrides — a lease-heartbeat sidecar client
        # must renew the SAME membership its primary client holds.
        if uid:
            self._uid = str(uid)
        else:
            tid = os.environ.get("PADDLE_TRAINER_ID")
            if tid:
                self._uid = f"trainer:{tid}"
            else:
                import uuid
                self._uid = uuid.uuid4().hex
        self._h = lib().pts_connect(host.encode(), int(port), float(timeout))
        if not self._h:
            raise PSConnectionError(
                f"cannot connect to pserver {host}:{port}")

    @property
    def endpoint(self):
        return f"{self._host}:{self._port}"

    @property
    def uid(self):
        """This client's stable membership/barrier identity."""
        return self._uid

    def _policy(self):
        """Retry policy, cached until the flags it was built from change
        (so the hot path pays two flag lookups, not a fresh RNG, and the
        jitter sequence actually advances across retries)."""
        from paddle_tpu.distributed import resilience
        from paddle_tpu.fluid import flags

        t = (self._retry_times if self._retry_times is not None
             else flags.flag("rpc_retry_times"))
        b = (self._retry_backoff_ms if self._retry_backoff_ms is not None
             else flags.flag("rpc_retry_backoff_ms"))
        cached = self._policy_cache
        if cached is None or cached.times != t or cached.backoff_ms != b:
            cached = self._policy_cache = resilience.RetryPolicy(
                times=t, backoff_ms=b)
        return cached

    def reconnect(self, timeout=None):
        """Drop the (broken) connection and dial the endpoint again.
        pts_connect itself polls the address until `timeout`, so a
        restarting pserver is picked up within ~50 ms of binding."""
        from paddle_tpu.distributed import resilience

        with self._lock:
            if self._h:
                lib().pts_client_close(self._h)
                self._h = None
            t = min(self._timeout, 5.0) if timeout is None else timeout
            h = lib().pts_connect(self._host.encode(), self._port, float(t))
            if not h:
                resilience.record("reconnect_failures")
                raise PSConnectionError(
                    f"reconnect to pserver {self.endpoint} failed")
            self._h = h
            resilience.record("reconnects")

    def _req_once(self, cmd, name="", round=0, blob=b""):
        """One wire attempt; classifies failures for the retry layer and
        books latency + outcome into the shared telemetry registry.  Every
        frame carries a fresh span id (retries are distinct spans); the
        server journals it against its handling, so a merged post-mortem
        trace attributes server-side command handling to this client —
        across restarts, because the id embeds this process's pid."""
        from paddle_tpu.observability import tracing as _tracing

        out, olen = ctypes.c_void_p(), ctypes.c_int64()
        wire_span, span_str = _tracing.new_wire_span()
        t0 = time.perf_counter()  # observability: allow
        with self._lock:
            if self._h is None:
                raise PSConnectionError(
                    f"connection to pserver {self.endpoint} is closed")
            rc = lib().pts_request(self._h, cmd, name.encode(), round,
                                   wire_span, blob,
                                   len(blob), ctypes.byref(out),
                                   ctypes.byref(olen))
        _record_rpc(cmd, time.perf_counter() - t0,  # observability: allow
                    {0: "ok", 1: "server_error", 2: "timeout"}.get(
                        rc, "transport_error"), span_id=span_str)
        data = _take(out, olen.value) if out.value else b""
        if rc == 0:
            return data
        what = (f"pserver rpc {_CMD_NAMES.get(cmd, cmd)} name={name!r} "
                f"to {self.endpoint}")
        if rc == 2:
            err = PSTimeoutError(f"{what}: server liveness deadline "
                                 f"expired (stale peer suspected)")
            if len(data) == 8:  # barrier timeout echoes the effective round
                err.server_round = int.from_bytes(data, "little")
            raise err
        if rc == 1:
            raise PSServerError(f"{what}: rejected by server (stopped, "
                                f"or bad request)")
        raise PSConnectionError(f"{what}: transport failed (rc={rc})")

    def _req(self, cmd, name="", round=0, blob=b"", retry=True):
        """RPC with transparent retry/reconnect.  Safe for idempotent
        commands (everything except barriers, which use _barrier below).
        Note send_grad retried across a reconnect is at-least-once: an
        ack lost with the connection means the server may already hold
        the payload (see docs/DISTRIBUTED.md "Fault tolerance")."""
        from paddle_tpu.distributed import fault_injection, resilience

        policy = self._policy() if retry else None
        attempt = 0
        while True:
            try:
                fault_injection.on_rpc(_CMD_NAMES.get(cmd, str(cmd)))
                return self._req_once(cmd, name, round, blob)
            except PSServerError:
                raise
            except PSTimeoutError:
                if policy is None or not policy.should_retry(attempt):
                    raise
                resilience.record("rpc_timeout_retries")
                attempt += 1
            except PSConnectionError as e:
                if policy is None or not policy.should_retry(attempt):
                    self.broken = True
                    raise PSConnectionError(
                        f"{e} (after {attempt} retries; "
                        f"FLAGS_rpc_retry_times="
                        f"{0 if policy is None else policy.times})") from e
                resilience.record("rpc_retries")
                time.sleep(policy.delay(attempt))
                attempt += 1
                try:
                    self.reconnect()
                except PSConnectionError:
                    continue  # endpoint still down; next attempt re-dials

    def _barrier(self, cmd, round=None):
        """Barrier RPC with exactly-once arrival under retry: arrivals
        are identity-deduped server-side (this client's uid travels in
        the name field), a liveness timeout (status 2) REWAITS on the
        server-echoed effective round, and a transport failure re-ARRIVES
        — a no-op on a surviving server, a fresh arrival on a restarted
        one."""
        from paddle_tpu.distributed import fault_injection, resilience

        rc_ = self._rounds_done if round is None else int(round)
        policy = self._policy()
        attempt = 0
        rewait = False
        while True:
            try:
                fault_injection.on_rpc(_CMD_NAMES[cmd])
                self._req_once(
                    cmd, name=self._uid,
                    round=(rc_ | _REWAIT_BIT) if rewait else rc_)
                if round is None and cmd == CMD_FETCH_BARRIER:
                    self._rounds_done += 1
                return
            except PSServerError:
                raise
            except PSTimeoutError as e:
                if not policy.should_retry(attempt):
                    raise
                resilience.record("barrier_rewaits")
                if e.server_round is not None:
                    rc_ = e.server_round  # wait on what the server parked
                rewait = True
                attempt += 1
            except PSConnectionError as e:
                if not policy.should_retry(attempt):
                    self.broken = True
                    raise PSConnectionError(
                        f"{e} (after {attempt} retries)") from e
                resilience.record("rpc_retries")
                time.sleep(policy.delay(attempt))
                attempt += 1
                rewait = False  # fresh/restarted server: must re-arrive
                try:
                    self.reconnect()
                except PSConnectionError:
                    continue

    def send_grad(self, name, arr):
        self._req(CMD_SEND_GRAD, name, blob=_encode_tensor(arr))

    def send_sparse_grad(self, name, rows, values):
        """Push a row-sparse (SelectedRows) gradient: only the touched
        embedding rows travel, not the vocab-sized dense tensor."""
        self._req(CMD_SEND_GRAD, name, blob=encode_sparse(rows, values))

    def lookup_rows(self, name, ids, dtype, row_width):
        """Distributed embedding lookup (parameter_prefetch): fetch
        `ids`' rows of the published table `name`.  Served natively by the
        pserver from the table blob — O(ids) bytes on the wire."""
        ids = np.ascontiguousarray(ids, dtype=np.int64).reshape(-1)
        dt = np.dtype(dtype)
        width = int(row_width) * dt.itemsize
        header = 1 + len(dt.str.encode())  # codec header before raw rows
        packed = (header << 32) | width
        blob = self._req(CMD_LOOKUP_ROWS, name, round=packed,
                         blob=ids.tobytes())
        return np.frombuffer(blob, dt).copy().reshape(len(ids),
                                                      int(row_width))

    def send_param(self, name, arr):
        self._req(CMD_SEND_PARAM, name, blob=_encode_tensor(arr))

    def get_param(self, name, want_version=0, shape=None):
        return _decode_tensor(self._req(CMD_GET_PARAM, name,
                                        round=want_version), shape)

    def send_barrier(self, round=None):
        """Arrive at the send barrier for `round` (the trainer's
        completed-round count; defaults to this client's own counter)."""
        self._barrier(CMD_SEND_BARRIER, round)

    def fetch_barrier(self, round=None):
        self._barrier(CMD_FETCH_BARRIER, round)

    def checkpoint_notify(self, path):
        """Ask the pserver to snapshot its shard to `path` (reference
        AsyncCheckpointNotify, send_recv.proto.in:30)."""
        self._req(CMD_CHECKPOINT_NOTIFY, str(path))

    # -- elastic membership (docs/DISTRIBUTED.md §6) ---------------------

    def join(self):
        """Register this client's uid as a member of an elastic job.  The
        idle job (round 0, nothing in flight) activates immediately; a
        running job queues the join for the next round boundary — poll
        `membership()` until `index >= 0` before entering the round loop.
        Idempotent: a relaunched trainer re-joining under its stable uid
        just renews its lease.  Returns the membership dict (epoch,
        round, version, count, index)."""
        return _decode_membership(self._req(CMD_JOIN, name=self._uid))

    def leave(self):
        """Graceful departure: queued server-side and applied at the next
        round boundary.  The caller must keep participating in rounds
        until its leave applies — announce, run the one in-flight round,
        then exit (the drain sequence in distributed.elastic)."""
        self._req(CMD_LEAVE, name=self._uid)

    def lease_heartbeat(self):
        """Renew this member's lease and return the current membership
        view.  Also answers for non-members (index -1), so a delayed
        joiner can watch the round counter before joining."""
        return _decode_membership(self._req(CMD_LEASE, name=self._uid))

    membership = lease_heartbeat

    def commit_epoch(self, epoch, round, position=None):
        """Propose the quorum epoch record (epoch, round, dataset
        position) to this shard; accepted iff its round is not behind
        the stored record's (commits are monotone).  Returns the shard's
        post-accept record — trainers propose to EVERY shard after each
        completed round, so the record survives the loss of any one
        shard, including the old shard-0 data authority."""
        import struct

        blob = struct.pack("<3Q", int(epoch), int(round),
                           int(round if position is None else position))
        return _decode_committed(
            self._req(CMD_COMMIT_EPOCH, name=self._uid, blob=blob))

    def committed_epoch(self):
        """Query this shard's quorum-committed epoch record without
        proposing (the empty-payload form of kCommitEpoch)."""
        return _decode_committed(
            self._req(CMD_COMMIT_EPOCH, name=self._uid))

    def stop_server(self):
        # no retry: stopping an already-dead server must fail fast, not
        # spend the whole backoff schedule reconnecting to a corpse
        self._req(CMD_STOP, retry=False)

    def close(self):
        with self._lock:
            if self._h:
                lib().pts_client_close(self._h)
                self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativePredictor:
    """C++-runtime predictor over a reference-format saved model
    (reference inference/api/paddle_inference_api.h CreatePaddlePredictor;
    this wrapper mirrors api/demo_ci usage from Python for tests — C/C++
    callers use the pti_* ABI in native_api.h directly).

    model_dir must hold `__model__` (protobuf ProgramDesc, e.g. from
    fluid.io.save_inference_model(model_format="protobuf")) and params as
    per-var LoDTensor files or one combined file (params_file=...).
    """

    def __init__(self, model_dir, params_file=None):
        self._h = lib().pti_create(
            str(model_dir).encode(),
            params_file.encode() if params_file else None)
        if not self._h:
            raise RuntimeError("pti_create failed")
        err = lib().pti_error(self._h)
        if err:
            msg = err.decode()
            lib().pti_free(self._h)
            self._h = None
            raise RuntimeError(f"NativePredictor: {msg}")

    @property
    def input_names(self):
        return [lib().pti_input_name(self._h, i).decode()
                for i in range(lib().pti_num_inputs(self._h))]

    @property
    def output_names(self):
        return [lib().pti_output_name(self._h, i).decode()
                for i in range(lib().pti_num_outputs(self._h))]

    def run(self, feed):
        """feed: {name: np.ndarray (float32 or int64)} → list of outputs in
        fetch order."""
        L = lib()
        for name, arr in feed.items():
            arr = np.ascontiguousarray(arr)
            if arr.dtype == np.float32:
                dtype = 0
            elif arr.dtype == np.int64:
                dtype = 1
            else:
                raise TypeError(f"feed {name!r}: dtype {arr.dtype} "
                                "unsupported (float32/int64 only)")
            dims = (ctypes.c_int64 * arr.ndim)(*arr.shape)
            L.pti_set_input(self._h, name.encode(),
                            arr.ctypes.data_as(ctypes.c_void_p), dims,
                            arr.ndim, dtype)
        if L.pti_run(self._h) != 0:
            raise RuntimeError(
                f"native inference failed: {L.pti_error(self._h).decode()}")
        outs = []
        for name in self.output_names:
            data = ctypes.c_void_p()
            dims = ctypes.POINTER(ctypes.c_int64)()
            ndims = ctypes.c_int()
            dtype = ctypes.c_int()
            n = L.pti_get_output(self._h, name.encode(), ctypes.byref(data),
                                 ctypes.byref(dims), ctypes.byref(ndims),
                                 ctypes.byref(dtype))
            if n < 0:
                raise RuntimeError(
                    f"output {name!r}: {L.pti_error(self._h).decode()}")
            shape = tuple(dims[i] for i in range(ndims.value))
            ct = ctypes.c_float if dtype.value == 0 else ctypes.c_int64
            buf = ctypes.cast(data, ctypes.POINTER(ct))
            np_dtype = "float32" if dtype.value == 0 else "int64"
            # astype already copies out of the runtime-owned buffer
            arr = np.ctypeslib.as_array(buf, shape=(int(n),)).astype(np_dtype)
            outs.append(arr.reshape(shape))
        return outs

    def close(self):
        if self._h:
            lib().pti_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
