"""Native (C++) data runtime bindings.

The reference implements its data path in C++ (recordio/, data_feed.cc,
lod_tensor_blocking_queue.h) so ingestion never blocks the training loop on
the Python GIL.  This package does the same for the TPU build: a small C++
shared library (src/data_runtime.cc) provides RecordIO, a blocking queue,
and a MultiSlot text-feed parser with a background reader thread; Python
binds it with ctypes (no pybind11 in this image).

The library is compiled on first use with g++ (cached next to the source,
keyed by source hash) — the moral equivalent of the reference's cmake step,
but zero-config for users.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

__all__ = ["lib", "RecordIOWriter", "RecordIOScanner", "BlockingQueue",
           "MultiSlotFeed", "is_available"]

_SRC = os.path.join(os.path.dirname(__file__), "src", "data_runtime.cc")
_lib = None
_lib_lock = threading.Lock()
_build_error = None


def _build() -> str:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    out_dir = os.path.join(os.path.dirname(__file__), "_build")
    os.makedirs(out_dir, exist_ok=True)
    so_path = os.path.join(out_dir, f"libptq_data_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    # per-process tmp name: concurrent first-use builds (pytest-xdist, two
    # jobs) must not interleave writes to the same output file
    tmp = f"{so_path}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-lz", "-o", tmp]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so_path)
    return so_path


def lib():
    """Load (building if needed) the native library; raises on failure."""
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            raise _build_error
        try:
            path = _build()
            L = ctypes.CDLL(path)
        except Exception as e:  # remember: don't retry the build every call
            _build_error = RuntimeError(f"native data runtime build failed: {e}")
            raise _build_error
        L.ptq_free.argtypes = [ctypes.c_char_p]
        L.ptq_recordio_writer_open.restype = ctypes.c_void_p
        L.ptq_recordio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        L.ptq_recordio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                                ctypes.c_int64]
        L.ptq_recordio_writer_close.argtypes = [ctypes.c_void_p]
        L.ptq_recordio_scanner_open.restype = ctypes.c_void_p
        L.ptq_recordio_scanner_open.argtypes = [ctypes.c_char_p]
        L.ptq_recordio_scanner_next.restype = ctypes.c_int64
        L.ptq_recordio_scanner_next.argtypes = [ctypes.c_void_p,
                                                ctypes.POINTER(ctypes.c_void_p)]
        L.ptq_recordio_scanner_close.argtypes = [ctypes.c_void_p]
        L.ptq_queue_new.restype = ctypes.c_void_p
        L.ptq_queue_new.argtypes = [ctypes.c_int64]
        L.ptq_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64, ctypes.c_double]
        L.ptq_queue_pop.restype = ctypes.c_int64
        L.ptq_queue_pop.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_void_p),
                                    ctypes.c_double]
        L.ptq_queue_size.restype = ctypes.c_int64
        L.ptq_queue_size.argtypes = [ctypes.c_void_p]
        L.ptq_queue_close.argtypes = [ctypes.c_void_p]
        L.ptq_queue_free.argtypes = [ctypes.c_void_p]
        L.ptq_feed_new.restype = ctypes.c_void_p
        L.ptq_feed_new.argtypes = [ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
                                   ctypes.c_char_p, ctypes.c_int, ctypes.c_int64]
        L.ptq_feed_next.restype = ctypes.c_int64
        L.ptq_feed_next.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_void_p)]
        L.ptq_feed_error.restype = ctypes.c_int64
        L.ptq_feed_error.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_void_p)]
        L.ptq_feed_free.argtypes = [ctypes.c_void_p]
        _lib = L
        return _lib


def is_available() -> bool:
    try:
        lib()
        return True
    except Exception:
        return False


def _take(ptr, length, free=True):
    """Copy `length` bytes from a returned buffer into Python, freeing it."""
    data = ctypes.string_at(ptr, length)
    if free and length >= 0 and ptr:
        lib().ptq_free(ctypes.cast(ptr, ctypes.c_char_p))
    return data


class RecordIOWriter:
    """Chunked record file writer (reference recordio/writer.cc)."""

    def __init__(self, path, compressor=1):
        self._h = lib().ptq_recordio_writer_open(path.encode(), compressor)
        if not self._h:
            raise IOError(f"cannot open {path} for writing")

    def write(self, data: bytes):
        if not self._h:
            raise ValueError("writer is closed")
        rc = lib().ptq_recordio_writer_write(self._h, data, len(data))
        if rc != 0:
            raise IOError("recordio write failed")

    def close(self):
        if self._h:
            rc = lib().ptq_recordio_writer_close(self._h)
            self._h = None
            if rc != 0:
                raise IOError("recordio flush failed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecordIOScanner:
    """Iterates records of a RecordIO file (reference recordio/scanner.cc)."""

    def __init__(self, path):
        self._h = lib().ptq_recordio_scanner_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        if not self._h:
            raise StopIteration
        out = ctypes.c_void_p()
        n = lib().ptq_recordio_scanner_next(self._h, ctypes.byref(out))
        if n == -1:
            raise StopIteration
        if n == -2:
            raise IOError("corrupt recordio chunk (crc/format mismatch)")
        return _take(out, n, free=False)  # buffer owned by scanner

    def close(self):
        if self._h:
            lib().ptq_recordio_scanner_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class BlockingQueue:
    """Bounded byte-blob queue (LoDTensorBlockingQueue analog) backed by C++
    so producers on any thread never contend on the GIL."""

    def __init__(self, capacity=64):
        self._h = lib().ptq_queue_new(capacity)

    def push(self, data: bytes, timeout=None) -> bool:
        rc = lib().ptq_queue_push(self._h, data, len(data),
                                  -1.0 if timeout is None else timeout)
        if rc == 2:
            raise RuntimeError("queue closed")
        return rc == 0

    def pop(self, timeout=None):
        out = ctypes.c_void_p()
        n = lib().ptq_queue_pop(self._h, ctypes.byref(out),
                                -1.0 if timeout is None else timeout)
        if n == -1:
            return None  # timeout
        if n == -2:
            raise EOFError("queue closed and drained")
        return _take(out, n)

    def size(self):
        return lib().ptq_queue_size(self._h)

    def close(self):
        lib().ptq_queue_close(self._h)

    def __del__(self):
        try:
            if self._h:
                lib().ptq_queue_free(self._h)
        except Exception:
            pass


def _decode_batch(blob: bytes):
    """Decode the C++ batch wire format → {slot_index: (padded, lens)} lists.

    Returns list of (type, lens, flat_values) per slot; padding to numpy
    arrays happens in MultiSlotFeed.__next__ (needs slot names/shapes).
    """
    off = 0
    (nslots,) = np.frombuffer(blob, "<u4", 1, off)
    off += 4
    slots = []
    for _ in range(int(nslots)):
        t = chr(blob[off])
        off += 1
        (bs,) = np.frombuffer(blob, "<u4", 1, off)
        off += 4
        lens = np.frombuffer(blob, "<u4", int(bs), off).copy()
        off += 4 * int(bs)
        (total,) = np.frombuffer(blob, "<u4", 1, off)
        off += 4
        if t == "f":
            vals = np.frombuffer(blob, "<f4", int(total), off).copy()
            off += 4 * int(total)
        else:
            vals = np.frombuffer(blob, "<i8", int(total), off).copy()
            off += 8 * int(total)
        slots.append((t, lens, vals))
    return slots


class MultiSlotFeed:
    """Background C++ parser of MultiSlot text files → padded numpy batches
    (reference framework/data_feed.cc MultiSlotDataFeed).

    slots: list of (name, 'f'|'u').  Iterating yields
    {name: padded [B, maxlen] array, name+'__len': int32 lengths}; slots
    whose samples all have length 1 are squeezed to [B, 1].
    """

    def __init__(self, files, slots, batch_size, queue_capacity=32):
        self.slot_names = [n for n, _ in slots]
        desc = ";".join(f"{n}:{t}" for n, t in slots).encode()
        arr = (ctypes.c_char_p * len(files))(*[f.encode() for f in files])
        self._h = lib().ptq_feed_new(arr, len(files), desc, batch_size,
                                     queue_capacity)
        if not self._h:
            raise ValueError("bad slot description or empty slot list")

    def __iter__(self):
        return self

    def __next__(self):
        if not self._h:
            raise StopIteration
        out = ctypes.c_void_p()
        n = lib().ptq_feed_next(self._h, ctypes.byref(out))
        if n == -1:
            raise StopIteration
        if n == -3:
            err = ctypes.c_void_p()
            m = lib().ptq_feed_error(self._h, ctypes.byref(err))
            raise IOError(_take(err, m).decode())
        blob = _take(out, n)
        feed = {}
        for name, (t, lens, vals) in zip(self.slot_names, _decode_batch(blob)):
            bs = len(lens)
            maxlen = int(lens.max()) if bs else 0
            dtype = "float32" if t == "f" else "int64"
            padded = np.zeros((bs, maxlen), dtype=dtype)
            pos = 0
            for i, L in enumerate(lens):
                padded[i, :L] = vals[pos:pos + L]
                pos += L
            feed[name] = padded
            feed[name + "__len"] = lens.astype("int32")
        return feed

    def close(self):
        if self._h:
            lib().ptq_feed_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
