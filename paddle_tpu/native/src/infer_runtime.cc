// Native inference runtime: load a reference-format saved model
// (__model__ ProgramDesc protobuf + LoDTensor param streams) and run it on
// CPU with no Python/JAX dependency.
//
// Reference analog: paddle/fluid/inference/api/paddle_inference_api.h
// (CreatePaddlePredictor<AnalysisConfig>, PaddleTensor, ZeroCopyTensor) and
// api/demo_ci — the flagship C++ deployment path.  TPU-native redesign: the
// *accelerated* serving path is AnalysisPredictor over XLA (Python,
// paddle_tpu/inference.py); THIS runtime is the dependency-free edge/CI
// deployment analog of demo_ci — a minimal interpreter over the same
// protobuf format with a practical inference kernel set (fc/conv/bn/pool/
// softmax/embedding and friends), fp32 + int64.
//
// Wire formats implemented from scratch (same as fluid/proto_compat.py):
//   proto2: framework.proto ProgramDesc (BlockDesc=1{idx,parent,vars=3,
//           ops=4}, OpDesc{inputs=1,outputs=2,type=3,attrs=4},
//           VarDesc{name=1,type=2,persistable=3})
//   LoDTensor stream: u32 version | u64 lod_level {u64 nbytes, data}* |
//           u32 tensor version | i32 desc_size | TensorDesc proto
//           {data_type=1, dims=2} | raw data

#include <algorithm>
#include <cmath>
#include <exception>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace pti {

// ---------------------------------------------------------------------------
// proto2 wire reader
// ---------------------------------------------------------------------------

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
      if (shift > 63) break;
    }
    ok = false;
    return 0;
  }

  // returns field number, sets wire type; 0 at end
  uint32_t tag(uint32_t* wt) {
    if (p >= end) return 0;
    uint64_t t = varint();
    if (!ok) return 0;
    *wt = static_cast<uint32_t>(t & 7);
    return static_cast<uint32_t>(t >> 3);
  }

  Cursor sub() {  // length-delimited
    uint64_t n = varint();
    if (!ok || p + n > end) {
      ok = false;
      return {end, end};
    }
    Cursor c{p, p + n};
    p += n;
    return c;
  }

  std::string str() {
    Cursor c = sub();
    return std::string(reinterpret_cast<const char*>(c.p), c.end - c.p);
  }

  void skip(uint32_t wt) {
    switch (wt) {
      case 0: varint(); break;
      case 1: p += 8; break;
      case 2: sub(); break;
      case 5: p += 4; break;
      default: ok = false;
    }
    if (p > end) ok = false;
  }

  float f32() {
    if (p + 4 > end) { ok = false; return 0; }
    float v;
    memcpy(&v, p, 4);
    p += 4;
    return v;
  }
};

// ---------------------------------------------------------------------------
// model structs
// ---------------------------------------------------------------------------

struct Attr {
  int type = -1;  // AttrType
  int64_t i = 0;
  float f = 0;
  std::string s;
  std::vector<int64_t> ints;
  std::vector<float> floats;
  std::vector<std::string> strings;
  bool b = false;
};

struct Op {
  std::string type;
  std::map<std::string, std::vector<std::string>> inputs, outputs;
  std::map<std::string, Attr> attrs;

  const std::string& in(const std::string& slot, size_t i = 0) const {
    static const std::string empty;
    auto it = inputs.find(slot);
    if (it == inputs.end() || i >= it->second.size()) return empty;
    return it->second[i];
  }
  const std::string& out(const std::string& slot, size_t i = 0) const {
    static const std::string empty;
    auto it = outputs.find(slot);
    if (it == outputs.end() || i >= it->second.size()) return empty;
    return it->second[i];
  }
  bool has_in(const std::string& slot) const {
    auto it = inputs.find(slot);
    return it != inputs.end() && !it->second.empty() &&
           !it->second[0].empty();
  }
  int64_t attr_i(const std::string& n, int64_t dflt = 0) const {
    auto it = attrs.find(n);
    return it == attrs.end() ? dflt : it->second.i;
  }
  float attr_f(const std::string& n, float dflt = 0) const {
    auto it = attrs.find(n);
    return it == attrs.end() ? dflt : it->second.f;
  }
  bool attr_b(const std::string& n, bool dflt = false) const {
    auto it = attrs.find(n);
    return it == attrs.end() ? dflt : it->second.b;
  }
  std::string attr_s(const std::string& n, const std::string& dflt = "") const {
    auto it = attrs.find(n);
    return it == attrs.end() ? dflt : it->second.s;
  }
  std::vector<int64_t> attr_ints(const std::string& n) const {
    auto it = attrs.find(n);
    return it == attrs.end() ? std::vector<int64_t>{} : it->second.ints;
  }
};

struct VarInfo {
  std::string name;
  std::vector<int64_t> dims;
  int dtype = 5;  // VarType.Type: FP32
  int kind = 7;   // VarType.Type of the VAR itself: LOD_TENSOR
  bool persistable = false;
};

struct Block {
  std::vector<VarInfo> vars;
  std::vector<Op> ops;
};

struct Tensor {
  std::vector<int64_t> dims;
  std::vector<float> f;    // FP32 payload
  std::vector<int64_t> i;  // INT64 payload
  bool is_f = true;

  int64_t numel() const {
    int64_t n = 1;
    for (auto d : dims) n *= d;
    return n;
  }
};

// ---------------------------------------------------------------------------
// ProgramDesc parsing
// ---------------------------------------------------------------------------

static Attr parse_attr(Cursor c, std::string* name) {
  Attr a;
  uint32_t wt;
  while (uint32_t f = c.tag(&wt)) {
    switch (f) {
      case 1: *name = c.str(); break;
      case 2: a.type = static_cast<int>(c.varint()); break;
      case 3: a.i = static_cast<int32_t>(c.varint()); break;
      case 4: a.f = c.f32(); break;
      case 5: a.s = c.str(); break;
      case 6:  // repeated int32 (maybe packed)
        if (wt == 2) {
          Cursor s = c.sub();
          while (s.p < s.end) a.ints.push_back(static_cast<int32_t>(s.varint()));
        } else {
          a.ints.push_back(static_cast<int32_t>(c.varint()));
        }
        break;
      case 7:
        if (wt == 2) {
          Cursor s = c.sub();
          while (s.p < s.end) a.floats.push_back(s.f32());
        } else {
          a.floats.push_back(c.f32());
        }
        break;
      case 8: a.strings.push_back(c.str()); break;
      case 10: a.b = c.varint() != 0; break;
      case 13: a.i = static_cast<int64_t>(c.varint()); break;
      case 15:
        if (wt == 2) {
          Cursor s = c.sub();
          while (s.p < s.end) a.ints.push_back(static_cast<int64_t>(s.varint()));
        } else {
          a.ints.push_back(static_cast<int64_t>(c.varint()));
        }
        break;
      default: c.skip(wt);
    }
    if (!c.ok) break;
  }
  return a;
}

static void parse_slot(Cursor c, std::map<std::string,
                                          std::vector<std::string>>* out) {
  std::string param;
  std::vector<std::string> args;
  uint32_t wt;
  while (uint32_t f = c.tag(&wt)) {
    if (f == 1) param = c.str();
    else if (f == 2) args.push_back(c.str());
    else c.skip(wt);
    if (!c.ok) break;
  }
  (*out)[param] = std::move(args);
}

static Op parse_op(Cursor c) {
  Op op;
  uint32_t wt;
  while (uint32_t f = c.tag(&wt)) {
    switch (f) {
      case 1: parse_slot(c.sub(), &op.inputs); break;
      case 2: parse_slot(c.sub(), &op.outputs); break;
      case 3: op.type = c.str(); break;
      case 4: {
        std::string name;
        Attr a = parse_attr(c.sub(), &name);
        op.attrs[name] = std::move(a);
        break;
      }
      default: c.skip(wt);
    }
    if (!c.ok) break;
  }
  return op;
}

// VarType.TensorDesc {data_type=1, dims=2}
static void parse_tensor_desc(Cursor c, VarInfo* v) {
  uint32_t wt;
  while (uint32_t f = c.tag(&wt)) {
    if (f == 1) v->dtype = static_cast<int>(c.varint());
    else if (f == 2) {
      if (wt == 2) {
        Cursor s = c.sub();
        while (s.p < s.end)
          v->dims.push_back(static_cast<int64_t>(s.varint()));
      } else {
        v->dims.push_back(static_cast<int64_t>(c.varint()));
      }
    } else c.skip(wt);
    if (!c.ok) break;
  }
}

// VarType {type=1, lod_tensor=3{tensor=1}}
static void parse_var_type(Cursor c, VarInfo* v) {
  uint32_t wt;
  while (uint32_t f = c.tag(&wt)) {
    if (f == 1) v->kind = static_cast<int>(c.varint());
    else if (f == 3) {  // LoDTensorDesc
      Cursor lt = c.sub();
      uint32_t wt2;
      while (uint32_t f2 = lt.tag(&wt2)) {
        if (f2 == 1) parse_tensor_desc(lt.sub(), v);
        else lt.skip(wt2);
        if (!lt.ok) break;
      }
    } else c.skip(wt);
    if (!c.ok) break;
  }
}

static VarInfo parse_var(Cursor c) {
  VarInfo v;
  uint32_t wt;
  while (uint32_t f = c.tag(&wt)) {
    if (f == 1) v.name = c.str();
    else if (f == 2) parse_var_type(c.sub(), &v);
    else if (f == 3) v.persistable = c.varint() != 0;
    else c.skip(wt);
    if (!c.ok) break;
  }
  return v;
}

static Block parse_block(Cursor c) {
  Block b;
  uint32_t wt;
  while (uint32_t f = c.tag(&wt)) {
    if (f == 3) b.vars.push_back(parse_var(c.sub()));
    else if (f == 4) b.ops.push_back(parse_op(c.sub()));
    else c.skip(wt);
    if (!c.ok) break;
  }
  return b;
}

static bool parse_program(const std::string& blob, std::vector<Block>* blocks) {
  Cursor c{reinterpret_cast<const uint8_t*>(blob.data()),
           reinterpret_cast<const uint8_t*>(blob.data()) + blob.size()};
  uint32_t wt;
  while (uint32_t f = c.tag(&wt)) {
    if (f == 1) blocks->push_back(parse_block(c.sub()));
    else c.skip(wt);
    if (!c.ok) return false;
  }
  return c.ok && !blocks->empty();
}

// ---------------------------------------------------------------------------
// LoDTensor stream reader
// ---------------------------------------------------------------------------

static bool read_lod_tensor(FILE* f, Tensor* t, std::string* err) {
  auto rd = [&](void* dst, size_t n) { return fread(dst, 1, n, f) == n; };
  uint32_t version;
  if (!rd(&version, 4)) { *err = "truncated LoDTensor (version)"; return false; }
  if (version != 0) { *err = "unsupported LoDTensor version"; return false; }
  uint64_t lod_level;
  if (!rd(&lod_level, 8)) { *err = "truncated LoDTensor (lod)"; return false; }
  for (uint64_t l = 0; l < lod_level; ++l) {
    uint64_t nbytes;
    if (!rd(&nbytes, 8)) { *err = "truncated lod level"; return false; }
    fseek(f, static_cast<long>(nbytes), SEEK_CUR);
  }
  uint32_t tver;
  if (!rd(&tver, 4)) { *err = "truncated tensor version"; return false; }
  int32_t desc_size;
  if (!rd(&desc_size, 4)) { *err = "truncated desc size"; return false; }
  if (desc_size < 0 || desc_size > (1 << 20)) {
    *err = "corrupt TensorDesc size " + std::to_string(desc_size);
    return false;
  }
  std::string desc(desc_size, '\0');
  if (!rd(desc.data(), desc_size)) { *err = "truncated TensorDesc"; return false; }
  VarInfo vi;
  parse_tensor_desc(
      Cursor{reinterpret_cast<const uint8_t*>(desc.data()),
             reinterpret_cast<const uint8_t*>(desc.data()) + desc.size()},
      &vi);
  t->dims = vi.dims;
  int64_t n = 1;
  for (auto d : t->dims) {
    if (d < 0 || d > (int64_t(1) << 32)) {
      *err = "corrupt tensor dim " + std::to_string(d);
      return false;
    }
    n *= d;
  }
  if (n < 0 || n > (int64_t(1) << 34)) {
    *err = "corrupt tensor size " + std::to_string(n);
    return false;
  }
  if (vi.dtype == 5) {  // FP32
    t->is_f = true;
    t->f.resize(n);
    if (!rd(t->f.data(), n * 4)) { *err = "truncated fp32 payload"; return false; }
  } else if (vi.dtype == 3) {  // INT64
    t->is_f = false;
    t->i.resize(n);
    if (!rd(t->i.data(), n * 8)) { *err = "truncated int64 payload"; return false; }
  } else if (vi.dtype == 2) {  // INT32 → widen
    std::vector<int32_t> tmp(n);
    if (!rd(tmp.data(), n * 4)) { *err = "truncated int32 payload"; return false; }
    t->is_f = false;
    t->i.assign(tmp.begin(), tmp.end());
  } else {
    *err = "unsupported param dtype " + std::to_string(vi.dtype);
    return false;
  }
  return true;
}

// ---------------------------------------------------------------------------
// kernels
// ---------------------------------------------------------------------------

static void matmul2d(const float* a, const float* b, float* out, int64_t m,
                     int64_t k, int64_t n) {
  // simple ikj loop: streams b rows, decent cache behavior at MLP sizes
  for (int64_t i = 0; i < m; ++i) {
    float* o = out + i * n;
    memset(o, 0, n * sizeof(float));
    const float* ar = a + i * k;
    for (int64_t kk = 0; kk < k; ++kk) {
      float av = ar[kk];
      const float* br = b + kk * n;
      for (int64_t j = 0; j < n; ++j) o[j] += av * br[j];
    }
  }
}

static int64_t flatten_rows(const std::vector<int64_t>& dims, int ncol) {
  int64_t m = 1;
  for (int i = 0; i < ncol && i < static_cast<int>(dims.size()); ++i)
    m *= dims[i];
  return m;
}

struct Runtime {
  std::vector<Block> blocks;
  std::map<std::string, Tensor> scope;  // params + activations
  std::vector<std::string> feed_names, fetch_names;
  std::string error;
  // load-time errors are permanent; run-time errors clear on the next run
  bool load_failed = false;

  Tensor* var(const std::string& n) {
    auto it = scope.find(n);
    return it == scope.end() ? nullptr : &it->second;
  }

  bool fail(const std::string& e) {
    if (error.empty()) error = e;
    return false;
  }

  bool run_op(const Op& op);
  bool run();
};

static void ewise_broadcast(const Tensor& x, const Tensor& y, int axis,
                            char kind, Tensor* out) {
  // y's dims align into x at `axis` (right-aligned when axis==-1), with
  // numpy broadcasting inside the aligned span (size-1 y dims repeat):
  // stride-0 trick over a full multi-index walk — exact for [M,1], [C,1,1]
  // and friends, not just contiguous tails
  out->dims = x.dims;
  out->is_f = true;
  out->f.resize(x.numel());
  int xr = static_cast<int>(x.dims.size());
  int yr = static_cast<int>(y.dims.size());
  if (axis < 0) axis = xr - yr;
  // y's stride per x-dim (0 where y is absent or size-1)
  std::vector<int64_t> ystride(xr, 0);
  int64_t s = 1;
  for (int i = yr - 1; i >= 0; --i) {
    int xi = axis + i;
    if (xi >= 0 && xi < xr && y.dims[i] != 1) ystride[xi] = s;
    s *= y.dims[i];
  }
  std::vector<int64_t> xstride(xr, 1);
  for (int i = xr - 2; i >= 0; --i) xstride[i] = xstride[i + 1] * x.dims[i + 1];
  int64_t n = x.numel();
  for (int64_t li = 0; li < n; ++li) {
    int64_t rem = li, yi = 0;
    for (int i = 0; i < xr; ++i) {
      int64_t d = rem / xstride[i];
      rem %= xstride[i];
      yi += d * ystride[i];
    }
    float a = x.f[li], b = y.f[yi];
    float r = 0;
    switch (kind) {
      case '+': r = a + b; break;
      case '-': r = a - b; break;
      case '*': r = a * b; break;
      case '/': r = a / b; break;
    }
    out->f[li] = r;
  }
}

bool Runtime::run_op(const Op& op) {
  const std::string& t = op.type;
  if (t == "feed" || t == "fetch") return true;  // handled by run()

  auto X = [&](const char* slot) -> Tensor* { return var(op.in(slot)); };
  auto make_out = [&](const char* slot) -> Tensor* {
    return &scope[op.out(slot)];
  };

  if (t == "mul") {
    Tensor *x = X("X"), *y = X("Y");
    if (!x || !y) return fail("mul: missing input");
    int ncol = static_cast<int>(op.attr_i("x_num_col_dims", 1));
    int64_t m = flatten_rows(x->dims, ncol);
    int64_t k = x->numel() / m;
    int64_t n = y->numel() / y->dims[0];
    if (y->dims[0] != k) return fail("mul: shape mismatch");
    Tensor* o = make_out("Out");
    o->is_f = true;
    o->dims.assign(x->dims.begin(), x->dims.begin() + ncol);
    o->dims.push_back(n);
    o->f.resize(m * n);
    matmul2d(x->f.data(), y->f.data(), o->f.data(), m, k, n);
    return true;
  }
  if (t == "matmul" || t == "matmul_v2") {
    Tensor *x = X("X"), *y = X("Y");
    if (!x || !y) return fail("matmul: missing input");
    bool tx = op.attr_b("transpose_X", false) || op.attr_b("trans_x", false);
    bool ty = op.attr_b("transpose_Y", false) || op.attr_b("trans_y", false);
    if (x->dims.size() != 2 || y->dims.size() != 2 || tx)
      return fail("matmul: only 2D, non-transposed X supported");
    int64_t m = x->dims[0], k = x->dims[1];
    Tensor* o = make_out("Out");
    o->is_f = true;
    if (!ty) {
      if (y->dims[0] != k) return fail("matmul: shape mismatch");
      int64_t n = y->dims[1];
      o->dims = {m, n};
      o->f.resize(m * n);
      matmul2d(x->f.data(), y->f.data(), o->f.data(), m, k, n);
    } else {
      if (y->dims[1] != k) return fail("matmul^T: shape mismatch");
      int64_t n = y->dims[0];
      o->dims = {m, n};
      o->f.assign(m * n, 0.f);
      for (int64_t i = 0; i < m; ++i)
        for (int64_t j = 0; j < n; ++j) {
          float acc = 0;
          for (int64_t kk = 0; kk < k; ++kk)
            acc += x->f[i * k + kk] * y->f[j * k + kk];
          o->f[i * n + j] = acc;
        }
    }
    float alpha = op.attr_f("alpha", 1.0f);
    if (alpha != 1.0f)
      for (auto& v : o->f) v *= alpha;
    return true;
  }
  if (t == "fc") {
    Tensor *x = X("Input"), *w = X("W");
    if (!x || !w) return fail("fc: missing input");
    int ncol = static_cast<int>(op.attr_i("in_num_col_dims", 1));
    int64_t m = flatten_rows(x->dims, ncol);
    int64_t k = x->numel() / m, n = w->dims[1];
    if (w->dims[0] != k) return fail("fc: shape mismatch");
    Tensor* o = make_out("Out");
    o->is_f = true;
    o->dims = {m, n};
    o->f.resize(m * n);
    matmul2d(x->f.data(), w->f.data(), o->f.data(), m, k, n);
    if (op.has_in("Bias")) {
      Tensor* b = X("Bias");
      for (int64_t i = 0; i < m; ++i)
        for (int64_t j = 0; j < n; ++j) o->f[i * n + j] += b->f[j];
    }
    std::string act = op.attr_s("activation_type");
    if (act == "relu")
      for (auto& v : o->f) v = v > 0 ? v : 0;
    else if (!act.empty())
      return fail("fc: unsupported activation " + act);
    return true;
  }
  if (t == "elementwise_add" || t == "elementwise_sub" ||
      t == "elementwise_mul" || t == "elementwise_div") {
    Tensor *x = X("X"), *y = X("Y");
    if (!x || !y) return fail(t + ": missing input");
    if (!x->is_f || !y->is_f)
      return fail(t + ": only float32 supported natively");
    char kind = t == "elementwise_add" ? '+'
                : t == "elementwise_sub" ? '-'
                : t == "elementwise_mul" ? '*' : '/';
    ewise_broadcast(*x, *y, static_cast<int>(op.attr_i("axis", -1)), kind,
                    make_out("Out"));
    return true;
  }
  if (t == "relu" || t == "sigmoid" || t == "tanh" || t == "exp") {
    Tensor* x = X("X");
    if (!x) return fail(t + ": missing input");
    if (!x->is_f) return fail(t + ": only float32 supported natively");
    Tensor* o = make_out("Out");
    *o = *x;
    for (auto& v : o->f) {
      if (t == "relu") v = v > 0 ? v : 0;
      else if (t == "sigmoid") v = 1.f / (1.f + std::exp(-v));
      else if (t == "tanh") v = std::tanh(v);
      else v = std::exp(v);
    }
    return true;
  }
  if (t == "softmax") {
    Tensor* x = X("X");
    if (!x) return fail("softmax: missing input");
    int64_t ax = op.attr_i("axis", -1);
    int xr = static_cast<int>(x->dims.size());
    if (ax != -1 && ax != xr - 1)
      return fail("softmax: only last-axis supported natively");
    Tensor* o = make_out("Out");
    *o = *x;
    int64_t last = x->dims.back(), rows = x->numel() / last;
    for (int64_t r = 0; r < rows; ++r) {
      float* p = o->f.data() + r * last;
      float mx = p[0];
      for (int64_t j = 1; j < last; ++j) mx = std::max(mx, p[j]);
      float sum = 0;
      for (int64_t j = 0; j < last; ++j) { p[j] = std::exp(p[j] - mx); sum += p[j]; }
      for (int64_t j = 0; j < last; ++j) p[j] /= sum;
    }
    return true;
  }
  if (t == "scale") {
    Tensor* x = X("X");
    if (!x) return fail("scale: missing input");
    Tensor* o = make_out("Out");
    *o = *x;
    float s = op.attr_f("scale", 1.0f), b = op.attr_f("bias", 0.0f);
    bool after = op.attr_b("bias_after_scale", true);
    for (auto& v : o->f) v = after ? v * s + b : (v + b) * s;
    return true;
  }
  if (t == "reshape" || t == "reshape2") {
    Tensor* x = X("X");
    if (!x) return fail("reshape: missing input");
    Tensor* o = make_out("Out");
    *o = *x;
    auto shape = op.attr_ints("shape");
    int64_t known = 1, minus1 = -1;
    for (size_t i = 0; i < shape.size(); ++i) {
      if (shape[i] == -1) minus1 = static_cast<int64_t>(i);
      else if (shape[i] == 0) shape[i] = x->dims[i];
      if (shape[i] > 0) known *= shape[i];
    }
    if (minus1 >= 0) shape[minus1] = x->numel() / known;
    o->dims = shape;
    return true;
  }
  if (t == "transpose" || t == "transpose2") {
    Tensor* x = X("X");
    if (!x) return fail("transpose: missing input");
    auto axis = op.attr_ints("axis");
    int r = static_cast<int>(x->dims.size());
    Tensor* o = make_out("Out");
    o->is_f = x->is_f;
    o->dims.resize(r);
    for (int i = 0; i < r; ++i) o->dims[i] = x->dims[axis[i]];
    std::vector<int64_t> xstr(r, 1), ostr(r, 1);
    for (int i = r - 2; i >= 0; --i) xstr[i] = xstr[i + 1] * x->dims[i + 1];
    for (int i = r - 2; i >= 0; --i) ostr[i] = ostr[i + 1] * o->dims[i + 1];
    int64_t n = x->numel();
    o->f.resize(x->is_f ? n : 0);
    o->i.resize(x->is_f ? 0 : n);
    std::vector<int64_t> idx(r);
    for (int64_t li = 0; li < n; ++li) {
      int64_t rem = li, src = 0;
      for (int i = 0; i < r; ++i) {
        idx[i] = rem / ostr[i];
        rem %= ostr[i];
        src += idx[i] * xstr[axis[i]];
      }
      if (x->is_f) o->f[li] = x->f[src];
      else o->i[li] = x->i[src];
    }
    return true;
  }
  if (t == "dropout") {
    Tensor* x = X("X");
    if (!x) return fail("dropout: missing input");
    Tensor* o = make_out("Out");
    *o = *x;  // inference: upscale_in_train → identity; downgrade → scale
    std::string impl = op.attr_s("dropout_implementation", "downgrade_in_infer");
    if (impl == "downgrade_in_infer") {
      float keep = 1.0f - op.attr_f("dropout_prob", 0.0f);
      for (auto& v : o->f) v *= keep;
    }
    return true;
  }
  if (t == "batch_norm") {
    Tensor *x = X("X"), *sc = X("Scale"), *bi = X("Bias"), *mu = X("Mean"),
           *va = X("Variance");
    if (!x || !sc || !bi || !mu || !va) return fail("batch_norm: missing input");
    float eps = op.attr_f("epsilon", 1e-5f);
    Tensor* o = make_out("Y");
    *o = *x;
    int64_t c = x->dims.size() > 1 ? x->dims[1] : x->dims[0];
    int64_t spatial = x->numel() / (x->dims[0] * c);
    for (int64_t nn = 0; nn < x->dims[0]; ++nn)
      for (int64_t cc = 0; cc < c; ++cc) {
        float inv = 1.0f / std::sqrt(va->f[cc] + eps);
        float g = sc->f[cc] * inv, be = bi->f[cc] - mu->f[cc] * g;
        float* p = o->f.data() + (nn * c + cc) * spatial;
        for (int64_t s = 0; s < spatial; ++s) p[s] = p[s] * g + be;
      }
    return true;
  }
  if (t == "lookup_table" || t == "lookup_table_v2") {
    Tensor *w = X("W"), *ids = X("Ids");
    if (!w || !ids) return fail("lookup_table: missing input");
    int64_t d = w->dims[1], n = ids->numel();
    int64_t pad = op.attr_i("padding_idx", -1);
    Tensor* o = make_out("Out");
    o->is_f = true;
    o->dims = ids->dims;
    if (!o->dims.empty() && o->dims.back() == 1) o->dims.pop_back();
    o->dims.push_back(d);
    o->f.resize(n * d);
    for (int64_t r = 0; r < n; ++r) {
      int64_t id = ids->i[r];
      if (id == pad || id < 0 || id >= w->dims[0])
        memset(o->f.data() + r * d, 0, d * 4);
      else
        memcpy(o->f.data() + r * d, w->f.data() + id * d, d * 4);
    }
    return true;
  }
  if (t == "concat") {
    auto it = op.inputs.find("X");
    if (it == op.inputs.end() || it->second.empty())
      return fail("concat: missing input");
    std::vector<Tensor*> xs;
    for (auto& n : it->second) {
      Tensor* x = var(n);
      if (!x) return fail("concat: missing " + n);
      xs.push_back(x);
    }
    int axis = static_cast<int>(op.attr_i("axis", 0));
    if (axis < 0) axis += static_cast<int>(xs[0]->dims.size());
    Tensor* o = make_out("Out");
    o->is_f = xs[0]->is_f;
    o->dims = xs[0]->dims;
    int64_t cat = 0;
    for (auto* x : xs) cat += x->dims[axis];
    o->dims[axis] = cat;
    int64_t pre = 1, post = 1;
    for (int i = 0; i < axis; ++i) pre *= xs[0]->dims[i];
    for (size_t i = axis + 1; i < xs[0]->dims.size(); ++i)
      post *= xs[0]->dims[i];
    o->f.resize(o->is_f ? o->numel() : 0);
    o->i.resize(o->is_f ? 0 : o->numel());
    int64_t ooff = 0;
    for (auto* x : xs) {
      int64_t chunk = x->dims[axis] * post;
      for (int64_t p = 0; p < pre; ++p) {
        if (o->is_f)
          memcpy(o->f.data() + p * cat * post + ooff,
                 x->f.data() + p * chunk, chunk * 4);
        else
          memcpy(o->i.data() + p * cat * post + ooff,
                 x->i.data() + p * chunk, chunk * 8);
      }
      ooff += chunk;
    }
    return true;
  }
  if (t == "pool2d") {
    Tensor* x = X("X");
    if (!x || x->dims.size() != 4) return fail("pool2d: need NCHW input");
    if (op.attr_b("adaptive", false))
      return fail("pool2d: adaptive mode not supported natively");
    bool global = op.attr_b("global_pooling", false);
    bool ceil_mode = op.attr_b("ceil_mode", false);
    std::string ptype = op.attr_s("pooling_type", "max");
    auto ksize = op.attr_ints("ksize");
    auto strides = op.attr_ints("strides");
    auto paddings = op.attr_ints("paddings");
    int64_t N = x->dims[0], C = x->dims[1], H = x->dims[2], W = x->dims[3];
    int64_t kh = global ? H : ksize[0], kw = global ? W : ksize[1];
    int64_t sh = global ? 1 : strides[0], sw = global ? 1 : strides[1];
    int64_t ph = global ? 0 : paddings[0], pw = global ? 0 : paddings[1];
    int64_t ceil_add = ceil_mode ? (sh - 1) : 0;
    int64_t ceil_add_w = ceil_mode ? (sw - 1) : 0;
    int64_t OH = (H + 2 * ph - kh + ceil_add) / sh + 1;
    int64_t OW = (W + 2 * pw - kw + ceil_add_w) / sw + 1;
    Tensor* o = make_out("Out");
    o->is_f = true;
    o->dims = {N, C, OH, OW};
    o->f.resize(o->numel());
    bool exclusive = op.attr_b("exclusive", true);
    for (int64_t n = 0; n < N; ++n)
      for (int64_t c = 0; c < C; ++c) {
        const float* in = x->f.data() + (n * C + c) * H * W;
        float* out = o->f.data() + (n * C + c) * OH * OW;
        for (int64_t oh = 0; oh < OH; ++oh)
          for (int64_t ow = 0; ow < OW; ++ow) {
            int64_t h0 = oh * sh - ph, w0 = ow * sw - pw;
            int64_t h1 = std::min(h0 + kh, H), w1 = std::min(w0 + kw, W);
            h0 = std::max<int64_t>(h0, 0);
            w0 = std::max<int64_t>(w0, 0);
            float acc = ptype == "max" ? -3.4e38f : 0.f;
            int64_t cnt = 0;
            for (int64_t hh = h0; hh < h1; ++hh)
              for (int64_t ww = w0; ww < w1; ++ww, ++cnt) {
                float v = in[hh * W + ww];
                acc = ptype == "max" ? std::max(acc, v) : acc + v;
              }
            if (ptype != "max")
              acc /= exclusive ? std::max<int64_t>(cnt, 1) : kh * kw;
            out[oh * OW + ow] = acc;
          }
      }
    return true;
  }
  if (t == "conv2d") {
    Tensor *x = X("Input"), *w = X("Filter");
    if (!x || !w) return fail("conv2d: missing input");
    auto strides = op.attr_ints("strides");
    auto paddings = op.attr_ints("paddings");
    auto dil = op.attr_ints("dilations");
    int64_t groups = op.attr_i("groups", 1);
    int64_t N = x->dims[0], C = x->dims[1], H = x->dims[2], W = x->dims[3];
    int64_t M = w->dims[0], Cg = w->dims[1], KH = w->dims[2], KW = w->dims[3];
    int64_t sh = strides.empty() ? 1 : strides[0];
    int64_t sw = strides.size() > 1 ? strides[1] : sh;
    int64_t ph = paddings.empty() ? 0 : paddings[0];
    int64_t pw = paddings.size() > 1 ? paddings[1] : ph;
    int64_t dh = dil.empty() ? 1 : dil[0], dw = dil.size() > 1 ? dil[1] : dh;
    int64_t OH = (H + 2 * ph - (dh * (KH - 1) + 1)) / sh + 1;
    int64_t OW = (W + 2 * pw - (dw * (KW - 1) + 1)) / sw + 1;
    if (C != Cg * groups) return fail("conv2d: channel/group mismatch");
    Tensor* o = make_out("Output");
    o->is_f = true;
    o->dims = {N, M, OH, OW};
    o->f.assign(o->numel(), 0.f);
    int64_t Mg = M / groups;
    for (int64_t n = 0; n < N; ++n)
      for (int64_t g = 0; g < groups; ++g)
        for (int64_t m = 0; m < Mg; ++m) {
          int64_t oc = g * Mg + m;
          float* out = o->f.data() + (n * M + oc) * OH * OW;
          for (int64_t ic = 0; ic < Cg; ++ic) {
            const float* in = x->f.data() + (n * C + g * Cg + ic) * H * W;
            const float* ker = w->f.data() + ((oc * Cg) + ic) * KH * KW;
            for (int64_t oh = 0; oh < OH; ++oh)
              for (int64_t ow = 0; ow < OW; ++ow) {
                float acc = 0;
                for (int64_t khh = 0; khh < KH; ++khh) {
                  int64_t hh = oh * sh - ph + khh * dh;
                  if (hh < 0 || hh >= H) continue;
                  for (int64_t kww = 0; kww < KW; ++kww) {
                    int64_t ww = ow * sw - pw + kww * dw;
                    if (ww < 0 || ww >= W) continue;
                    acc += in[hh * W + ww] * ker[khh * KW + kww];
                  }
                }
                out[oh * OW + ow] += acc;
              }
          }
        }
    return true;
  }
  if (t == "mean") {
    Tensor* x = X("X");
    if (!x) return fail("mean: missing input");
    Tensor* o = make_out("Out");
    o->is_f = true;
    o->dims = {1};
    double acc = 0;
    for (auto v : x->f) acc += v;
    o->f = {static_cast<float>(acc / x->numel())};
    return true;
  }
  if (t == "arg_max") {
    Tensor* x = X("X");
    if (!x) return fail("arg_max: missing input");
    int64_t ax = op.attr_i("axis", -1);
    int xr = static_cast<int>(x->dims.size());
    if (ax != -1 && ax != xr - 1)
      return fail("arg_max: only last-axis supported natively");
    int64_t last = x->dims.back(), rows = x->numel() / last;
    Tensor* o = make_out("Out");
    o->is_f = false;
    o->dims = x->dims;
    o->dims.pop_back();
    if (o->dims.empty()) o->dims = {1};
    o->i.resize(rows);
    for (int64_t r = 0; r < rows; ++r) {
      const float* p = x->f.data() + r * last;
      int64_t best = 0;
      for (int64_t j = 1; j < last; ++j)
        if (p[j] > p[best]) best = j;
      o->i[r] = best;
    }
    return true;
  }
  return fail("no native kernel for op '" + t +
              "' (serve this model with the Python AnalysisPredictor)");
}

bool Runtime::run() {
  for (const auto& op : blocks[0].ops) {
    if (!run_op(op)) return false;
  }
  return true;
}

}  // namespace pti

// ---------------------------------------------------------------------------
// C ABI (mirrors CreatePaddlePredictor / PaddleTensor at arm's length)
// ---------------------------------------------------------------------------

extern "C" {

// model_dir must contain __model__; params either per-var files (pass
// params_file=nullptr) or one combined file (load_combine order: sorted by
// var name — io.py save side mirrors).  Errors (I/O, parse, corrupt
// streams) are reported via pti_error after create; no C++ exception may
// cross the C ABI.
static void* pti_create_impl(const char* model_dir, const char* params_file,
                             pti::Runtime* rt);

void* pti_create(const char* model_dir, const char* params_file) {
  auto* rt = new pti::Runtime();
  try {
    pti_create_impl(model_dir, params_file, rt);
  } catch (const std::exception& e) {
    rt->error = std::string("corrupt model: ") + e.what();
  } catch (...) {
    rt->error = "corrupt model: unknown C++ exception";
  }
  rt->load_failed = !rt->error.empty();
  return rt;
}

static void* pti_create_impl(const char* model_dir, const char* params_file,
                             pti::Runtime* rt) {
  std::string dir(model_dir);
  std::string model_path = dir + "/__model__";
  FILE* f = fopen(model_path.c_str(), "rb");
  if (!f) {
    rt->error = "cannot open " + model_path;
    return rt;
  }
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string blob(sz, '\0');
  if (fread(blob.data(), 1, sz, f) != static_cast<size_t>(sz)) {
    fclose(f);
    rt->error = "short read on __model__";
    return rt;
  }
  fclose(f);
  if (!pti::parse_program(blob, &rt->blocks)) {
    rt->error = "cannot parse __model__ ProgramDesc";
    return rt;
  }
  // feed/fetch names (col-ordered)
  std::vector<std::pair<int, std::string>> feeds, fetches;
  for (const auto& op : rt->blocks[0].ops) {
    if (op.type == "feed")
      feeds.push_back({static_cast<int>(op.attr_i("col", 0)), op.out("Out")});
    else if (op.type == "fetch")
      fetches.push_back({static_cast<int>(op.attr_i("col", 0)), op.in("X")});
  }
  std::sort(feeds.begin(), feeds.end());
  std::sort(fetches.begin(), fetches.end());
  for (auto& p : feeds) rt->feed_names.push_back(p.second);
  for (auto& p : fetches) rt->fetch_names.push_back(p.second);

  // persistable LOD_TENSOR vars referenced by compute ops = params to load
  // (the feed/fetch holder vars are persistable too — FEED_MINIBATCH=9 /
  // FETCH_LIST=10 — but have no file on disk)
  std::map<std::string, bool> used;
  for (const auto& op : rt->blocks[0].ops) {
    if (op.type == "feed" || op.type == "fetch") continue;
    for (const auto& kv : op.inputs)
      for (const auto& n : kv.second) used[n] = true;
  }
  std::vector<std::string> params;
  for (const auto& v : rt->blocks[0].vars)
    if (v.persistable && v.kind == 7 && used.count(v.name))
      params.push_back(v.name);
  std::sort(params.begin(), params.end());

  std::string err;
  if (params_file && params_file[0]) {
    std::string path = dir + "/" + params_file;
    FILE* pf = fopen(path.c_str(), "rb");
    if (!pf) {
      rt->error = "cannot open " + path;
      return rt;
    }
    for (const auto& name : params) {
      pti::Tensor t;
      if (!pti::read_lod_tensor(pf, &t, &err)) {
        rt->error = "param " + name + ": " + err;
        fclose(pf);
        return rt;
      }
      rt->scope[name] = std::move(t);
    }
    fclose(pf);
  } else {
    for (const auto& name : params) {
      std::string path = dir + "/" + name;
      FILE* pf = fopen(path.c_str(), "rb");
      if (!pf) {
        rt->error = "cannot open param file " + path;
        return rt;
      }
      pti::Tensor t;
      bool ok = pti::read_lod_tensor(pf, &t, &err);
      fclose(pf);
      if (!ok) {
        rt->error = "param " + name + ": " + err;
        return rt;
      }
      rt->scope[name] = std::move(t);
    }
  }
  return rt;
}

const char* pti_error(void* h) {
  return static_cast<pti::Runtime*>(h)->error.c_str();
}

int pti_num_inputs(void* h) {
  return static_cast<int>(static_cast<pti::Runtime*>(h)->feed_names.size());
}
const char* pti_input_name(void* h, int i) {
  return static_cast<pti::Runtime*>(h)->feed_names[i].c_str();
}
int pti_num_outputs(void* h) {
  return static_cast<int>(static_cast<pti::Runtime*>(h)->fetch_names.size());
}
const char* pti_output_name(void* h, int i) {
  return static_cast<pti::Runtime*>(h)->fetch_names[i].c_str();
}

// dtype: 0 = float32, 1 = int64
int pti_set_input(void* h, const char* name, const void* data,
                  const int64_t* dims, int ndims, int dtype) {
  auto* rt = static_cast<pti::Runtime*>(h);
  pti::Tensor t;
  t.dims.assign(dims, dims + ndims);
  int64_t n = t.numel();
  if (dtype == 0) {
    t.is_f = true;
    t.f.assign(static_cast<const float*>(data),
               static_cast<const float*>(data) + n);
  } else {
    t.is_f = false;
    t.i.assign(static_cast<const int64_t*>(data),
               static_cast<const int64_t*>(data) + n);
  }
  rt->scope[name] = std::move(t);
  return 0;
}

int pti_run(void* h) {
  auto* rt = static_cast<pti::Runtime*>(h);
  if (!rt->load_failed) rt->error.clear();  // run errors are not sticky
  if (!rt->error.empty()) return 1;
  try {
    return rt->run() ? 0 : 1;
  } catch (const std::exception& e) {
    rt->error = std::string("native kernel exception: ") + e.what();
    return 1;
  } catch (...) {
    rt->error = "native kernel exception";
    return 1;
  }
}

// returns element count (<0 on error); *data points into runtime-owned
// memory, valid until the next pti_run/pti_free
int64_t pti_get_output(void* h, const char* name, const void** data,
                       const int64_t** dims, int* ndims, int* dtype) {
  auto* rt = static_cast<pti::Runtime*>(h);
  pti::Tensor* t = rt->var(name);
  if (!t) {
    rt->error = "no output var " + std::string(name);
    return -1;
  }
  *dims = t->dims.data();
  *ndims = static_cast<int>(t->dims.size());
  if (t->is_f) {
    *data = t->f.data();
    *dtype = 0;
  } else {
    *data = t->i.data();
    *dtype = 1;
  }
  return t->numel();
}

void pti_free(void* h) { delete static_cast<pti::Runtime*>(h); }

}  // extern "C"
