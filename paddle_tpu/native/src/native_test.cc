// Native-runtime unit tests — the reference's C++ test pattern
// (operators/distributed/rpc_server_test.cc: in-process client+server;
// recordio tests; blocking-queue tests) without a gtest dependency: plain
// CHECK macros, exit code 0 on success.  Built and run by
// tests/test_native_cc.py with the same g++ invocation as the library.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "native_api.h"

#define CHECK(cond)                                                       \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,       \
                   __LINE__, #cond);                                      \
      std::exit(1);                                                       \
    }                                                                     \
  } while (0)

static void test_recordio(const char* tmpdir) {
  std::string path = std::string(tmpdir) + "/t.recordio";
  void* w = ptq_recordio_writer_open(path.c_str(), 1);
  CHECK(w != nullptr);
  CHECK(ptq_recordio_writer_write(w, "hello", 5) == 0);
  std::string big(100000, 'x');
  CHECK(ptq_recordio_writer_write(w, big.data(), (int64_t)big.size()) == 0);
  CHECK(ptq_recordio_writer_close(w) == 0);

  void* s = ptq_recordio_scanner_open(path.c_str());
  CHECK(s != nullptr);
  char* out = nullptr;  // scanner-owned buffer: do NOT free
  CHECK(ptq_recordio_scanner_next(s, &out) == 5);
  CHECK(std::memcmp(out, "hello", 5) == 0);
  CHECK(ptq_recordio_scanner_next(s, &out) == (int64_t)big.size());
  CHECK(ptq_recordio_scanner_next(s, &out) == -1);  // EOF
  ptq_recordio_scanner_close(s);
  std::puts("recordio ok");
}

static void test_queue() {
  // push: 0 ok / 1 timeout / 2 closed; pop: length / -1 timeout / -2 closed
  void* q = ptq_queue_new(2);
  CHECK(ptq_queue_push(q, "a", 1, 0.1) == 0);
  CHECK(ptq_queue_push(q, "b", 1, 0.1) == 0);
  CHECK(ptq_queue_push(q, "c", 1, 0.01) == 1);  // full → timeout
  char* out = nullptr;
  CHECK(ptq_queue_pop(q, &out, 0.1) == 1 && out[0] == 'a');
  ptq_free(out);
  // producer thread unblocks a waiting consumer
  std::thread prod([&] { CHECK(ptq_queue_push(q, "z", 1, 1.0) == 0); });
  CHECK(ptq_queue_pop(q, &out, 1.0) == 1 && out[0] == 'b');
  ptq_free(out);
  CHECK(ptq_queue_pop(q, &out, 1.0) == 1 && out[0] == 'z');
  ptq_free(out);
  prod.join();
  ptq_queue_close(q);
  CHECK(ptq_queue_pop(q, &out, 0.05) == -2);  // closed + drained
  ptq_queue_free(q);
  std::puts("queue ok");
}

static void test_ps_sync_round() {
  // rpc_server_test.cc pattern: server driver thread + 2 client threads in
  // one process, one full sync round over real loopback sockets
  void* srv = pts_server_start(0, 2);
  CHECK(srv != nullptr);
  int port = pts_server_port(srv);

  std::thread driver([&] {
    CHECK(pts_server_wait_round(srv) == 1);
    CHECK(pts_server_grad_count(srv) == 2);
    char *name = nullptr, *data = nullptr;
    int64_t n = pts_server_grad_at(srv, 0, &name, &data);
    CHECK(n == 4);
    int64_t nlen = pts_server_grad_name_len(srv, 0);
    CHECK(std::string(name, (size_t)nlen) == "g");
    ptq_free(name);
    ptq_free(data);
    pts_server_publish(srv, "p", "PPPP", 4);
    pts_server_bump_version(srv);
    pts_server_release_send(srv);
    CHECK(pts_server_end_round(srv) == 1);
  });

  auto trainer = [&](int id) {
    void* c = pts_connect("127.0.0.1", port, 5.0);
    CHECK(c != nullptr);
    CHECK(pts_request(c, kSendGrad, "g", 0, 0, "GGGG", 4, nullptr,
                      nullptr) == 0);
    CHECK(pts_request(c, kSendBarrier, "", 0, 0, nullptr, 0, nullptr,
                      nullptr)
          == 0);
    char* out = nullptr;
    int64_t olen = 0;
    CHECK(pts_request(c, kGetParam, "p", 1, 0, nullptr, 0, &out, &olen) == 0);
    CHECK(olen == 4 && std::memcmp(out, "PPPP", 4) == 0);
    ptq_free(out);
    CHECK(pts_request(c, kFetchBarrier, "", 0, 0, nullptr, 0, nullptr,
                      nullptr)
          == 0);
    pts_client_close(c);
  };
  std::thread t0(trainer, 0), t1(trainer, 1);
  t0.join();
  t1.join();
  driver.join();
  pts_server_stop(srv);
  std::puts("ps sync round ok");
}

static void test_ps_async_pop_and_lookup() {
  void* srv = pts_server_start(0, 1);
  int port = pts_server_port(srv);
  void* c = pts_connect("127.0.0.1", port, 5.0);
  CHECK(c != nullptr);

  // async pop: timeout first, then a pushed grad wakes the pop
  char *name = nullptr, *data = nullptr;
  CHECK(pts_server_pop_grad(srv, 30, &name, &data) == -1);  // timeout
  CHECK(pts_request(c, kSendGrad, "w@GRAD", 0, 0, "abcd", 4, nullptr,
                    nullptr)
        == 0);
  int64_t n = pts_server_pop_grad(srv, 1000, &name, &data);
  CHECK(n == 4 && std::string(name) == "w@GRAD");
  CHECK(std::memcmp(data, "abcd", 4) == 0);
  ptq_free(name);
  ptq_free(data);

  // native row lookup: 3 rows of 4 bytes behind a 2-byte header
  //   blob = header "HD" + rows "AAAA" "BBBB" "CCCC"
  pts_server_publish(srv, "emb", "HDAAAABBBBCCCC", 14);
  uint64_t packed = ((uint64_t)2 << 32) | 4;  // offset 2, width 4
  int64_t ids[2] = {2, 0};
  char* out = nullptr;
  int64_t olen = 0;
  CHECK(pts_request(c, kLookupRows, "emb", packed, 0,
                    (const char*)ids, sizeof(ids), &out, &olen) == 0);
  CHECK(olen == 8 && std::memcmp(out, "CCCCAAAA", 8) == 0);
  ptq_free(out);
  // out-of-range id → error status
  int64_t bad[1] = {7};
  CHECK(pts_request(c, kLookupRows, "emb", packed, 0,
                    (const char*)bad, sizeof(bad), &out, &olen) == 1);
  ptq_free(out);

  pts_request(c, kStop, "", 0, 0, nullptr, 0, nullptr, nullptr);
  pts_client_close(c);
  pts_server_stop(srv);
  std::puts("ps async pop + lookup ok");
}

static void test_ps_barrier_deadline_and_rewait() {
  // liveness deadline: with only 1 of 2 trainers arriving, the barrier
  // wait answers status 2 (retryable timeout) instead of parking forever;
  // a REWAIT retry must not re-count the arrival
  void* srv = pts_server_start(0, 2);
  CHECK(srv != nullptr);
  pts_server_set_barrier_timeout_ms(srv, 100);
  int port = pts_server_port(srv);
  void* c = pts_connect("127.0.0.1", port, 5.0);
  CHECK(c != nullptr);
  CHECK(pts_request(c, kSendBarrier, "", 0, 0, nullptr, 0, nullptr,
                      nullptr)
        == 2);  // timed out: stale-peer detection
  CHECK(pts_server_stat(srv, 0) == 1);  // send-barrier timeout counted
  // rewait (high bit set): times out again, still exactly one arrival
  CHECK(pts_request(c, kSendBarrier, "", kPtsRewaitBit, 0, nullptr, 0,
                    nullptr, nullptr) == 2);
  CHECK(pts_server_stat(srv, 0) == 2);
  // versioned GET_PARAM also honors the deadline
  char* out = nullptr;
  int64_t olen = 0;
  CHECK(pts_request(c, kGetParam, "nope", 9, 0, nullptr, 0, &out, &olen) == 2);
  ptq_free(out);
  CHECK(pts_server_stat(srv, 2) == 1);
  pts_client_close(c);
  pts_server_stop(srv);
  std::puts("ps barrier deadline + rewait ok");
}

static void test_ps_elastic_membership() {
  // elastic quorum: two members join the idle job (activated immediately),
  // run a round; one leaves gracefully — the next round completes with a
  // quorum of ONE, and the membership blob reports the new epoch/count.
  // Also: the span field of every served frame lands in the span journal.
  void* srv = pts_server_start(0, 99);  // n_trainers ignored once elastic
  CHECK(srv != nullptr);
  pts_server_enable_elastic(srv, 0);  // no lease expiry in this test
  int port = pts_server_port(srv);
  void* a = pts_connect("127.0.0.1", port, 5.0);
  void* b = pts_connect("127.0.0.1", port, 5.0);
  CHECK(a && b);
  char* out = nullptr;
  int64_t olen = 0;
  CHECK(pts_request(a, kJoin, "uid:a", 0, 7001, nullptr, 0, &out, &olen)
        == 0);
  CHECK(olen == 40);
  uint64_t info[5];
  std::memcpy(info, out, 40);
  ptq_free(out);
  CHECK(info[3] == 1 && info[4] == 0);  // count 1, index 0 (idle-activated)
  CHECK(pts_request(b, kJoin, "uid:b", 0, 7002, nullptr, 0, &out, &olen)
        == 0);
  std::memcpy(info, out, 40);
  ptq_free(out);
  CHECK(info[3] == 2);              // both active
  CHECK(pts_server_stat(srv, 6) == 2);  // active members
  CHECK(pts_server_stat(srv, 7) == 2);  // joins

  auto run_round = [&](int quorum, uint64_t r) {
    // r = the members' completed-round count: elastic fetch barriers for
    // an already-closed round ack immediately, so frames must carry the
    // real round (exactly what the Python client's counter does)
    std::vector<std::thread> ts;
    const char* uids[2] = {"uid:a", "uid:b"};
    void* conns[2] = {a, b};
    for (int i = 0; i < quorum; ++i) {
      ts.emplace_back([&, i] {
        CHECK(pts_request(conns[i], kSendBarrier, uids[i], r, 0, nullptr, 0,
                          nullptr, nullptr) == 0);
        CHECK(pts_request(conns[i], kFetchBarrier, uids[i], r, 0, nullptr,
                          0, nullptr, nullptr) == 0);
      });
    }
    CHECK(pts_server_wait_round(srv) == 1);
    pts_server_release_send(srv);
    CHECK(pts_server_end_round(srv) == 1);
    for (auto& t : ts) t.join();
  };
  run_round(2, 0);
  CHECK(pts_server_stat(srv, 3) == 1);  // one completed round
  // graceful leave: queued, applied at the NEXT round boundary — member b
  // still counts for the in-flight round it announced the leave in
  CHECK(pts_request(b, kLeave, "uid:b", 0, 0, nullptr, 0, nullptr, nullptr)
        == 0);
  run_round(2, 1);  // b participates in its announced round
  CHECK(pts_server_stat(srv, 6) == 1);  // leave applied at the boundary
  CHECK(pts_server_stat(srv, 8) == 1);  // leaves counted
  run_round(1, 2);  // the shrunk quorum completes alone
  CHECK(pts_server_stat(srv, 3) == 3);
  // span journal captured the traced join frames
  uint64_t spans[4 * 64];
  int64_t n = pts_server_drain_spans(srv, spans, 64);
  CHECK(n >= 2);
  bool saw = false;
  for (int64_t i = 0; i < n; ++i)
    if (spans[i * 4] == kJoin && spans[i * 4 + 1] == 7001) saw = true;
  CHECK(saw);
  pts_client_close(a);
  pts_client_close(b);
  pts_server_stop(srv);
  std::puts("ps elastic membership ok");
}

static void test_ps_commit_epoch(const char* tmpdir) {
  // quorum-committed epoch record: proposals are monotone in round, a
  // query returns the stored record, snapshots round-trip it (v2), and
  // reconcile fast-forwards a stale restored shard's round counter.
  void* srv = pts_server_start(0, 1);
  CHECK(srv != nullptr);
  pts_server_enable_elastic(srv, 0);
  int port = pts_server_port(srv);
  void* c = pts_connect("127.0.0.1", port, 5.0);
  CHECK(c != nullptr);
  char* out = nullptr;
  int64_t olen = 0;
  // empty query on a fresh server: all-zero record
  CHECK(pts_request(c, kCommitEpoch, "uid:t", 0, 0, nullptr, 0, &out, &olen)
        == 0);
  CHECK(olen == 24);
  uint64_t rec[3];
  std::memcpy(rec, out, 24);
  ptq_free(out);
  CHECK(rec[0] == 0 && rec[1] == 0 && rec[2] == 0);
  // propose (epoch 2, round 5, pos 5) — accepted and echoed back
  uint64_t prop[3] = {2, 5, 5};
  CHECK(pts_request(c, kCommitEpoch, "uid:t", 0, 0, (const char*)prop, 24,
                    &out, &olen) == 0);
  std::memcpy(rec, out, 24);
  ptq_free(out);
  CHECK(rec[0] == 2 && rec[1] == 5 && rec[2] == 5);
  // a STALE proposal (round 3 < 5) must not roll the record back
  uint64_t stale[3] = {9, 3, 3};
  CHECK(pts_request(c, kCommitEpoch, "uid:t", 0, 0, (const char*)stale, 24,
                    &out, &olen) == 0);
  std::memcpy(rec, out, 24);
  ptq_free(out);
  CHECK(rec[1] == 5 && rec[2] == 5);
  // malformed record length → error status
  CHECK(pts_request(c, kCommitEpoch, "uid:t", 0, 0, "xyz", 3, &out, &olen)
        == 1);
  ptq_free(out);
  CHECK(pts_server_stat(srv, 10) == 2);  // committed epoch
  CHECK(pts_server_stat(srv, 11) == 5);  // committed round
  // snapshot v2 round-trips the record into a fresh server
  std::string snap = std::string(tmpdir) + "/commit.ckpt";
  CHECK(pts_server_save(srv, snap.c_str()) == 1);
  void* srv2 = pts_server_start(0, 1);
  CHECK(srv2 != nullptr);
  pts_server_enable_elastic(srv2, 0);
  CHECK(pts_server_load(srv2, snap.c_str()) == 1);
  CHECK(pts_server_stat(srv2, 11) == 5);
  CHECK(pts_server_stat(srv2, 12) == 5);
  // reconcile: the quorum says round 8 — the restored shard's round
  // counter (0, from the empty snapshot's table section) fast-forwards
  CHECK(pts_server_reconcile_committed(srv2, 3, 8, 8) == 1);
  CHECK(pts_server_stat(srv2, 3) == 8);   // round_id adopted
  CHECK(pts_server_stat(srv2, 11) == 8);  // committed record adopted
  // idempotent: already at the quorum → no movement
  CHECK(pts_server_reconcile_committed(srv2, 3, 8, 8) == 0);
  pts_server_stop(srv2);
  pts_client_close(c);
  pts_server_stop(srv);
  std::puts("ps commit epoch ok");
}

int main(int argc, char** argv) {
  const char* tmpdir = argc > 1 ? argv[1] : "/tmp";
  test_recordio(tmpdir);
  test_queue();
  test_ps_sync_round();
  test_ps_async_pop_and_lookup();
  test_ps_barrier_deadline_and_rewait();
  test_ps_elastic_membership();
  test_ps_commit_epoch(tmpdir);
  std::puts("ALL NATIVE TESTS PASSED");
  return 0;
}
