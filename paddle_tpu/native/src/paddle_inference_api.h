// C++ convenience API over the native inference runtime.
//
// Reference analog: paddle/fluid/inference/api/paddle_inference_api.h —
// CreatePaddlePredictor<AnalysisConfig>() / PaddlePredictor::Run over
// PaddleTensor.  This header keeps the same usage shape on top of the
// pti_* C ABI (native_api.h) so demo_ci-style C++ programs port directly:
//
//   AnalysisConfig cfg(model_dir);            // or (model_dir, params_file)
//   auto pred = CreatePaddlePredictor(cfg);
//   std::vector<PaddleTensor> in{...}, out;
//   pred->Run(in, &out);
//
// Header-only; link infer_runtime.cc.

#pragma once

#include <cstdint>
#include <cstring>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

#include "native_api.h"

namespace paddle_tpu {

enum class PaddleDType { FLOAT32 = 0, INT64 = 1 };

struct PaddleTensor {
  std::string name;
  std::vector<int64_t> shape;
  std::vector<float> f32;   // payload when dtype == FLOAT32
  std::vector<int64_t> i64; // payload when dtype == INT64
  PaddleDType dtype = PaddleDType::FLOAT32;
};

struct AnalysisConfig {
  std::string model_dir;
  std::string params_file;  // empty → per-var param files

  AnalysisConfig() = default;
  explicit AnalysisConfig(std::string dir) : model_dir(std::move(dir)) {}
  AnalysisConfig(std::string dir, std::string params)
      : model_dir(std::move(dir)), params_file(std::move(params)) {}
};

class PaddlePredictor {
 public:
  explicit PaddlePredictor(const AnalysisConfig& cfg) {
    h_ = pti_create(cfg.model_dir.c_str(),
                    cfg.params_file.empty() ? nullptr
                                            : cfg.params_file.c_str());
    const char* err = pti_error(h_);
    if (err && err[0]) {
      std::string msg(err);
      pti_free(h_);
      h_ = nullptr;
      throw std::runtime_error("PaddlePredictor: " + msg);
    }
  }

  ~PaddlePredictor() {
    if (h_) pti_free(h_);
  }

  PaddlePredictor(const PaddlePredictor&) = delete;
  PaddlePredictor& operator=(const PaddlePredictor&) = delete;

  std::vector<std::string> GetInputNames() const {
    std::vector<std::string> out;
    for (int i = 0; i < pti_num_inputs(h_); ++i)
      out.emplace_back(pti_input_name(h_, i));
    return out;
  }

  std::vector<std::string> GetOutputNames() const {
    std::vector<std::string> out;
    for (int i = 0; i < pti_num_outputs(h_); ++i)
      out.emplace_back(pti_output_name(h_, i));
    return out;
  }

  bool Run(const std::vector<PaddleTensor>& inputs,
           std::vector<PaddleTensor>* outputs) {
    for (const auto& t : inputs) {
      const void* data = t.dtype == PaddleDType::FLOAT32
                             ? static_cast<const void*>(t.f32.data())
                             : static_cast<const void*>(t.i64.data());
      pti_set_input(h_, t.name.c_str(), data, t.shape.data(),
                    static_cast<int>(t.shape.size()),
                    static_cast<int>(t.dtype));
    }
    if (pti_run(h_) != 0) return false;
    outputs->clear();
    for (const auto& name : GetOutputNames()) {
      const void* data;
      const int64_t* dims;
      int ndims, dtype;
      int64_t n = pti_get_output(h_, name.c_str(), &data, &dims, &ndims,
                                 &dtype);
      if (n < 0) return false;
      PaddleTensor t;
      t.name = name;
      t.shape.assign(dims, dims + ndims);
      t.dtype = static_cast<PaddleDType>(dtype);
      if (t.dtype == PaddleDType::FLOAT32) {
        t.f32.resize(n);
        memcpy(t.f32.data(), data, n * sizeof(float));
      } else {
        t.i64.resize(n);
        memcpy(t.i64.data(), data, n * sizeof(int64_t));
      }
      outputs->push_back(std::move(t));
    }
    return true;
  }

  const char* error() const { return pti_error(h_); }

 private:
  void* h_ = nullptr;
};

inline std::unique_ptr<PaddlePredictor> CreatePaddlePredictor(
    const AnalysisConfig& config) {
  return std::make_unique<PaddlePredictor>(config);
}

}  // namespace paddle_tpu
