// paddle_tpu native data runtime: RecordIO + blocking queue + MultiSlot feed.
//
// Reference analogs (re-designed, not ported):
//   - paddle/fluid/recordio/{chunk,writer,scanner}.cc : chunked record file
//     with per-chunk CRC + optional compression
//   - paddle/fluid/operators/reader/lod_tensor_blocking_queue.h : bounded
//     producer/consumer queue feeding the device pipeline
//   - paddle/fluid/framework/data_feed.cc (MultiSlotDataFeed::ParseOneInstance)
//     : text-slot parser with background reader threads
//
// TPU-native shape: the C++ side produces *batches as flat byte buffers*
// (dense values + per-sample lengths), which Python turns into padded numpy
// arrays feeding the XLA program — the LoD→padding translation happens once,
// here, off the critical Python thread.
//
// C ABI only (ctypes-friendly); all buffers returned via ptq_buf are malloc'd
// and freed with ptq_free.

#include "native_api.h"

#include <zlib.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// small helpers
// ---------------------------------------------------------------------------

void ptq_free(char* p) { free(p); }

static char* dup_buf(const std::string& s) {
  char* out = static_cast<char*>(malloc(s.size()));
  if (out && !s.empty()) memcpy(out, s.data(), s.size());
  return out;
}

// ---------------------------------------------------------------------------
// RecordIO: file = sequence of chunks.
// chunk header: magic u32 'PTRC', num_records u32, raw_len u64,
//               comp_len u64, crc32 u32 (of compressed payload), flags u8
// payload: records, each u32 len + bytes; deflate-compressed when flags&1.
// ---------------------------------------------------------------------------

static const uint32_t kChunkMagic = 0x50545243;  // "PTRC"

struct RecordWriter {
  FILE* f = nullptr;
  std::string pending;  // serialized records of the open chunk
  uint32_t n_records = 0;
  int compressor = 1;           // 0 none, 1 zlib
  size_t chunk_bytes = 1 << 20;  // flush threshold

  int flush_chunk() {
    if (n_records == 0) return 0;
    std::string payload = pending;
    uint8_t flags = 0;
    if (compressor == 1) {
      uLongf bound = compressBound(pending.size());
      std::string comp(bound, '\0');
      if (compress2(reinterpret_cast<Bytef*>(&comp[0]), &bound,
                    reinterpret_cast<const Bytef*>(pending.data()),
                    pending.size(), Z_BEST_SPEED) == Z_OK) {
        comp.resize(bound);
        payload.swap(comp);
        flags = 1;
      }
    }
    uint32_t crc = crc32(0L, reinterpret_cast<const Bytef*>(payload.data()),
                         payload.size());
    uint64_t raw_len = pending.size(), comp_len = payload.size();
    if (fwrite(&kChunkMagic, 4, 1, f) != 1) return -1;
    if (fwrite(&n_records, 4, 1, f) != 1) return -1;
    if (fwrite(&raw_len, 8, 1, f) != 1) return -1;
    if (fwrite(&comp_len, 8, 1, f) != 1) return -1;
    if (fwrite(&crc, 4, 1, f) != 1) return -1;
    if (fwrite(&flags, 1, 1, f) != 1) return -1;
    if (comp_len && fwrite(payload.data(), comp_len, 1, f) != 1) return -1;
    pending.clear();
    n_records = 0;
    return 0;
  }
};

void* ptq_recordio_writer_open(const char* path, int compressor) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new RecordWriter();
  w->f = f;
  w->compressor = compressor;
  return w;
}

int ptq_recordio_writer_write(void* handle, const char* data, int64_t len) {
  auto* w = static_cast<RecordWriter*>(handle);
  uint32_t l = static_cast<uint32_t>(len);
  w->pending.append(reinterpret_cast<const char*>(&l), 4);
  w->pending.append(data, len);
  w->n_records++;
  if (w->pending.size() >= w->chunk_bytes) return w->flush_chunk();
  return 0;
}

int ptq_recordio_writer_close(void* handle) {
  auto* w = static_cast<RecordWriter*>(handle);
  int rc = w->flush_chunk();
  fclose(w->f);
  delete w;
  return rc;
}

struct RecordScanner {
  FILE* f = nullptr;
  uint64_t file_size = 0;
  std::string chunk;     // decompressed records of current chunk
  size_t offset = 0;
  std::string current;   // last record returned

  // returns 0 ok, 1 eof, -1 corrupt
  int load_chunk() {
    uint32_t magic = 0, n_records = 0, crc = 0;
    uint64_t raw_len = 0, comp_len = 0;
    uint8_t flags = 0;
    if (fread(&magic, 4, 1, f) != 1) return 1;  // clean EOF
    if (magic != kChunkMagic) return -1;
    if (fread(&n_records, 4, 1, f) != 1) return -1;
    if (fread(&raw_len, 8, 1, f) != 1) return -1;
    if (fread(&comp_len, 8, 1, f) != 1) return -1;
    if (fread(&crc, 4, 1, f) != 1) return -1;
    if (fread(&flags, 1, 1, f) != 1) return -1;
    // bound header lengths before allocating: a corrupt length field must
    // surface as -1, not as std::bad_alloc aborting through the C ABI.
    // comp_len can't exceed what's left of the file; raw_len can't exceed
    // a sane decompression blow-up of it.
    long at = ftell(f);
    if (at < 0 || comp_len > file_size - static_cast<uint64_t>(at)) return -1;
    // deflate's max expansion is ~1032:1; 1056x + slack stays above it so a
    // maximally-compressible (e.g. all-zero) chunk still round-trips
    if (raw_len > comp_len * 1056 + (1ull << 16)) return -1;
    std::string payload(comp_len, '\0');
    if (comp_len && fread(&payload[0], comp_len, 1, f) != 1) return -1;
    uint32_t got = crc32(0L, reinterpret_cast<const Bytef*>(payload.data()),
                         payload.size());
    if (got != crc) return -1;
    if (flags & 1) {
      std::string raw(raw_len, '\0');
      uLongf out_len = raw_len;
      if (uncompress(reinterpret_cast<Bytef*>(&raw[0]), &out_len,
                     reinterpret_cast<const Bytef*>(payload.data()),
                     payload.size()) != Z_OK || out_len != raw_len)
        return -1;
      chunk.swap(raw);
    } else {
      chunk.swap(payload);
    }
    offset = 0;
    return 0;
  }
};

void* ptq_recordio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* s = new RecordScanner();
  s->f = f;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  s->file_size = sz > 0 ? static_cast<uint64_t>(sz) : 0;
  return s;
}

// returns record length (>=0), -1 on EOF, -2 on corruption
int64_t ptq_recordio_scanner_next(void* handle, char** out) {
  auto* s = static_cast<RecordScanner*>(handle);
  while (s->offset >= s->chunk.size()) {
    int rc = s->load_chunk();
    if (rc == 1) return -1;
    if (rc < 0) return -2;
  }
  if (s->offset + 4 > s->chunk.size()) return -2;
  uint32_t len = 0;
  memcpy(&len, s->chunk.data() + s->offset, 4);
  s->offset += 4;
  if (s->offset + len > s->chunk.size()) return -2;
  s->current.assign(s->chunk.data() + s->offset, len);
  s->offset += len;
  *out = &s->current[0];
  return len;
}

void ptq_recordio_scanner_close(void* handle) {
  auto* s = static_cast<RecordScanner*>(handle);
  fclose(s->f);
  delete s;
}

// ---------------------------------------------------------------------------
// Blocking queue of byte blobs (LoDTensorBlockingQueue analog)
// ---------------------------------------------------------------------------

struct BlockingQueue {
  std::mutex mu;
  std::condition_variable cv_push, cv_pop, cv_idle;
  std::deque<std::string> items;
  size_t capacity;
  bool closed = false;
  int waiters = 0;  // threads blocked in push/pop: free() must wait for them

  explicit BlockingQueue(size_t cap) : capacity(cap) {}

  struct WaiterGuard {
    BlockingQueue* q;
    explicit WaiterGuard(BlockingQueue* q_) : q(q_) { q->waiters++; }
    ~WaiterGuard() {
      if (--q->waiters == 0) q->cv_idle.notify_all();
    }
  };
};

void* ptq_queue_new(int64_t capacity) {
  return new BlockingQueue(static_cast<size_t>(capacity));
}

// 0 ok, 1 timeout, 2 closed
int ptq_queue_push(void* handle, const char* data, int64_t len,
                   double timeout_s) {
  auto* q = static_cast<BlockingQueue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  BlockingQueue::WaiterGuard guard(q);
  auto pred = [q] { return q->closed || q->items.size() < q->capacity; };
  if (timeout_s < 0) {
    q->cv_push.wait(lk, pred);
  } else if (!q->cv_push.wait_for(
                 lk, std::chrono::duration<double>(timeout_s), pred)) {
    return 1;
  }
  if (q->closed) return 2;
  q->items.emplace_back(data, static_cast<size_t>(len));
  q->cv_pop.notify_one();
  return 0;
}

// >=0 length, -1 timeout, -2 closed-and-empty
int64_t ptq_queue_pop(void* handle, char** out, double timeout_s) {
  auto* q = static_cast<BlockingQueue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  BlockingQueue::WaiterGuard guard(q);
  auto pred = [q] { return q->closed || !q->items.empty(); };
  if (timeout_s < 0) {
    q->cv_pop.wait(lk, pred);
  } else if (!q->cv_pop.wait_for(
                 lk, std::chrono::duration<double>(timeout_s), pred)) {
    return -1;
  }
  if (q->items.empty()) return -2;  // closed
  std::string item = std::move(q->items.front());
  q->items.pop_front();
  q->cv_push.notify_one();
  // keep the lock until WaiterGuard decrements `waiters` — it must not race
  // with ptq_queue_free's idle wait
  *out = dup_buf(item);
  return static_cast<int64_t>(item.size());
}

int64_t ptq_queue_size(void* handle) {
  auto* q = static_cast<BlockingQueue*>(handle);
  std::lock_guard<std::mutex> lk(q->mu);
  return static_cast<int64_t>(q->items.size());
}

int64_t ptq_queue_waiters(void* handle) {
  auto* q = static_cast<BlockingQueue*>(handle);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->waiters;
}

void ptq_queue_close(void* handle) {
  auto* q = static_cast<BlockingQueue*>(handle);
  {
    std::lock_guard<std::mutex> lk(q->mu);
    q->closed = true;
  }
  q->cv_push.notify_all();
  q->cv_pop.notify_all();
}

void ptq_queue_free(void* handle) {
  auto* q = static_cast<BlockingQueue*>(handle);
  {
    // close, then wait for every blocked push/pop to leave before the mutex
    // and condition variables are destroyed (use-after-free otherwise)
    std::unique_lock<std::mutex> lk(q->mu);
    q->closed = true;
    q->cv_push.notify_all();
    q->cv_pop.notify_all();
    q->cv_idle.wait(lk, [q] { return q->waiters == 0; });
  }
  delete q;
}

// ---------------------------------------------------------------------------
// MultiSlot text feed (data_feed.cc analog)
//
// Line format (reference MultiSlotDataFeed): for each slot in order:
//   <n> v_1 ... v_n
// Slot desc string: "name:f" (float32) or "name:u" (int64), ';'-separated.
//
// Batch wire format produced (little endian):
//   u32 nslots
//   per slot: u8 type ('f'|'u'), u32 batch,
//             u32 lens[batch], u32 total, values[total] (f32 or i64)
// ---------------------------------------------------------------------------

struct SlotDesc {
  std::string name;
  char type;  // 'f' or 'u'
};

struct SlotBatch {
  std::vector<uint32_t> lens;
  std::vector<float> fvals;
  std::vector<int64_t> ivals;
};

struct MultiSlotFeed {
  std::vector<std::string> files;
  std::vector<SlotDesc> slots;
  int batch_size;
  BlockingQueue queue;
  // N parser workers claim files from `next_file` (reference
  // framework/data_set.cc splits the filelist across thread_num DataFeeds;
  // same file-level parallelism, one shared output queue).  The LAST
  // worker to finish closes the queue.
  std::vector<std::thread> workers;
  std::atomic<int> next_file{0};
  std::atomic<int> active_workers{0};
  std::atomic<bool> stop{false};
  std::string error;
  std::mutex err_mu;

  MultiSlotFeed(size_t cap) : queue(cap) {}

  void set_error(const std::string& e) {
    {
      std::lock_guard<std::mutex> lk(err_mu);
      if (error.empty()) error = e;
    }
    stop.store(true);  // all workers wind down; no point parsing further
  }

  static bool parse_line(const char* line, const std::vector<SlotDesc>& slots,
                         std::vector<SlotBatch>* batch) {
    const char* p = line;
    char* end = nullptr;
    for (size_t si = 0; si < slots.size(); ++si) {
      long n = strtol(p, &end, 10);
      if (end == p || n < 0) return false;
      p = end;
      auto& sb = (*batch)[si];
      sb.lens.push_back(static_cast<uint32_t>(n));
      for (long i = 0; i < n; ++i) {
        if (slots[si].type == 'f') {
          float v = strtof(p, &end);
          if (end == p) return false;
          sb.fvals.push_back(v);
        } else {
          long long v = strtoll(p, &end, 10);
          if (end == p) return false;
          sb.ivals.push_back(v);
        }
        p = end;
      }
    }
    // a slot-count mismatch between file and config must error, not train on
    // silently misaligned data: only whitespace may remain
    while (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n') ++p;
    return *p == '\0';
  }

  std::string serialize(const std::vector<SlotBatch>& batch) const {
    std::string out;
    uint32_t nslots = slots.size();
    out.append(reinterpret_cast<const char*>(&nslots), 4);
    for (size_t si = 0; si < slots.size(); ++si) {
      const auto& sb = batch[si];
      uint8_t t = slots[si].type;
      uint32_t bs = sb.lens.size();
      out.append(reinterpret_cast<const char*>(&t), 1);
      out.append(reinterpret_cast<const char*>(&bs), 4);
      out.append(reinterpret_cast<const char*>(sb.lens.data()), 4 * bs);
      if (slots[si].type == 'f') {
        uint32_t total = sb.fvals.size();
        out.append(reinterpret_cast<const char*>(&total), 4);
        out.append(reinterpret_cast<const char*>(sb.fvals.data()), 4 * total);
      } else {
        uint32_t total = sb.ivals.size();
        out.append(reinterpret_cast<const char*>(&total), 4);
        out.append(reinterpret_cast<const char*>(sb.ivals.data()), 8 * total);
      }
    }
    return out;
  }

  bool has_error() {
    std::lock_guard<std::mutex> lk(err_mu);
    return !error.empty();
  }

  void run() {
    // one parser worker: claims whole files until none remain, carries its
    // partial batch across the files IT parsed (thread-local accumulator,
    // like the reference's per-thread DataFeed)
    std::vector<SlotBatch> batch(slots.size());
    int in_batch = 0;
    char* line = nullptr;     // getline-managed growable buffer: no 64 KiB
    size_t line_cap = 0;      // truncation of long ragged-slot lines
    for (;;) {
      int fi = next_file.fetch_add(1);
      if (fi >= static_cast<int>(files.size()) || stop.load()) break;
      const std::string& path = files[fi];
      FILE* f = fopen(path.c_str(), "r");
      if (!f) {
        set_error("cannot open " + path);
        break;
      }
      ssize_t nread;
      while (!stop.load() && (nread = getline(&line, &line_cap, f)) != -1) {
        if (nread == 0 || line[0] == '\n' || line[0] == '\0') continue;
        if (!parse_line(line, slots, &batch)) {
          set_error("parse error in " + path + ": " +
                    std::string(line, std::min<size_t>(nread, 60)));
          break;
        }
        if (++in_batch == batch_size) {
          std::string ser = serialize(batch);
          while (!stop.load() &&
                 ptq_queue_push(&queue, ser.data(), ser.size(), 0.1) == 1) {
          }
          for (auto& sb : batch) {
            sb.lens.clear();
            sb.fvals.clear();
            sb.ivals.clear();
          }
          in_batch = 0;
        }
      }
      fclose(f);
      if (has_error()) break;
    }
    // never flush a partial batch after an error: parse_line may have left
    // the slots with misaligned per-slot lengths for the failed line
    if (in_batch > 0 && !stop.load() && !has_error()) {
      std::string ser = serialize(batch);
      while (!stop.load() &&
             ptq_queue_push(&queue, ser.data(), ser.size(), 0.1) == 1) {
      }
    }
    free(line);
    if (active_workers.fetch_sub(1) == 1) ptq_queue_close(&queue);
  }
};

void* ptq_feed_new(const char** files, int nfiles, const char* slots_desc,
                   int batch_size, int64_t queue_cap, int n_threads) {
  auto* feed = new MultiSlotFeed(static_cast<size_t>(queue_cap));
  for (int i = 0; i < nfiles; ++i) feed->files.emplace_back(files[i]);
  std::string desc(slots_desc);
  size_t pos = 0;
  while (pos < desc.size()) {
    size_t semi = desc.find(';', pos);
    if (semi == std::string::npos) semi = desc.size();
    std::string item = desc.substr(pos, semi - pos);
    size_t colon = item.find(':');
    if (colon == std::string::npos || colon + 1 >= item.size() ||
        (item[colon + 1] != 'f' && item[colon + 1] != 'u')) {
      delete feed;
      return nullptr;
    }
    feed->slots.push_back({item.substr(0, colon), item[colon + 1]});
    pos = semi + 1;
  }
  if (feed->slots.empty()) {
    delete feed;
    return nullptr;
  }
  feed->batch_size = batch_size;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > nfiles && nfiles > 0) n_threads = nfiles;
  feed->active_workers.store(n_threads);
  for (int i = 0; i < n_threads; ++i)
    feed->workers.emplace_back([feed] { feed->run(); });
  return feed;
}

// >=0 length, -1 end-of-data, -3 worker error (fetch with ptq_feed_error)
int64_t ptq_feed_next(void* handle, char** out) {
  auto* feed = static_cast<MultiSlotFeed*>(handle);
  int64_t rc = ptq_queue_pop(&feed->queue, out, -1.0);
  if (rc == -2) {
    std::lock_guard<std::mutex> lk(feed->err_mu);
    return feed->error.empty() ? -1 : -3;
  }
  return rc;
}

int64_t ptq_feed_error(void* handle, char** out) {
  auto* feed = static_cast<MultiSlotFeed*>(handle);
  std::lock_guard<std::mutex> lk(feed->err_mu);
  *out = dup_buf(feed->error);
  return static_cast<int64_t>(feed->error.size());
}

void ptq_feed_free(void* handle) {
  auto* feed = static_cast<MultiSlotFeed*>(handle);
  feed->stop.store(true);
  ptq_queue_close(&feed->queue);
  for (auto& w : feed->workers)
    if (w.joinable()) w.join();
  delete feed;
}

}  // extern "C"
