// demo_ci: standalone C++ inference demo over the native runtime.
//
// Reference analog: inference/api/demo_ci/simple_on_word2vec.cc — the
// reference's shipped example of serving a saved model from C++ with no
// Python.  Usage:
//   demo_ci <model_dir> [params_file]
// Feeds deterministic inputs (0.01*i) to every model input, runs, and
// prints each output as "out <name> <numel> v0 v1 ... v7" for the test
// harness to compare against the Python executor.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "native_api.h"

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <model_dir> [params_file]\n", argv[0]);
    return 2;
  }
  void* p = pti_create(argv[1], argc > 2 ? argv[2] : nullptr);
  if (pti_error(p)[0]) {
    fprintf(stderr, "create failed: %s\n", pti_error(p));
    pti_free(p);
    return 1;
  }
  // deterministic demo batch: every float input gets batch=2 rows of
  // 0.01*i; shapes come from the harness via PTI_DEMO_DIMS ("name:2x16;...")
  const char* dims_env = getenv("PTI_DEMO_DIMS");
  if (!dims_env) {
    fprintf(stderr, "set PTI_DEMO_DIMS=name:2x16;...\n");
    pti_free(p);
    return 2;
  }
  std::string spec(dims_env);
  size_t pos = 0;
  while (pos < spec.size()) {
    size_t semi = spec.find(';', pos);
    if (semi == std::string::npos) semi = spec.size();
    std::string item = spec.substr(pos, semi - pos);
    pos = semi + 1;
    size_t colon = item.find(':');
    std::string name = item.substr(0, colon);
    std::vector<int64_t> dims;
    int64_t n = 1;
    for (size_t i = colon + 1; i < item.size();) {
      size_t x = item.find('x', i);
      if (x == std::string::npos) x = item.size();
      dims.push_back(atoll(item.substr(i, x - i).c_str()));
      n *= dims.back();
      i = x + 1;
    }
    std::vector<float> data(n);
    for (int64_t i = 0; i < n; ++i) data[i] = 0.01f * static_cast<float>(i);
    pti_set_input(p, name.c_str(), data.data(), dims.data(),
                  static_cast<int>(dims.size()), 0);
  }
  if (pti_run(p) != 0) {
    fprintf(stderr, "run failed: %s\n", pti_error(p));
    pti_free(p);
    return 1;
  }
  for (int i = 0; i < pti_num_outputs(p); ++i) {
    const char* name = pti_output_name(p, i);
    const void* data;
    const int64_t* dims;
    int ndims, dtype;
    int64_t n = pti_get_output(p, name, &data, &dims, &ndims, &dtype);
    if (n < 0) {
      fprintf(stderr, "get_output failed: %s\n", pti_error(p));
      pti_free(p);
      return 1;
    }
    printf("out %s %lld", name, static_cast<long long>(n));
    const float* f = static_cast<const float*>(data);
    for (int64_t j = 0; j < n && j < 8; ++j)
      printf(" %.6f", dtype == 0 ? f[j]
                                 : static_cast<float>(
                                       static_cast<const int64_t*>(data)[j]));
    printf("\n");
  }
  pti_free(p);
  printf("DEMO_CI_OK\n");
  return 0;
}
