// Parameter-server transport: native TCP RPC runtime.
//
// Reference analog: paddle/fluid/operators/distributed/ — the gRPC/BRPC
// SendRecvService (send_recv.proto.in:19: SendVariable / GetVariable),
// RequestHandler dispatch, and the listen_and_serv sync loop
// (listen_and_serv_op.cc:109 RunSyncLoop: wait kRequestSend barrier → run
// optimize blocks → release kRequestGet).  TPU-native redesign: the dense
// data path rides XLA collectives; this runtime exists for the
// parameter-server mode (sparse/CTR workloads, async geo-SGD) where a
// host-side service is the right architecture.  Tensors travel as opaque
// byte blobs (name + payload); aggregation and optimizer math happen in the
// driver above — the transport stays dumb and fast.
//
// Wire format (little-endian), one request per frame:
//   u8 cmd | u16 name_len | name | u64 round | u64 span | u64 data_len |
//   data
// response:
//   u8 status (0 ok, 1 stopped/error, 2 liveness-deadline timeout —
//   retryable) | u64 data_len | data
//
// `span` is the client's span id for the attempt (0 = untraced); served
// frames with a nonzero span are journaled (cmd, span, start, duration)
// and drained by the driver for post-mortem client↔server attribution.
//
// Sync-round protocol (mirrors RunSyncLoop):
//   trainers: SEND_GRAD*  SEND_BARRIER  GET_PARAM(round=r)*  FETCH_BARRIER
//   server driver: wait_round → drain grads → optimize → publish* →
//                  bump_version → release_send → end_round
//
// Barrier acks are RENDEZVOUS: a SEND_BARRIER is not acknowledged until the
// driver has processed the round (release_send), and a FETCH_BARRIER not
// until the driver closed the round (end_round).  Without this, a fast
// trainer could race into round r+1 — its barrier/grads arriving before the
// driver resets round state — and be silently wiped (lost-wakeup deadlock).
//
// Elastic membership (pts_server_enable_elastic): the barrier arrival
// count comes from the live MEMBER set instead of the fixed n_trainers.
// Members join under a lease (kJoin, renewed by kLease heartbeats and by
// barrier arrivals); a member whose lease expires while not parked in a
// barrier is EVICTED inside the driver's wait predicates, so the count
// renegotiates downward and the surviving round completes instead of
// timing out.  Joins and graceful leaves apply at ROUND BOUNDARIES
// (end_round, where every surviving trainer is parked in its fetch ack),
// bumping the membership epoch — so every trainer's per-round view of
// (epoch, index, count) is consistent.  The idle job (round 0, nothing
// arrived yet) activates joins immediately: the launch cohort rendezvous.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include "native_api.h"

#include <algorithm>
#include <array>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

bool read_n(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_n(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

char* dup_blob(const std::string& s) {
  char* p = static_cast<char*>(::malloc(s.size() ? s.size() : 1));
  if (p && !s.empty()) ::memcpy(p, s.data(), s.size());
  return p;
}

constexpr uint64_t kMaxBlob = 1ull << 33;  // 8 GiB sanity bound

struct Frame {
  uint8_t cmd;
  std::string name;
  uint64_t round;
  uint64_t span = 0;
  std::string data;
};

int64_t steady_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

uint64_t wall_us() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::microseconds>(
          std::chrono::system_clock::now().time_since_epoch())
          .count());
}

bool read_frame(int fd, Frame* f) {
  uint8_t cmd;
  uint16_t nlen;
  if (!read_n(fd, &cmd, 1) || !read_n(fd, &nlen, 2)) return false;
  f->cmd = cmd;
  f->name.resize(nlen);
  if (nlen && !read_n(fd, &f->name[0], nlen)) return false;
  uint64_t dlen;
  if (!read_n(fd, &f->round, 8) || !read_n(fd, &f->span, 8) ||
      !read_n(fd, &dlen, 8))
    return false;
  if (dlen > kMaxBlob) return false;
  f->data.resize(dlen);
  if (dlen && !read_n(fd, &f->data[0], dlen)) return false;
  return true;
}

bool write_response(int fd, uint8_t status, const std::string& data) {
  uint64_t dlen = data.size();
  return write_n(fd, &status, 1) && write_n(fd, &dlen, 8) &&
         (dlen == 0 || write_n(fd, data.data(), dlen));
}

struct PSServer {
  int listen_fd = -1;
  int port = 0;
  int n_trainers = 1;

  std::mutex mu;
  std::condition_variable cv;
  std::unordered_map<std::string, std::string> table;  // published params
  uint64_t version = 0;
  std::deque<std::pair<std::string, std::string>> grads;
  int send_arrived = 0;    // trainers parked in SEND_BARRIER this round
  int fetch_arrived = 0;   // trainers parked in FETCH_BARRIER this round
  // client identities counted this round: a trainer that reconnects and
  // re-arrives (its ack was lost with the connection, server survived)
  // must not be counted twice.  Barrier frames carry the client's uid in
  // the (otherwise unused) name field; an empty name skips the dedup.
  std::unordered_set<std::string> send_ids, fetch_ids;
  uint64_t round_id = 0;       // completed rounds
  uint64_t send_ack_round = 0;  // rounds whose send barrier was released
  bool stopped = false;
  // liveness deadline on server-side waits (barriers, versioned GET_PARAM).
  // 0 = wait forever (seed behavior).  On expiry the request is answered
  // with status 2 (retryable timeout) instead of parking the connection
  // forever behind a dead peer — the stale-trainer detector.
  int barrier_timeout_ms = 0;
  int64_t stat_send_barrier_timeouts = 0;
  int64_t stat_fetch_barrier_timeouts = 0;
  int64_t stat_get_timeouts = 0;

  // --- elastic membership state ------------------------------------- //
  // active members are the barrier arrival quorum; inactive entries are
  // PENDING joins awaiting the next round boundary.  std::map keeps uids
  // sorted so a member's index (its deterministic data-shard slot) is
  // its rank in the active iteration order.
  struct Member {
    int64_t deadline_ms = 0;  // steady-clock lease expiry; 0 = no lease
    bool active = false;
  };
  bool elastic = false;
  int lease_timeout_ms = 0;
  std::map<std::string, Member> members;
  std::unordered_set<std::string> pending_leaves;
  // PENDING members parked in a send barrier: their arrival was not
  // counted (they are not in the quorum yet) — when activation lands at
  // a boundary while they are STILL parked, the arrival retro-counts so
  // a re-forming job (every active member gone) can complete its first
  // round.  Cleared with send_ids at release_send: a released arrival
  // was consumed and must never retro-count later.
  std::unordered_set<std::string> pending_send_arrivals;
  uint64_t epoch = 0;
  // arrival count the in-flight round was completed with (wait_round /
  // end_round may renegotiate below n_trainers mid-round)
  int round_expected = 0;
  int64_t stat_joins = 0, stat_leaves = 0, stat_evictions = 0;
  // quorum-committed epoch record (kCommitEpoch): the cross-shard
  // data-authority agreement — epoch / round / dataset position the
  // trainers last proposed to EVERY shard, monotone in round.  A
  // relaunched shard reconciles its snapshot against the quorum's copy
  // of this record instead of trusting its own file.
  uint64_t committed_epoch = 0, committed_round = 0, committed_pos = 0;

  // accept a proposal when its round is not behind the stored record's;
  // the epoch field only ever moves forward (a proposer that has not
  // seen the latest membership flip must not roll the epoch back)
  void accept_commit(uint64_t ep, uint64_t rnd, uint64_t pos) {
    if (rnd < committed_round) return;
    committed_round = rnd;
    committed_pos = pos;
    if (ep > committed_epoch) committed_epoch = ep;
    if (epoch > committed_epoch) committed_epoch = epoch;
  }

  std::string committed_blob() const {
    uint64_t vals[3] = {committed_epoch, committed_round, committed_pos};
    return std::string(reinterpret_cast<const char*>(vals), sizeof(vals));
  }

  // span journal: (cmd, span id, wall start us, handling duration us) of
  // served frames carrying a nonzero span — drained by the driver
  std::deque<std::array<uint64_t, 4>> span_log;
  static constexpr size_t kMaxSpanLog = 8192;

  int active_count() const {
    int n = 0;
    for (auto& kv : members)
      if (kv.second.active) ++n;
    return n;
  }

  // the barrier arrival quorum: live members when elastic, else the
  // launch-time n_trainers (seed behavior, bit-for-bit)
  int expected() const { return elastic ? active_count() : n_trainers; }

  void renew_lease(const std::string& uid) {
    if (!elastic || uid.empty()) return;
    auto it = members.find(uid);
    if (it != members.end() && lease_timeout_ms > 0)
      it->second.deadline_ms = steady_ms() + lease_timeout_ms;
  }

  // true while nothing of the current round is in flight AND no round ever
  // completed — the launch-cohort window where membership can change
  // without any trainer holding a stale (epoch, index, count) view
  bool idle_at_start() const {
    return round_id == 0 && send_arrived == 0 && fetch_arrived == 0 &&
           grads.empty();
  }

  // ROUND-BOUNDARY membership transition: activate pending joins, apply
  // queued leaves, bump the epoch on any change.  Callers: end_round
  // (after round_id++, before releasing fetch acks — every survivor is
  // still parked, so nobody observes a half-applied epoch) and the
  // idle-at-start join/leave fast path.
  void apply_membership() {
    bool changed = false;
    for (auto& kv : members) {
      if (!kv.second.active) {
        kv.second.active = true;
        changed = true;
        // a newly-activated member still parked in its send barrier has
        // an uncounted arrival — count it now (it is in the quorum as of
        // this boundary, and it will not re-arrive)
        auto it = pending_send_arrivals.find(kv.first);
        if (it != pending_send_arrivals.end()) {
          if (send_ids.insert(kv.first).second) ++send_arrived;
          pending_send_arrivals.erase(it);
        }
      }
    }
    for (auto& uid : pending_leaves) {
      if (members.erase(uid)) {
        pending_send_arrivals.erase(uid);
        ++stat_leaves;
        changed = true;
      }
    }
    pending_leaves.clear();
    if (changed) ++epoch;
  }

  // lease sweep: evict expired members that are NOT parked in a barrier
  // (a parked member is provably connected; its arrival already counted,
  // so evicting it would corrupt the round math).  Runs inside the
  // driver's wait predicates so a renegotiated count completes the
  // surviving round.
  void prune_expired() {
    if (!elastic || lease_timeout_ms <= 0) return;
    int64_t now = steady_ms();
    bool changed = false;
    for (auto it = members.begin(); it != members.end();) {
      const std::string& uid = it->first;
      if (it->second.deadline_ms > 0 && now > it->second.deadline_ms &&
          !send_ids.count(uid) && !fetch_ids.count(uid)) {
        pending_leaves.erase(uid);
        pending_send_arrivals.erase(uid);
        it = members.erase(it);
        ++stat_evictions;
        changed = true;
      } else {
        ++it;
      }
    }
    if (changed) {
      ++epoch;
      cv.notify_all();
    }
  }

  // barrier-arrival membership bookkeeping: an arrival IS proof of life
  // (lease renewed); an arrival from a uid the member set has never seen
  // (server restarted from a pre-join snapshot, an evicted member's
  // delayed frame, or a caller that skipped the join protocol)
  // implicitly JOINS — but under the same activation rule as kJoin:
  // immediately only while the job is idle at round 0, PENDING (a
  // boundary activates it) otherwise.  Activating mid-round would
  // mutate the quorum and epoch other trainers already computed their
  // round view from, and an arrival counted after the round's quorum
  // was renegotiated would leak a permanent +1 into send_arrived.
  // Returns true when the uid is PENDING (arrival must not count).
  bool arrival_membership(const std::string& uid) {
    if (!elastic || uid.empty()) return false;
    auto mit = members.find(uid);
    if (mit == members.end()) {
      Member m;
      m.active = idle_at_start();
      if (lease_timeout_ms > 0)
        m.deadline_ms = steady_ms() + lease_timeout_ms;
      members.emplace(uid, m);
      ++stat_joins;
      if (m.active) ++epoch;
      return !m.active;
    }
    renew_lease(uid);
    return !mit->second.active;
  }

  // the 40-byte membership reply: epoch | round | version | count | index
  std::string membership_blob(const std::string& uid) {
    uint64_t vals[5];
    vals[0] = epoch;
    vals[1] = round_id;
    vals[2] = version;
    vals[3] = static_cast<uint64_t>(active_count());
    vals[4] = ~0ull;
    uint64_t idx = 0;
    for (auto& kv : members) {
      if (!kv.second.active) continue;
      if (kv.first == uid) {
        vals[4] = idx;
        break;
      }
      ++idx;
    }
    return std::string(reinterpret_cast<const char*>(vals), sizeof(vals));
  }

  // poll cadence for elastic driver waits: fine enough to evict within a
  // fraction of the lease, never busier than 10 ms
  std::chrono::milliseconds elastic_poll() const {
    int ms = lease_timeout_ms > 0 ? lease_timeout_ms / 4 : 500;
    return std::chrono::milliseconds(std::min(500, std::max(10, ms)));
  }

  // wait on cv with the liveness deadline; returns false on timeout
  template <class Pred>
  bool wait_alive(std::unique_lock<std::mutex>& lk, Pred pred) {
    if (barrier_timeout_ms <= 0) {
      cv.wait(lk, pred);
      return true;
    }
    return cv.wait_for(lk, std::chrono::milliseconds(barrier_timeout_ms),
                       pred);
  }

  std::thread accept_thread;
  std::vector<std::thread> conn_threads;
  std::vector<int> conn_fds;

  void log_span(uint8_t cmd, uint64_t span, uint64_t start_us,
                uint64_t dur_us) {
    if (!span) return;
    std::lock_guard<std::mutex> lk(mu);
    if (span_log.size() >= kMaxSpanLog) span_log.pop_front();
    span_log.push_back({static_cast<uint64_t>(cmd), span, start_us, dur_us});
  }

  void serve_conn(int fd) {
    Frame f;
    while (read_frame(fd, &f)) {
      uint64_t t_start = wall_us();
      auto t0 = std::chrono::steady_clock::now();
      bool keep = handle_frame(fd, f);
      log_span(f.cmd, f.span, t_start,
               static_cast<uint64_t>(
                   std::chrono::duration_cast<std::chrono::microseconds>(
                       std::chrono::steady_clock::now() - t0)
                       .count()));
      if (!keep) return;
    }
  }

  // serve one frame; returns false when the connection should close
  bool handle_frame(int fd, Frame& f) {
    std::unique_lock<std::mutex> lk(mu);
    if (stopped && f.cmd != kStop) {
      write_response(fd, 1, "");
      return false;
    }
    switch (f.cmd) {
        case kSendGrad:
          grads.emplace_back(f.name, std::move(f.data));
          cv.notify_all();  // wake a driver parked in pop_grad (async mode)
          lk.unlock();
          return write_response(fd, 0, "");
        case kLookupRows: {
          // round packs (header_offset << 32) | row_width_bytes: published
          // blobs carry the Python codec's dtype header before the raw rows
          uint64_t width = f.round & 0xffffffffull;
          uint64_t offset = f.round >> 32;
          auto it = table.find(f.name);
          if (it == table.end() || width == 0 ||
              it->second.size() < offset ||
              f.data.size() % sizeof(int64_t) != 0) {
            lk.unlock();
            return write_response(fd, 1, "");
          }
          const std::string& blob = it->second;
          size_t n_rows = (blob.size() - offset) / width;
          size_t n_ids = f.data.size() / sizeof(int64_t);
          const int64_t* ids =
              reinterpret_cast<const int64_t*>(f.data.data());
          std::string out;
          out.resize(n_ids * width);
          bool ok = true;
          for (size_t i = 0; i < n_ids; ++i) {
            if (ids[i] < 0 || static_cast<size_t>(ids[i]) >= n_rows) {
              ok = false;
              break;
            }
            ::memcpy(&out[i * width], blob.data() + offset + ids[i] * width,
                     width);
          }
          lk.unlock();
          return write_response(fd, ok ? 0 : 1, ok ? out : "");
        }
        case kSendParam:
          table[f.name] = std::move(f.data);
          cv.notify_all();
          lk.unlock();
          return write_response(fd, 0, "");
        case kSendBarrier: {
          // f.round carries the trainer's completed-round count; the
          // rewait bit marks a retry of a timed-out wait — the trainer
          // already arrived, so it must NOT be counted again (the barrier
          // retry is idempotent; see the client's _barrier loop).
          uint64_t rc = f.round & ~kPtsRewaitBit;
          if ((f.round & kPtsRewaitBit) == 0) {
            // elastic: an arrival is proof of life (lease renewed), and
            // an unknown uid implicitly joins ACTIVE — see
            // arrival_membership.  A PENDING (not yet activated) member
            // parks without being counted: it is not part of this
            // round's quorum — its activation lands at the next round
            // boundary.
            bool pending = arrival_membership(f.name);
            // a fresh client (relaunched trainer, re-dialed channel)
            // arrives with a LOW count: it means "ack when the round I'm
            // joining completes", i.e. the server's current round.  A
            // REWAIT keeps the client's echoed count — its release may
            // have happened while the timeout answer was in flight.
            if (round_id > rc) rc = round_id;
            // LATE replay: between release_send and end_round no live
            // trainer can legitimately send-arrive (they are all parked
            // in fetch), so an arrival here is a relaunched trainer
            // replaying an already-released round — ack it (pred is
            // already true) but do NOT count it toward the next round.
            bool late = send_ack_round > rc;
            // identity-deduped arrival: a re-arrive after a reconnect on
            // a SURVIVING server is a no-op (its first arrival stands)
            if (!late && !pending &&
                (f.name.empty() || send_ids.insert(f.name).second))
              ++send_arrived;
            if (pending) pending_send_arrivals.insert(f.name);
          }
          cv.notify_all();
          // ack deferred until the driver released this round's sends
          bool done = wait_alive(
              lk, [&] { return stopped || send_ack_round > rc; });
          if (!done) {
            ++stat_send_barrier_timeouts;
            // echo the EFFECTIVE round the arrival waits on so the
            // client's rewait targets it exactly (a fresh client's own
            // count may be lower than the floored value)
            std::string eff(8, '\0');
            ::memcpy(&eff[0], &rc, 8);
            lk.unlock();
            // keep the connection on success: the trainer rewaits on it
            return write_response(fd, 2, eff);
          }
          bool ok = !stopped;
          lk.unlock();
          if (!write_response(fd, ok ? 0 : 1, "")) return false;
          return ok;
        }
        case kFetchBarrier: {
          uint64_t rc = f.round & ~kPtsRewaitBit;
          if ((f.round & kPtsRewaitBit) == 0) {
            bool pending = arrival_membership(f.name);
            // elastic: a fetch for a round that ALREADY closed (a member
            // activated at the boundary whose round-r fetch lost the
            // race with end_round) is acked immediately and never
            // counted — flooring it into the CURRENT round's quorum
            // would park the member in fetch while wait_round waits for
            // its send: a livelock.  The fixed-quorum lane keeps the
            // flooring: there a fresh client's fetch must fill the dead
            // trainer's slot for the in-flight round.
            bool closed = elastic && round_id > rc;
            if (!pending && !closed &&
                (f.name.empty() || fetch_ids.insert(f.name).second))
              ++fetch_arrived;
            if (!closed && round_id > rc)
              rc = round_id;  // same fresh-client rule (non-elastic)
          }
          cv.notify_all();
          bool done = wait_alive(lk, [&] { return stopped || round_id > rc; });
          if (!done) {
            ++stat_fetch_barrier_timeouts;
            std::string eff(8, '\0');
            ::memcpy(&eff[0], &rc, 8);
            lk.unlock();
            return write_response(fd, 2, eff);
          }
          bool ok = !stopped;
          lk.unlock();
          if (!write_response(fd, ok ? 0 : 1, "")) return false;
          return ok;
        }
        case kJoin: {
          if (!elastic || f.name.empty()) {
            lk.unlock();
            return write_response(fd, 1, "");
          }
          // same find-or-create + activation rule as an implicit
          // barrier-frame join (idle job activates immediately, running
          // job queues for the boundary; re-join renews the lease)
          arrival_membership(f.name);
          pending_leaves.erase(f.name);  // a re-join cancels a queued leave
          cv.notify_all();
          std::string blob = membership_blob(f.name);
          lk.unlock();
          return write_response(fd, 0, blob);
        }
        case kLease: {
          // heartbeat + membership query (also answers non-members, so a
          // delayed joiner can watch the round counter before joining)
          renew_lease(f.name);
          std::string blob = membership_blob(f.name);
          lk.unlock();
          return write_response(fd, 0, blob);
        }
        case kCommitEpoch: {
          // a commit frame is proof of life too (it rides the same
          // per-round cadence as barrier arrivals)
          renew_lease(f.name);
          if (f.data.size() == 24) {
            uint64_t vals[3];
            ::memcpy(vals, f.data.data(), 24);
            accept_commit(vals[0], vals[1], vals[2]);
          } else if (!f.data.empty()) {
            lk.unlock();
            return write_response(fd, 1, "");
          }
          std::string blob = committed_blob();
          lk.unlock();
          return write_response(fd, 0, blob);
        }
        case kLeave: {
          if (elastic && !f.name.empty() && members.count(f.name)) {
            pending_leaves.insert(f.name);
            if (idle_at_start()) apply_membership();
            cv.notify_all();
          }
          lk.unlock();
          return write_response(fd, 0, "");
        }
        case kGetParam: {
          uint64_t want = f.round;
          bool done = wait_alive(lk, [&] {
            return stopped || (version >= want && table.count(f.name));
          });
          if (!done) {
            ++stat_get_timeouts;
            lk.unlock();
            // GET_PARAM is idempotent: the client re-sends it
            return write_response(fd, 2, "");
          }
          if (stopped) {
            write_response(fd, 1, "");
            return false;
          }
          std::string blob = table[f.name];
          lk.unlock();
          return write_response(fd, 0, blob);
        }
        case kCheckpointNotify: {
          // snapshot the table to the requested path (reference pservers
          // save their own shard on the CheckpointNotify RPC).  Copy under
          // the lock; disk IO and the response write happen UNLOCKED — a
          // stalled notifier must not wedge every other connection.
          auto copy = table;
          auto mcopy = members;
          uint64_t ver = version, rid = round_id, ep = epoch;
          uint64_t committed[3] = {committed_epoch, committed_round,
                                   committed_pos};
          lk.unlock();
          bool ok =
              write_snapshot(f.name, copy, ver, rid, ep, mcopy, committed);
          return write_response(fd, ok ? 0 : 1, "");
        }
        case kStop:
          stopped = true;
          cv.notify_all();
          lk.unlock();
          write_response(fd, 0, "");
          return false;
        default:
          lk.unlock();
          write_response(fd, 1, "");
          return false;
    }
  }

  // Snapshot file format (little-endian):
  //   u64 magic "PTSCKPT0"/"PTSCKPT1"/"PTSCKPT2" | u64 version |
  //   u64 round_id | u64 count |
  //   count × (u16 name_len | name | u64 blob_len | blob)
  // The v1 magic appends a membership section so an elastic shard's
  // restart resumes with its quorum (active member uids) and epoch:
  //   u64 epoch | u64 n_members | n × (u16 uid_len | uid)
  // The v2 magic appends the quorum-committed epoch record after the
  // member section (u64 committed_epoch | u64 committed_round |
  // u64 committed_pos), so a restarted shard can tell a STALE snapshot
  // from a current one before it even reaches its peers.  v0/v1 files
  // stay loadable.
  static constexpr uint64_t kCkptMagic = 0x505453434B505430ull;
  static constexpr uint64_t kCkptMagicV1 = 0x505453434B505431ull;
  static constexpr uint64_t kCkptMagicV2 = 0x505453434B505432ull;

  static bool write_snapshot(
      const std::string& path,
      const std::unordered_map<std::string, std::string>& copy,
      uint64_t ver, uint64_t rid, uint64_t ep,
      const std::map<std::string, Member>& mcopy,
      const uint64_t committed[3]) {
    // write-to-temp + rename: a crash mid-save (the supervised pserver
    // snapshots EVERY round, so the window recurs constantly) must never
    // truncate the previous good snapshot the relaunch depends on
    std::string tmp = path + ".tmp";
    FILE* fp = ::fopen(tmp.c_str(), "wb");
    if (!fp) return false;
    bool ok = true;
    uint64_t magic = kCkptMagicV2, count = copy.size();
    ok &= ::fwrite(&magic, 8, 1, fp) == 1;
    ok &= ::fwrite(&ver, 8, 1, fp) == 1;
    ok &= ::fwrite(&rid, 8, 1, fp) == 1;
    ok &= ::fwrite(&count, 8, 1, fp) == 1;
    for (auto& kv : copy) {
      uint16_t nlen = static_cast<uint16_t>(kv.first.size());
      uint64_t blen = kv.second.size();
      ok &= ::fwrite(&nlen, 2, 1, fp) == 1;
      ok &= nlen == 0 || ::fwrite(kv.first.data(), nlen, 1, fp) == 1;
      ok &= ::fwrite(&blen, 8, 1, fp) == 1;
      ok &= blen == 0 || ::fwrite(kv.second.data(), blen, 1, fp) == 1;
    }
    uint64_t n_members = 0;
    for (auto& kv : mcopy)
      if (kv.second.active) ++n_members;
    ok &= ::fwrite(&ep, 8, 1, fp) == 1;
    ok &= ::fwrite(&n_members, 8, 1, fp) == 1;
    for (auto& kv : mcopy) {
      if (!kv.second.active) continue;
      uint16_t ulen = static_cast<uint16_t>(kv.first.size());
      ok &= ::fwrite(&ulen, 2, 1, fp) == 1;
      ok &= ulen == 0 || ::fwrite(kv.first.data(), ulen, 1, fp) == 1;
    }
    ok &= ::fwrite(committed, 8, 3, fp) == 3;
    ok &= ::fclose(fp) == 0;
    if (ok) ok = ::rename(tmp.c_str(), path.c_str()) == 0;
    if (!ok) ::remove(tmp.c_str());
    return ok;
  }

  bool load_snapshot(const std::string& path) {
    FILE* fp = ::fopen(path.c_str(), "rb");
    if (!fp) return false;
    auto rd = [&](void* p, size_t n) { return ::fread(p, n, 1, fp) == 1; };
    uint64_t magic = 0, ver = 0, rid = 0, count = 0;
    bool ok = rd(&magic, 8) &&
              (magic == kCkptMagic || magic == kCkptMagicV1 ||
               magic == kCkptMagicV2) &&
              rd(&ver, 8) &&
              rd(&rid, 8) && rd(&count, 8) && count < (1ull << 32);
    std::unordered_map<std::string, std::string> loaded;
    for (uint64_t i = 0; ok && i < count; ++i) {
      uint16_t nlen = 0;
      uint64_t blen = 0;
      ok = rd(&nlen, 2);
      std::string name(nlen, '\0');
      ok = ok && (nlen == 0 || rd(&name[0], nlen));
      ok = ok && rd(&blen, 8) && blen <= kMaxBlob;
      std::string blob(blen, '\0');
      ok = ok && (blen == 0 || rd(&blob[0], blen));
      if (ok) loaded.emplace(std::move(name), std::move(blob));
    }
    uint64_t ep = 0, n_members = 0;
    uint64_t loaded_commit[3] = {0, 0, 0};
    std::map<std::string, Member> mloaded;
    if (ok && (magic == kCkptMagicV1 || magic == kCkptMagicV2)) {
      ok = rd(&ep, 8) && rd(&n_members, 8) && n_members < (1ull << 20);
      for (uint64_t i = 0; ok && i < n_members; ++i) {
        uint16_t ulen = 0;
        ok = rd(&ulen, 2);
        std::string uid(ulen, '\0');
        ok = ok && (ulen == 0 || rd(&uid[0], ulen));
        if (ok) {
          Member m;
          m.active = true;
          mloaded.emplace(std::move(uid), m);
        }
      }
    }
    if (ok && magic == kCkptMagicV2) ok = rd(loaded_commit, 24);
    ::fclose(fp);
    if (!ok) return false;
    std::lock_guard<std::mutex> lk(mu);
    table = std::move(loaded);
    version = ver;
    round_id = rid;
    // a restarted shard resumes mid-protocol: trainers re-arriving with
    // completed-round count == rid must wait for the NEXT release
    send_ack_round = rid;
    // elastic: restore the quorum with FRESH leases — the restored
    // members get one lease window to re-arrive; the survivors renew on
    // their first frame and a member that died with the old server is
    // evicted, renegotiating the count (double-failure path)
    if (elastic && !mloaded.empty()) {
      int64_t dl = lease_timeout_ms > 0 ? steady_ms() + lease_timeout_ms : 0;
      for (auto& kv : mloaded) kv.second.deadline_ms = dl;
      members = std::move(mloaded);
      epoch = ep;
    }
    committed_epoch = loaded_commit[0];
    committed_round = loaded_commit[1];
    committed_pos = loaded_commit[2];
    cv.notify_all();
    return true;
  }

  void accept_loop() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) return;  // listen socket closed on stop
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(mu);
      if (stopped) {
        ::close(fd);
        return;
      }
      conn_fds.push_back(fd);
      conn_threads.emplace_back([this, fd] {
        serve_conn(fd);
        ::close(fd);
      });
    }
  }
};

struct PSClient {
  int fd = -1;
  std::mutex mu;
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------------- //
// server                                                                 //
// ---------------------------------------------------------------------- //

void* pts_server_start(int port, int n_trainers) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 64) < 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* s = new PSServer();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->n_trainers = n_trainers;
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  return s;
}

int pts_server_port(void* h) { return static_cast<PSServer*>(h)->port; }

// liveness deadline (ms) for server-side barrier / versioned-get waits;
// 0 disables (wait forever, the seed behavior)
void pts_server_set_barrier_timeout_ms(void* h, int ms) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->barrier_timeout_ms = ms;
}

// switch the barrier quorum from the fixed n_trainers to the live member
// set, with lease-based eviction (0 = members never expire)
void pts_server_enable_elastic(void* h, int lease_timeout_ms) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->elastic = true;
  s->lease_timeout_ms = lease_timeout_ms;
}

// resilience counters: 0 send-barrier timeouts, 1 fetch-barrier timeouts,
// 2 get-param timeouts, 3 completed rounds, 4 published version,
// 5 membership epoch, 6 active members, 7 joins, 8 leaves, 9 evictions,
// 10 committed epoch, 11 committed round, 12 committed position
int64_t pts_server_stat(void* h, int which) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  switch (which) {
    case 0: return s->stat_send_barrier_timeouts;
    case 1: return s->stat_fetch_barrier_timeouts;
    case 2: return s->stat_get_timeouts;
    case 3: return static_cast<int64_t>(s->round_id);
    case 4: return static_cast<int64_t>(s->version);
    case 5: return static_cast<int64_t>(s->epoch);
    case 6: return s->active_count();
    case 7: return s->stat_joins;
    case 8: return s->stat_leaves;
    case 9: return s->stat_evictions;
    case 10: return static_cast<int64_t>(s->committed_epoch);
    case 11: return static_cast<int64_t>(s->committed_round);
    case 12: return static_cast<int64_t>(s->committed_pos);
    default: return -1;
  }
}

// reconcile a restored shard against the quorum committed record (see
// native_api.h).  Fast-forwarding round_id also fast-forwards
// send_ack_round: the rounds this shard missed were fully released by
// the job, so a survivor's rewait on one of them must ack immediately.
int pts_server_reconcile_committed(void* h, uint64_t epoch, uint64_t round,
                                   uint64_t position) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->accept_commit(epoch, round, position);
  bool moved = false;
  if (round > s->round_id) {
    s->round_id = round;
    if (s->send_ack_round < round) s->send_ack_round = round;
    // keep the sync lane's version==rounds invariant: a survivor's
    // versioned GET_PARAM for the adopted round must not wait for a
    // fold that already happened elsewhere.  The table may be up to
    // (round - snapshot round) rounds stale — the documented
    // at-least-once recovery bound for a shard killed between
    // end_round and its snapshot write.
    if (s->version < round) s->version = round;
    moved = true;
  }
  if (epoch > s->epoch) {
    s->epoch = epoch;
    moved = true;
  }
  if (moved) s->cv.notify_all();
  return moved ? 1 : 0;
}

// drain journaled (cmd, span, start us, dur us) records; out must hold
// 4 * max_records u64s
int64_t pts_server_drain_spans(void* h, uint64_t* out, int64_t max_records) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  int64_t n = 0;
  while (n < max_records && !s->span_log.empty()) {
    auto& rec = s->span_log.front();
    for (int k = 0; k < 4; ++k) out[n * 4 + k] = rec[k];
    s->span_log.pop_front();
    ++n;
  }
  return n;
}

// 1 = round ready (quorum hit send_barrier), 0 = stopped.  Elastic mode
// polls so an expired lease renegotiates the quorum downward and the
// surviving round completes instead of waiting out the dead peer.
int pts_server_wait_round(void* h) {
  auto* s = static_cast<PSServer*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  auto pred = [s] {
    if (s->stopped) return true;
    if (s->elastic) {
      s->prune_expired();
      // a job whose entire quorum died can RE-FORM here: with no active
      // members left, nobody holds a stale per-round view, so pending
      // joins (and parked pending arrivals) activate immediately — the
      // only other activation point, end_round, is unreachable while
      // this wait blocks
      if (s->expected() == 0) s->apply_membership();
      int exp = s->expected();
      if (exp > 0 && s->send_arrived >= exp) {
        s->round_expected = exp;
        return true;
      }
      return false;
    }
    if (s->send_arrived >= s->n_trainers) {
      s->round_expected = s->n_trainers;
      return true;
    }
    return false;
  };
  if (s->elastic && s->lease_timeout_ms > 0) {
    while (!pred()) s->cv.wait_for(lk, s->elastic_poll());
  } else {
    s->cv.wait(lk, pred);
  }
  return s->stopped ? 0 : 1;
}

// release trainers parked in SEND_BARRIER (call after publish+bump_version)
void pts_server_release_send(void* h) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->send_ack_round = s->round_id + 1;
  // subtract the quorum the round actually completed with — under
  // elastic renegotiation that may be below n_trainers
  s->send_arrived -= s->round_expected ? s->round_expected : s->n_trainers;
  // a member leaving the park is alive NOW: renew before send_ids (its
  // eviction shield while parked) clears, or a round that out-waited the
  // lease would evict its own survivors the moment it releases them
  for (auto& uid : s->send_ids) s->renew_lease(uid);
  s->send_ids.clear();  // next round's arrivals dedupe afresh
  // released pending arrivals were consumed by this ack — they must not
  // retro-count into a later round at activation
  s->pending_send_arrivals.clear();
  s->cv.notify_all();
}

int64_t pts_server_grad_count(void* h) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return static_cast<int64_t>(s->grads.size());
}

// copies grad i's name and payload; both freed by caller via ptq_free
int64_t pts_server_grad_at(void* h, int64_t i, char** name_out,
                           char** data_out) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (i < 0 || i >= static_cast<int64_t>(s->grads.size())) return -1;
  *name_out = dup_blob(s->grads[i].first);
  *data_out = dup_blob(s->grads[i].second);
  return static_cast<int64_t>(s->grads[i].second.size());
}

int64_t pts_server_grad_name_len(void* h, int64_t i) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (i < 0 || i >= static_cast<int64_t>(s->grads.size())) return -1;
  return static_cast<int64_t>(s->grads[i].first.size());
}

// Async-mode driver API: block until a grad arrives, pop it.  Returns the
// payload length (name/data freed by caller via ptq_free), -1 on timeout,
// -2 when the server was stopped.  The sync loop never calls this; the
// async loop (listen_and_serv with sync_mode=False, reference
// listen_and_serv_op.cc RunAsyncLoop) lives on it.
int64_t pts_server_pop_grad(void* h, int timeout_ms, char** name_out,
                            char** data_out) {
  auto* s = static_cast<PSServer*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  bool ready = s->cv.wait_for(
      lk, std::chrono::milliseconds(timeout_ms),
      [s] { return s->stopped || !s->grads.empty(); });
  if (s->stopped && s->grads.empty()) return -2;
  if (!ready || s->grads.empty()) return -1;
  auto item = std::move(s->grads.front());
  s->grads.pop_front();
  // name is returned NUL-terminated (no paired length call — the item is
  // already popped); var names never contain NUL
  char* np = static_cast<char*>(::malloc(item.first.size() + 1));
  if (np) {
    ::memcpy(np, item.first.data(), item.first.size());
    np[item.first.size()] = '\0';
  }
  *name_out = np;
  *data_out = dup_blob(item.second);
  return static_cast<int64_t>(item.second.size());
}

void pts_server_publish(void* h, const char* name, const char* data,
                        int64_t len) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->table[name] = std::string(data, static_cast<size_t>(len));
}

void pts_server_bump_version(void* h) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  ++s->version;
  s->cv.notify_all();
}

// wait for all fetch barriers, close the round, release the trainers;
// 1 = ok, 0 = stopped.  No round r+1 message can arrive before this resets
// state: every trainer is still parked in its FETCH_BARRIER ack.  Elastic
// mode renegotiates the quorum here too (a member that died after its
// send barrier becomes evictable once release_send cleared send_ids), and
// this is THE round boundary where queued joins/leaves apply: every
// survivor is parked in its fetch ack, so the epoch flips atomically
// before anyone computes its next-round (index, count) view.
int pts_server_end_round(void* h) {
  auto* s = static_cast<PSServer*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  int used = 0;
  auto pred = [s, &used] {
    if (s->stopped) return true;
    if (s->elastic) {
      s->prune_expired();
      int exp = s->expected();
      // exp == 0: every member evicted mid-fetch — close the round so a
      // future joiner finds the server at a clean boundary
      if (exp == 0 || s->fetch_arrived >= exp) {
        used = std::min(exp, s->fetch_arrived);
        return true;
      }
      return false;
    }
    if (s->fetch_arrived >= s->n_trainers) {
      used = s->n_trainers;
      return true;
    }
    return false;
  };
  if (s->elastic && s->lease_timeout_ms > 0) {
    while (!pred()) s->cv.wait_for(lk, s->elastic_poll());
  } else {
    s->cv.wait(lk, pred);
  }
  if (s->stopped) return 0;
  s->grads.clear();
  s->fetch_arrived -= used;
  for (auto& uid : s->fetch_ids) s->renew_lease(uid);  // see release_send
  s->fetch_ids.clear();
  ++s->round_id;
  if (s->elastic) s->apply_membership();
  s->cv.notify_all();
  return 1;
}

// fetch a published/pushed param (e.g. the trainer-0 init push); -1 if absent
int64_t pts_server_table_get(void* h, const char* name, char** out) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->table.find(name);
  if (it == s->table.end()) return -1;
  *out = dup_blob(it->second);
  return static_cast<int64_t>(it->second.size());
}

// block until `name` exists in the table (init push); 1 ok, 0 stopped
int pts_server_wait_table(void* h, const char* name) {
  auto* s = static_cast<PSServer*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv.wait(lk, [&] { return s->stopped || s->table.count(name); });
  return s->stopped ? 0 : 1;
}

// write the table snapshot to `path`; 1 ok, 0 failed
int pts_server_save(void* h, const char* path) {
  auto* s = static_cast<PSServer*>(h);
  std::unordered_map<std::string, std::string> copy;
  std::map<std::string, PSServer::Member> mcopy;
  uint64_t ver, rid, ep, committed[3];
  {
    std::lock_guard<std::mutex> lk(s->mu);
    copy = s->table;
    mcopy = s->members;
    ver = s->version;
    rid = s->round_id;
    ep = s->epoch;
    committed[0] = s->committed_epoch;
    committed[1] = s->committed_round;
    committed[2] = s->committed_pos;
  }
  return PSServer::write_snapshot(path, copy, ver, rid, ep, mcopy,
                                  committed)
             ? 1
             : 0;
}

// restore the table (+version/round) from a snapshot; 1 ok, 0 failed
int pts_server_load(void* h, const char* path) {
  auto* s = static_cast<PSServer*>(h);
  return s->load_snapshot(path) ? 1 : 0;
}

void pts_server_stop(void* h) {
  auto* s = static_cast<PSServer*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->stopped = true;
    s->cv.notify_all();
    // unblock conn threads parked in read()
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  for (auto& t : s->conn_threads)
    if (t.joinable()) t.join();
  delete s;
}

// ---------------------------------------------------------------------- //
// client                                                                 //
// ---------------------------------------------------------------------- //

void* pts_connect(const char* host, int port, double timeout_s) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) return nullptr;
  // retry until the server comes up (reference grpc_client retry semantics)
  int tries = static_cast<int>(timeout_s / 0.05) + 1;
  for (int i = 0; i < tries; ++i) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto* c = new PSClient();
      c->fd = fd;
      return c;
    }
    ::close(fd);
    ::usleep(50000);
  }
  return nullptr;
}

// generic request; returns status (0 ok, 1 error, -1 io failure).  For
// kGetParam the payload lands in *out (caller frees via ptq_free), length in
// *olen.  `span` rides every frame (0 = untraced attempt).
int pts_request(void* h, int cmd, const char* name, uint64_t round,
                uint64_t span, const char* data, int64_t dlen, char** out,
                int64_t* olen) {
  auto* c = static_cast<PSClient*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t cmd8 = static_cast<uint8_t>(cmd);
  uint16_t nlen = static_cast<uint16_t>(::strlen(name));
  uint64_t dl = static_cast<uint64_t>(dlen < 0 ? 0 : dlen);
  if (!write_n(c->fd, &cmd8, 1) || !write_n(c->fd, &nlen, 2) ||
      !write_n(c->fd, name, nlen) || !write_n(c->fd, &round, 8) ||
      !write_n(c->fd, &span, 8) ||
      !write_n(c->fd, &dl, 8) || (dl && !write_n(c->fd, data, dl)))
    return -1;
  uint8_t status;
  uint64_t rlen;
  if (!read_n(c->fd, &status, 1) || !read_n(c->fd, &rlen, 8)) return -1;
  if (rlen > kMaxBlob) return -1;
  std::string payload(rlen, '\0');
  if (rlen && !read_n(c->fd, &payload[0], rlen)) return -1;
  if (out) {
    *out = dup_blob(payload);
    if (olen) *olen = static_cast<int64_t>(rlen);
  }
  return status;
}

void pts_client_close(void* h) {
  auto* c = static_cast<PSClient*>(h);
  ::close(c->fd);
  delete c;
}

}  // extern "C"
