// Parameter-server transport: native TCP RPC runtime.
//
// Reference analog: paddle/fluid/operators/distributed/ — the gRPC/BRPC
// SendRecvService (send_recv.proto.in:19: SendVariable / GetVariable),
// RequestHandler dispatch, and the listen_and_serv sync loop
// (listen_and_serv_op.cc:109 RunSyncLoop: wait kRequestSend barrier → run
// optimize blocks → release kRequestGet).  TPU-native redesign: the dense
// data path rides XLA collectives; this runtime exists for the
// parameter-server mode (sparse/CTR workloads, async geo-SGD) where a
// host-side service is the right architecture.  Tensors travel as opaque
// byte blobs (name + payload); aggregation and optimizer math happen in the
// driver above — the transport stays dumb and fast.
//
// Wire format (little-endian), one request per frame:
//   u8 cmd | u16 name_len | name | u64 round | u64 data_len | data
// response:
//   u8 status (0 ok, 1 stopped/error, 2 liveness-deadline timeout —
//   retryable) | u64 data_len | data
//
// Sync-round protocol (mirrors RunSyncLoop):
//   trainers: SEND_GRAD*  SEND_BARRIER  GET_PARAM(round=r)*  FETCH_BARRIER
//   server driver: wait_round → drain grads → optimize → publish* →
//                  bump_version → release_send → end_round
//
// Barrier acks are RENDEZVOUS: a SEND_BARRIER is not acknowledged until the
// driver has processed the round (release_send), and a FETCH_BARRIER not
// until the driver closed the round (end_round).  Without this, a fast
// trainer could race into round r+1 — its barrier/grads arriving before the
// driver resets round state — and be silently wiped (lost-wakeup deadlock).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include "native_api.h"

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

bool read_n(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_n(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

char* dup_blob(const std::string& s) {
  char* p = static_cast<char*>(::malloc(s.size() ? s.size() : 1));
  if (p && !s.empty()) ::memcpy(p, s.data(), s.size());
  return p;
}

constexpr uint64_t kMaxBlob = 1ull << 33;  // 8 GiB sanity bound

struct Frame {
  uint8_t cmd;
  std::string name;
  uint64_t round;
  std::string data;
};

bool read_frame(int fd, Frame* f) {
  uint8_t cmd;
  uint16_t nlen;
  if (!read_n(fd, &cmd, 1) || !read_n(fd, &nlen, 2)) return false;
  f->cmd = cmd;
  f->name.resize(nlen);
  if (nlen && !read_n(fd, &f->name[0], nlen)) return false;
  uint64_t dlen;
  if (!read_n(fd, &f->round, 8) || !read_n(fd, &dlen, 8)) return false;
  if (dlen > kMaxBlob) return false;
  f->data.resize(dlen);
  if (dlen && !read_n(fd, &f->data[0], dlen)) return false;
  return true;
}

bool write_response(int fd, uint8_t status, const std::string& data) {
  uint64_t dlen = data.size();
  return write_n(fd, &status, 1) && write_n(fd, &dlen, 8) &&
         (dlen == 0 || write_n(fd, data.data(), dlen));
}

struct PSServer {
  int listen_fd = -1;
  int port = 0;
  int n_trainers = 1;

  std::mutex mu;
  std::condition_variable cv;
  std::unordered_map<std::string, std::string> table;  // published params
  uint64_t version = 0;
  std::deque<std::pair<std::string, std::string>> grads;
  int send_arrived = 0;    // trainers parked in SEND_BARRIER this round
  int fetch_arrived = 0;   // trainers parked in FETCH_BARRIER this round
  // client identities counted this round: a trainer that reconnects and
  // re-arrives (its ack was lost with the connection, server survived)
  // must not be counted twice.  Barrier frames carry the client's uid in
  // the (otherwise unused) name field; an empty name skips the dedup.
  std::unordered_set<std::string> send_ids, fetch_ids;
  uint64_t round_id = 0;       // completed rounds
  uint64_t send_ack_round = 0;  // rounds whose send barrier was released
  bool stopped = false;
  // liveness deadline on server-side waits (barriers, versioned GET_PARAM).
  // 0 = wait forever (seed behavior).  On expiry the request is answered
  // with status 2 (retryable timeout) instead of parking the connection
  // forever behind a dead peer — the stale-trainer detector.
  int barrier_timeout_ms = 0;
  int64_t stat_send_barrier_timeouts = 0;
  int64_t stat_fetch_barrier_timeouts = 0;
  int64_t stat_get_timeouts = 0;

  // wait on cv with the liveness deadline; returns false on timeout
  template <class Pred>
  bool wait_alive(std::unique_lock<std::mutex>& lk, Pred pred) {
    if (barrier_timeout_ms <= 0) {
      cv.wait(lk, pred);
      return true;
    }
    return cv.wait_for(lk, std::chrono::milliseconds(barrier_timeout_ms),
                       pred);
  }

  std::thread accept_thread;
  std::vector<std::thread> conn_threads;
  std::vector<int> conn_fds;

  void serve_conn(int fd) {
    Frame f;
    while (read_frame(fd, &f)) {
      std::unique_lock<std::mutex> lk(mu);
      if (stopped && f.cmd != kStop) {
        write_response(fd, 1, "");
        break;
      }
      switch (f.cmd) {
        case kSendGrad:
          grads.emplace_back(f.name, std::move(f.data));
          cv.notify_all();  // wake a driver parked in pop_grad (async mode)
          lk.unlock();
          if (!write_response(fd, 0, "")) return;
          break;
        case kLookupRows: {
          // round packs (header_offset << 32) | row_width_bytes: published
          // blobs carry the Python codec's dtype header before the raw rows
          uint64_t width = f.round & 0xffffffffull;
          uint64_t offset = f.round >> 32;
          auto it = table.find(f.name);
          if (it == table.end() || width == 0 ||
              it->second.size() < offset ||
              f.data.size() % sizeof(int64_t) != 0) {
            lk.unlock();
            if (!write_response(fd, 1, "")) return;
            break;
          }
          const std::string& blob = it->second;
          size_t n_rows = (blob.size() - offset) / width;
          size_t n_ids = f.data.size() / sizeof(int64_t);
          const int64_t* ids =
              reinterpret_cast<const int64_t*>(f.data.data());
          std::string out;
          out.resize(n_ids * width);
          bool ok = true;
          for (size_t i = 0; i < n_ids; ++i) {
            if (ids[i] < 0 || static_cast<size_t>(ids[i]) >= n_rows) {
              ok = false;
              break;
            }
            ::memcpy(&out[i * width], blob.data() + offset + ids[i] * width,
                     width);
          }
          lk.unlock();
          if (!write_response(fd, ok ? 0 : 1, ok ? out : "")) return;
          break;
        }
        case kSendParam:
          table[f.name] = std::move(f.data);
          cv.notify_all();
          lk.unlock();
          if (!write_response(fd, 0, "")) return;
          break;
        case kSendBarrier: {
          // f.round carries the trainer's completed-round count; the
          // rewait bit marks a retry of a timed-out wait — the trainer
          // already arrived, so it must NOT be counted again (the barrier
          // retry is idempotent; see the client's _barrier loop).
          uint64_t rc = f.round & ~kPtsRewaitBit;
          if ((f.round & kPtsRewaitBit) == 0) {
            // a fresh client (relaunched trainer, re-dialed channel)
            // arrives with a LOW count: it means "ack when the round I'm
            // joining completes", i.e. the server's current round.  A
            // REWAIT keeps the client's echoed count — its release may
            // have happened while the timeout answer was in flight.
            if (round_id > rc) rc = round_id;
            // LATE replay: between release_send and end_round no live
            // trainer can legitimately send-arrive (they are all parked
            // in fetch), so an arrival here is a relaunched trainer
            // replaying an already-released round — ack it (pred is
            // already true) but do NOT count it toward the next round.
            bool late = send_ack_round > rc;
            // identity-deduped arrival: a re-arrive after a reconnect on
            // a SURVIVING server is a no-op (its first arrival stands)
            if (!late && (f.name.empty() || send_ids.insert(f.name).second))
              ++send_arrived;
          }
          cv.notify_all();
          // ack deferred until the driver released this round's sends
          bool done = wait_alive(
              lk, [&] { return stopped || send_ack_round > rc; });
          if (!done) {
            ++stat_send_barrier_timeouts;
            // echo the EFFECTIVE round the arrival waits on so the
            // client's rewait targets it exactly (a fresh client's own
            // count may be lower than the floored value)
            std::string eff(8, '\0');
            ::memcpy(&eff[0], &rc, 8);
            lk.unlock();
            if (!write_response(fd, 2, eff)) return;
            break;  // keep the connection: the trainer rewaits on it
          }
          bool ok = !stopped;
          lk.unlock();
          if (!write_response(fd, ok ? 0 : 1, "")) return;
          if (!ok) return;
          break;
        }
        case kFetchBarrier: {
          uint64_t rc = f.round & ~kPtsRewaitBit;
          if ((f.round & kPtsRewaitBit) == 0) {
            if (f.name.empty() || fetch_ids.insert(f.name).second)
              ++fetch_arrived;
            if (round_id > rc) rc = round_id;  // same fresh-client rule
          }
          cv.notify_all();
          bool done = wait_alive(lk, [&] { return stopped || round_id > rc; });
          if (!done) {
            ++stat_fetch_barrier_timeouts;
            std::string eff(8, '\0');
            ::memcpy(&eff[0], &rc, 8);
            lk.unlock();
            if (!write_response(fd, 2, eff)) return;
            break;
          }
          bool ok = !stopped;
          lk.unlock();
          if (!write_response(fd, ok ? 0 : 1, "")) return;
          if (!ok) return;
          break;
        }
        case kGetParam: {
          uint64_t want = f.round;
          bool done = wait_alive(lk, [&] {
            return stopped || (version >= want && table.count(f.name));
          });
          if (!done) {
            ++stat_get_timeouts;
            lk.unlock();
            if (!write_response(fd, 2, "")) return;
            break;  // GET_PARAM is idempotent: the client re-sends it
          }
          if (stopped) {
            write_response(fd, 1, "");
            return;
          }
          std::string blob = table[f.name];
          lk.unlock();
          if (!write_response(fd, 0, blob)) return;
          break;
        }
        case kCheckpointNotify: {
          // snapshot the table to the requested path (reference pservers
          // save their own shard on the CheckpointNotify RPC).  Copy under
          // the lock; disk IO and the response write happen UNLOCKED — a
          // stalled notifier must not wedge every other connection.
          auto copy = table;
          uint64_t ver = version, rid = round_id;
          lk.unlock();
          bool ok = write_snapshot(f.name, copy, ver, rid);
          if (!write_response(fd, ok ? 0 : 1, "")) return;
          break;
        }
        case kStop:
          stopped = true;
          cv.notify_all();
          lk.unlock();
          write_response(fd, 0, "");
          return;
        default:
          lk.unlock();
          write_response(fd, 1, "");
          return;
      }
    }
  }

  // Snapshot file format (little-endian):
  //   u64 magic 0x50545343'4B505430 ("PTSCKPT0") | u64 version |
  //   u64 round_id | u64 count | count × (u16 name_len | name |
  //   u64 blob_len | blob)
  static constexpr uint64_t kCkptMagic = 0x505453434B505430ull;

  static bool write_snapshot(
      const std::string& path,
      const std::unordered_map<std::string, std::string>& copy,
      uint64_t ver, uint64_t rid) {
    // write-to-temp + rename: a crash mid-save (the supervised pserver
    // snapshots EVERY round, so the window recurs constantly) must never
    // truncate the previous good snapshot the relaunch depends on
    std::string tmp = path + ".tmp";
    FILE* fp = ::fopen(tmp.c_str(), "wb");
    if (!fp) return false;
    bool ok = true;
    uint64_t magic = kCkptMagic, count = copy.size();
    ok &= ::fwrite(&magic, 8, 1, fp) == 1;
    ok &= ::fwrite(&ver, 8, 1, fp) == 1;
    ok &= ::fwrite(&rid, 8, 1, fp) == 1;
    ok &= ::fwrite(&count, 8, 1, fp) == 1;
    for (auto& kv : copy) {
      uint16_t nlen = static_cast<uint16_t>(kv.first.size());
      uint64_t blen = kv.second.size();
      ok &= ::fwrite(&nlen, 2, 1, fp) == 1;
      ok &= nlen == 0 || ::fwrite(kv.first.data(), nlen, 1, fp) == 1;
      ok &= ::fwrite(&blen, 8, 1, fp) == 1;
      ok &= blen == 0 || ::fwrite(kv.second.data(), blen, 1, fp) == 1;
    }
    ok &= ::fclose(fp) == 0;
    if (ok) ok = ::rename(tmp.c_str(), path.c_str()) == 0;
    if (!ok) ::remove(tmp.c_str());
    return ok;
  }

  bool load_snapshot(const std::string& path) {
    FILE* fp = ::fopen(path.c_str(), "rb");
    if (!fp) return false;
    auto rd = [&](void* p, size_t n) { return ::fread(p, n, 1, fp) == 1; };
    uint64_t magic = 0, ver = 0, rid = 0, count = 0;
    bool ok = rd(&magic, 8) && magic == kCkptMagic && rd(&ver, 8) &&
              rd(&rid, 8) && rd(&count, 8) && count < (1ull << 32);
    std::unordered_map<std::string, std::string> loaded;
    for (uint64_t i = 0; ok && i < count; ++i) {
      uint16_t nlen = 0;
      uint64_t blen = 0;
      ok = rd(&nlen, 2);
      std::string name(nlen, '\0');
      ok = ok && (nlen == 0 || rd(&name[0], nlen));
      ok = ok && rd(&blen, 8) && blen <= kMaxBlob;
      std::string blob(blen, '\0');
      ok = ok && (blen == 0 || rd(&blob[0], blen));
      if (ok) loaded.emplace(std::move(name), std::move(blob));
    }
    ::fclose(fp);
    if (!ok) return false;
    std::lock_guard<std::mutex> lk(mu);
    table = std::move(loaded);
    version = ver;
    round_id = rid;
    // a restarted shard resumes mid-protocol: trainers re-arriving with
    // completed-round count == rid must wait for the NEXT release
    send_ack_round = rid;
    cv.notify_all();
    return true;
  }

  void accept_loop() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) return;  // listen socket closed on stop
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(mu);
      if (stopped) {
        ::close(fd);
        return;
      }
      conn_fds.push_back(fd);
      conn_threads.emplace_back([this, fd] {
        serve_conn(fd);
        ::close(fd);
      });
    }
  }
};

struct PSClient {
  int fd = -1;
  std::mutex mu;
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------------- //
// server                                                                 //
// ---------------------------------------------------------------------- //

void* pts_server_start(int port, int n_trainers) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 64) < 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* s = new PSServer();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->n_trainers = n_trainers;
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  return s;
}

int pts_server_port(void* h) { return static_cast<PSServer*>(h)->port; }

// liveness deadline (ms) for server-side barrier / versioned-get waits;
// 0 disables (wait forever, the seed behavior)
void pts_server_set_barrier_timeout_ms(void* h, int ms) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->barrier_timeout_ms = ms;
}

// resilience counters: 0 send-barrier timeouts, 1 fetch-barrier timeouts,
// 2 get-param timeouts, 3 completed rounds, 4 published version
int64_t pts_server_stat(void* h, int which) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  switch (which) {
    case 0: return s->stat_send_barrier_timeouts;
    case 1: return s->stat_fetch_barrier_timeouts;
    case 2: return s->stat_get_timeouts;
    case 3: return static_cast<int64_t>(s->round_id);
    case 4: return static_cast<int64_t>(s->version);
    default: return -1;
  }
}

// 1 = round ready (all trainers hit send_barrier), 0 = stopped
int pts_server_wait_round(void* h) {
  auto* s = static_cast<PSServer*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv.wait(lk, [s] {
    return s->stopped || s->send_arrived >= s->n_trainers;
  });
  return s->stopped ? 0 : 1;
}

// release trainers parked in SEND_BARRIER (call after publish+bump_version)
void pts_server_release_send(void* h) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->send_ack_round = s->round_id + 1;
  s->send_arrived -= s->n_trainers;
  s->send_ids.clear();  // next round's arrivals dedupe afresh
  s->cv.notify_all();
}

int64_t pts_server_grad_count(void* h) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return static_cast<int64_t>(s->grads.size());
}

// copies grad i's name and payload; both freed by caller via ptq_free
int64_t pts_server_grad_at(void* h, int64_t i, char** name_out,
                           char** data_out) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (i < 0 || i >= static_cast<int64_t>(s->grads.size())) return -1;
  *name_out = dup_blob(s->grads[i].first);
  *data_out = dup_blob(s->grads[i].second);
  return static_cast<int64_t>(s->grads[i].second.size());
}

int64_t pts_server_grad_name_len(void* h, int64_t i) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (i < 0 || i >= static_cast<int64_t>(s->grads.size())) return -1;
  return static_cast<int64_t>(s->grads[i].first.size());
}

// Async-mode driver API: block until a grad arrives, pop it.  Returns the
// payload length (name/data freed by caller via ptq_free), -1 on timeout,
// -2 when the server was stopped.  The sync loop never calls this; the
// async loop (listen_and_serv with sync_mode=False, reference
// listen_and_serv_op.cc RunAsyncLoop) lives on it.
int64_t pts_server_pop_grad(void* h, int timeout_ms, char** name_out,
                            char** data_out) {
  auto* s = static_cast<PSServer*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  bool ready = s->cv.wait_for(
      lk, std::chrono::milliseconds(timeout_ms),
      [s] { return s->stopped || !s->grads.empty(); });
  if (s->stopped && s->grads.empty()) return -2;
  if (!ready || s->grads.empty()) return -1;
  auto item = std::move(s->grads.front());
  s->grads.pop_front();
  // name is returned NUL-terminated (no paired length call — the item is
  // already popped); var names never contain NUL
  char* np = static_cast<char*>(::malloc(item.first.size() + 1));
  if (np) {
    ::memcpy(np, item.first.data(), item.first.size());
    np[item.first.size()] = '\0';
  }
  *name_out = np;
  *data_out = dup_blob(item.second);
  return static_cast<int64_t>(item.second.size());
}

void pts_server_publish(void* h, const char* name, const char* data,
                        int64_t len) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->table[name] = std::string(data, static_cast<size_t>(len));
}

void pts_server_bump_version(void* h) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  ++s->version;
  s->cv.notify_all();
}

// wait for all fetch barriers, close the round, release the trainers;
// 1 = ok, 0 = stopped.  No round r+1 message can arrive before this resets
// state: every trainer is still parked in its FETCH_BARRIER ack.
int pts_server_end_round(void* h) {
  auto* s = static_cast<PSServer*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv.wait(lk, [s] {
    return s->stopped || s->fetch_arrived >= s->n_trainers;
  });
  if (s->stopped) return 0;
  s->grads.clear();
  s->fetch_arrived -= s->n_trainers;
  s->fetch_ids.clear();
  ++s->round_id;
  s->cv.notify_all();
  return 1;
}

// fetch a published/pushed param (e.g. the trainer-0 init push); -1 if absent
int64_t pts_server_table_get(void* h, const char* name, char** out) {
  auto* s = static_cast<PSServer*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->table.find(name);
  if (it == s->table.end()) return -1;
  *out = dup_blob(it->second);
  return static_cast<int64_t>(it->second.size());
}

// block until `name` exists in the table (init push); 1 ok, 0 stopped
int pts_server_wait_table(void* h, const char* name) {
  auto* s = static_cast<PSServer*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv.wait(lk, [&] { return s->stopped || s->table.count(name); });
  return s->stopped ? 0 : 1;
}

// write the table snapshot to `path`; 1 ok, 0 failed
int pts_server_save(void* h, const char* path) {
  auto* s = static_cast<PSServer*>(h);
  std::unordered_map<std::string, std::string> copy;
  uint64_t ver, rid;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    copy = s->table;
    ver = s->version;
    rid = s->round_id;
  }
  return PSServer::write_snapshot(path, copy, ver, rid) ? 1 : 0;
}

// restore the table (+version/round) from a snapshot; 1 ok, 0 failed
int pts_server_load(void* h, const char* path) {
  auto* s = static_cast<PSServer*>(h);
  return s->load_snapshot(path) ? 1 : 0;
}

void pts_server_stop(void* h) {
  auto* s = static_cast<PSServer*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->stopped = true;
    s->cv.notify_all();
    // unblock conn threads parked in read()
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  for (auto& t : s->conn_threads)
    if (t.joinable()) t.join();
  delete s;
}

// ---------------------------------------------------------------------- //
// client                                                                 //
// ---------------------------------------------------------------------- //

void* pts_connect(const char* host, int port, double timeout_s) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) return nullptr;
  // retry until the server comes up (reference grpc_client retry semantics)
  int tries = static_cast<int>(timeout_s / 0.05) + 1;
  for (int i = 0; i < tries; ++i) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto* c = new PSClient();
      c->fd = fd;
      return c;
    }
    ::close(fd);
    ::usleep(50000);
  }
  return nullptr;
}

// generic request; returns status (0 ok, 1 error, -1 io failure).  For
// kGetParam the payload lands in *out (caller frees via ptq_free), length in
// *olen.
int pts_request(void* h, int cmd, const char* name, uint64_t round,
                const char* data, int64_t dlen, char** out, int64_t* olen) {
  auto* c = static_cast<PSClient*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t cmd8 = static_cast<uint8_t>(cmd);
  uint16_t nlen = static_cast<uint16_t>(::strlen(name));
  uint64_t dl = static_cast<uint64_t>(dlen < 0 ? 0 : dlen);
  if (!write_n(c->fd, &cmd8, 1) || !write_n(c->fd, &nlen, 2) ||
      !write_n(c->fd, name, nlen) || !write_n(c->fd, &round, 8) ||
      !write_n(c->fd, &dl, 8) || (dl && !write_n(c->fd, data, dl)))
    return -1;
  uint8_t status;
  uint64_t rlen;
  if (!read_n(c->fd, &status, 1) || !read_n(c->fd, &rlen, 8)) return -1;
  if (rlen > kMaxBlob) return -1;
  std::string payload(rlen, '\0');
  if (rlen && !read_n(c->fd, &payload[0], rlen)) return -1;
  if (out) {
    *out = dup_blob(payload);
    if (olen) *olen = static_cast<int64_t>(rlen);
  }
  return status;
}

void pts_client_close(void* h) {
  auto* c = static_cast<PSClient*>(h);
  ::close(c->fd);
  delete c;
}

}  // extern "C"
