// C ABI of the native runtimes (data_runtime.cc + ps_runtime.cc), shared
// by the implementations and native_test.cc so a signature change is a
// compile error everywhere instead of silent ABI drift.  ctypes binds the
// same surface from paddle_tpu/native/__init__.py.
#pragma once

#include <cstdint>

// Parameter-server wire commands (one byte on the wire; see the frame
// format documented at the top of ps_runtime.cc).
enum PtsCmd : uint8_t {
  kSendGrad = 1,
  kGetParam = 2,
  kSendBarrier = 3,
  kFetchBarrier = 4,
  kSendParam = 5,
  kStop = 6,
  // sparse/distributed-embedding row fetch (reference
  // operators/distributed/parameter_prefetch.cc): request.round packs
  // (header_offset << 32) | row_width_bytes, request.data is an i64 id
  // array; the response is the concatenated rows from the table blob.
  kLookupRows = 7,
  // server-side shard snapshot (reference CheckpointNotify RPC,
  // operators/distributed/send_recv.proto.in:30): request.name is the
  // path the server writes its table snapshot to.
  kCheckpointNotify = 8,
  // --- elastic membership (no reference analog; SURVEY §5 gap) --------- //
  // All three carry the client's stable uid in `name` and answer with the
  // 40-byte membership blob: u64 epoch | u64 round_id | u64 version |
  // u64 active_count | u64 index (~0ull when the uid is pending/absent).
  // kLease renews the sender's lease (heartbeat) and doubles as the
  // membership query; kJoin registers a PENDING member (activated at the
  // next round boundary, or immediately while the job is still idle at
  // round 0); kLeave queues a graceful departure applied at the next
  // round boundary — the leaver participates in rounds until it applies.
  kLease = 9,
  kJoin = 10,
  kLeave = 11,
  // Quorum-committed epoch record (cross-shard data-authority agreement;
  // docs/DISTRIBUTED.md §6 "Preemption and recovery").  request.data is
  // either empty (QUERY) or the 24-byte record u64 epoch | u64 round |
  // u64 position (PROPOSAL; accepted iff its round is >= the stored
  // record's round — commits are monotone in round).  The response is
  // always the server's current 24-byte committed record.  Trainers
  // propose to EVERY shard after each completed round, so the record a
  // majority of shards holds survives the loss of any one shard —
  // including the old shard-0 membership authority — and a relaunched
  // shard reconciles its snapshot against the quorum record instead of
  // trusting its own file (pts_server_reconcile_committed).
  kCommitEpoch = 12,
};

// Response status codes: 0 ok, 1 error/stopped, 2 liveness-deadline
// timeout (retryable: barriers rewait, GET_PARAM re-sends).  A status-2
// barrier response carries an 8-byte payload: the EFFECTIVE round the
// arrival waits on, which the client echoes back in its rewait.

// Barrier frames carry the trainer's completed-round count in `round`
// and its stable client uid in `name` (arrivals are identity-deduped;
// empty name skips the dedup); this high bit marks a REWAIT — the retry
// of a timed-out barrier wait, which must not re-count the arrival.
constexpr uint64_t kPtsRewaitBit = 1ull << 63;

extern "C" {
// --- shared ---------------------------------------------------------- //
void ptq_free(char* p);

// --- RecordIO -------------------------------------------------------- //
void* ptq_recordio_writer_open(const char* path, int compressor);
int ptq_recordio_writer_write(void* handle, const char* data, int64_t len);
int ptq_recordio_writer_close(void* handle);
void* ptq_recordio_scanner_open(const char* path);
// returns record length (>=0), -1 on EOF, -2 on corruption; *out is
// scanner-owned (valid until the next call) — do NOT free
int64_t ptq_recordio_scanner_next(void* handle, char** out);
void ptq_recordio_scanner_close(void* handle);

// --- blocking queue --------------------------------------------------- //
void* ptq_queue_new(int64_t capacity);
// 0 ok, 1 timeout, 2 closed
int ptq_queue_push(void* handle, const char* data, int64_t len,
                   double timeout_s);
// >=0 length (caller frees *out via ptq_free), -1 timeout, -2 closed+empty
int64_t ptq_queue_pop(void* handle, char** out, double timeout_s);
int64_t ptq_queue_size(void* handle);
int64_t ptq_queue_waiters(void* handle);
void ptq_queue_close(void* handle);
void ptq_queue_free(void* handle);

// --- MultiSlot feed --------------------------------------------------- //
// n_threads parser workers claim files from a shared index (file-level
// parallelism, one shared output queue); clamped to [1, nfiles]
void* ptq_feed_new(const char** files, int nfiles, const char* slots_desc,
                   int batch_size, int64_t queue_capacity, int n_threads);
int64_t ptq_feed_next(void* handle, char** out);
int64_t ptq_feed_error(void* handle, char** out);
void ptq_feed_free(void* handle);

// --- native inference runtime (infer_runtime.cc) ----------------------- //
// Reference analog: inference/api/paddle_inference_api.h
// CreatePaddlePredictor — load __model__ (ProgramDesc protobuf) + params
// (per-var LoDTensor files, or one combined file when params_file != NULL)
// and run on CPU with no Python/JAX dependency.
void* pti_create(const char* model_dir, const char* params_file);
const char* pti_error(void* handle);
int pti_num_inputs(void* handle);
const char* pti_input_name(void* handle, int i);
int pti_num_outputs(void* handle);
const char* pti_output_name(void* handle, int i);
// dtype: 0 = float32, 1 = int64
int pti_set_input(void* handle, const char* name, const void* data,
                  const int64_t* dims, int ndims, int dtype);
int pti_run(void* handle);
int64_t pti_get_output(void* handle, const char* name, const void** data,
                       const int64_t** dims, int* ndims, int* dtype);
void pti_free(void* handle);

// --- parameter-server transport --------------------------------------- //
void* pts_server_start(int port, int n_trainers);
int pts_server_port(void* h);
// liveness deadline for barrier / versioned-get waits; 0 = wait forever
void pts_server_set_barrier_timeout_ms(void* h, int ms);
// elastic membership: barrier arrival counts come from the live member
// set (kJoin/kLeave/lease expiry) instead of the fixed n_trainers.
// lease_timeout_ms is the heartbeat deadline — an active member with no
// lease-renewing frame for that long is evicted at the next wait
// predicate evaluation (0 = members never expire).
void pts_server_enable_elastic(void* h, int lease_timeout_ms);
// counters: 0 send-barrier timeouts, 1 fetch-barrier timeouts,
// 2 get-param timeouts, 3 completed rounds, 4 published version,
// 5 membership epoch, 6 active members, 7 joins, 8 leaves, 9 evictions,
// 10 committed epoch, 11 committed round, 12 committed position
int64_t pts_server_stat(void* h, int which);
// reconcile a relaunched shard against the QUORUM committed record
// (gathered by the driver from the surviving peers' kCommitEpoch
// queries): when the quorum round is AHEAD of this shard's restored
// round counter, fast-forward round_id / send_ack_round (and the
// committed record) so the survivors' in-flight barrier arithmetic
// lines up — without this, a shard restored from a pre-kill snapshot
// parks the whole job behind a round count only it believes in.
// Returns 1 when the counters moved, 0 when the snapshot was already
// at (or ahead of) the quorum.
int pts_server_reconcile_committed(void* h, uint64_t epoch, uint64_t round,
                                   uint64_t position);
// drain up to max_records span-journal entries (4 u64 each: cmd, span id,
// wall-clock start us, handling duration us) into out; returns the count.
// The journal records every served frame whose span field was nonzero —
// the server half of client↔server RPC attribution in merged traces.
int64_t pts_server_drain_spans(void* h, uint64_t* out, int64_t max_records);
int pts_server_wait_round(void* h);
void pts_server_release_send(void* h);
int64_t pts_server_grad_count(void* h);
int64_t pts_server_grad_at(void* h, int64_t i, char** name_out,
                           char** data_out);
int64_t pts_server_grad_name_len(void* h, int64_t i);
// payload length (caller frees name/data via ptq_free; name is
// NUL-terminated), -1 timeout, -2 stopped-and-drained
int64_t pts_server_pop_grad(void* h, int timeout_ms, char** name_out,
                            char** data_out);
void pts_server_publish(void* h, const char* name, const char* data,
                        int64_t len);
void pts_server_bump_version(void* h);
int pts_server_end_round(void* h);
int64_t pts_server_table_get(void* h, const char* name, char** out);
int pts_server_wait_table(void* h, const char* name);
// shard snapshot to/from `path` (temp+rename inside save); 1 ok, 0 failed
int pts_server_save(void* h, const char* path);
int pts_server_load(void* h, const char* path);
void pts_server_stop(void* h);
void* pts_connect(const char* host, int port, double timeout_s);
// status 0 ok / 1 error / 2 server deadline (retryable) / -1 io failure;
// kGetParam payload lands in *out (caller frees via ptq_free).  `span` is
// the caller's span id for this attempt (0 = untraced); the server
// journals it against the handled command for post-mortem attribution.
int pts_request(void* h, int cmd, const char* name, uint64_t round,
                uint64_t span, const char* data, int64_t dlen, char** out,
                int64_t* olen);
void pts_client_close(void* h);
}  // extern "C"
